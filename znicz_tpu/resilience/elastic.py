"""Elastic multi-process training — the fleet supervisor (ISSUE 9).

``run_supervised`` (supervisor.py) restarts a crashed trainer
*in-process*; this module lifts the same contract across real process
boundaries, the VELES master–slave topology reborn as
coordinator-supervised SPMD peers (PAPER.md §1; TensorFlow's
checkpoint-based recovery, arXiv 1605.08695, is the fault-tolerance
blueprint; Awan et al. 2018 motivates treating process death as a
first-class, measured event).

``run_elastic(worker_argv, snap_dir)`` spawns N worker processes — each
one the ordinary ``python -m znicz_tpu <workflow.py> ...`` CLI, joined
into one job via ``launcher.multihost`` when ``spmd=True`` — and
supervises them:

- **exit-code watch + heartbeats**: workers touch a per-rank heartbeat
  file (``start_heartbeat``, wired by ``__main__``) carrying a
  timestamp and the workflow's ``signals_dispatched`` progress counter;
  the fleet declares a worker dead on an unexpected exit, wedged on a
  stale heartbeat, and hung on a flat progress counter;
- **kill-and-resume**: on any death the remainder is SIGTERM'd (the
  launcher's snapshot-then-exit handler gives them one epoch boundary
  to publish), a flight-recorder artifact is dumped, the newest VALID
  snapshot is picked via ``find_latest_valid_snapshot``, and the fleet
  relaunches — **optionally at a different world size**
  (``world_sizes=[2, 1]`` = start at 2, resume at 1): elastic re-mesh,
  real across processes;
- **budget + backoff** ride the existing :class:`SupervisorPolicy`.

Worker environment contract (what a worker process finds):

=============================  =========================================
``ZNICZ_TPU_ELASTIC_RANK``     this worker's rank (snapshot election:
                               rank 0 writes, every other rank verifies
                               — ``snapshotter.process_rank_world``)
``ZNICZ_TPU_ELASTIC_WORLD``    the round's worker count
``ZNICZ_TPU_SNAP_DIR``         the fleet's snapshot directory (workflow
                               files point their snapshotter here)
``ZNICZ_TPU_HEARTBEAT``        heartbeat file path (``__main__`` starts
                               the beat thread when set)
``ZNICZ_TPU_FAULT_PLAN``       serialized :class:`FaultPlan` — round-0
                               workers only, so a seeded kill drill
                               does not re-fire after every resume
``ZNICZ_TPU_METRICS_EXPORT``   rank-tagged registry snapshot file the
                               worker atomically rewrites (``__main__``
                               starts the exporter when set) — the
                               supervisor's fleet aggregator ingests
                               these beside the heartbeats (ISSUE 11)
=============================  =========================================

Fleet telemetry (ISSUE 11): the supervisor hosts an
``observe/federation.py`` :class:`FleetAggregator` over the round's
worker snapshot files — every flight artifact it dumps embeds each
worker's last registry snapshot (the ``planes.fleet`` key), and
``fleet_port=N`` / ``--fleet-port N`` serves the merged
``/fleet/metrics[.prom]`` + ``/fleet/status.json`` view while the
fleet runs.

Determinism contract (pinned by tests/test_elastic.py): the workers'
snapshot resume is the snapshotter's bit-exact resume, so a fleet killed
at any point and relaunched at ANY world size reproduces the
uninterrupted run's metric history exactly.

CLI: ``python -m znicz_tpu elastic --workers N --snap-dir D
<workflow.py> [worker args ...]``.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Mapping, Optional, Sequence

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import federation as _federation
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import probe as _probe
from znicz_tpu.resilience import faults
from znicz_tpu.resilience.supervisor import (SupervisorExhausted,
                                             SupervisorPolicy,
                                             find_latest_valid_snapshot)

#: exit code a SIGTERM'd worker uses for "terminated as asked" (the
#: launcher's snapshot-then-exit handler).  During a round TEARDOWN this
#: is the expected graceful outcome; a worker exiting 143 on its own
#: (an operator or cgroup SIGTERM the fleet did not send) still counts
#: as a death, because the round can no longer complete either way —
#: the distinction 143 buys is "clean snapshot published" vs "died
#: mid-write", not "ignore me"
TERMINATED_EXIT = 143

HEARTBEAT_ENV = "ZNICZ_TPU_HEARTBEAT"
HEARTBEAT_INTERVAL_ENV = "ZNICZ_TPU_HEARTBEAT_INTERVAL"
RANK_ENV = "ZNICZ_TPU_ELASTIC_RANK"
WORLD_ENV = "ZNICZ_TPU_ELASTIC_WORLD"
SNAP_DIR_ENV = "ZNICZ_TPU_SNAP_DIR"


class ElasticExhausted(SupervisorExhausted):
    """Fleet restart budget spent without a completed run."""


# -- worker side -------------------------------------------------------------

def start_heartbeat(path: str, interval: float = 0.25,
                    progress=None) -> threading.Thread:
    """Worker-side beat: a daemon thread rewrites ``path`` with
    ``"<unix-ts> <progress>"`` every ``interval`` seconds.  ``progress``
    is a callable returning the workflow's ``signals_dispatched`` (-1
    until one exists) — mtime proves the PROCESS is alive, the counter
    proves the STEP LOOP is, which is how the fleet tells a wedged
    process from a hung step.  Write failures are swallowed: a full
    disk must not kill the trainer, only its liveness signal."""
    progress = progress or (lambda: -1)

    def beat() -> None:
        while True:
            try:
                value = int(progress())
            except Exception:  # noqa: BLE001 — a torn-down workflow
                value = -1
            try:
                with open(path, "w") as f:
                    f.write(f"{time.time():.3f} {value}\n")
            except OSError:
                pass
            time.sleep(interval)

    t = threading.Thread(target=beat, name="znicz-heartbeat", daemon=True)
    t.start()
    return t


def _read_heartbeat(path: str):
    """-> (mtime, progress) or None while the file does not parse."""
    try:
        with open(path) as f:
            ts_text, _, progress_text = f.read().strip().partition(" ")
        return float(ts_text), int(progress_text)
    except (OSError, ValueError):
        return None


# -- supervisor side ---------------------------------------------------------

class WorkerProcess:
    """One spawned worker process + its log pump — the fleet's unit of
    supervision.  ISSUE 13 makes it the SHARED spawn/retire primitive:
    the elastic training supervisor and the serving fleet's worker pool
    (``znicz_tpu/fleet/workers.py``) both manage these, through
    :func:`spawn_worker` / :func:`teardown_workers`, so process
    lifecycle (log pumping, SIGTERM-grace-SIGKILL reaping, tail capture
    for post-mortems) lives once."""

    def __init__(self, rank: int, proc: subprocess.Popen,
                 heartbeat_path: str, log_path: str,
                 log_tree: str = "elastic") -> None:
        self.rank = rank
        self.proc = proc
        self.heartbeat_path = heartbeat_path
        self.log_path = log_path
        self.log_tree = log_tree
        self.tail: collections.deque = collections.deque(maxlen=40)
        self.started = time.monotonic()
        self.last_progress = -1
        self.last_progress_change = self.started
        self.killed = False          # teardown-initiated, not a death
        self._pump = threading.Thread(target=self._pump_output,
                                      name=f"znicz-{log_tree}-w{rank}-log",
                                      daemon=True)
        self._pump.start()

    def _pump_output(self) -> None:
        """Worker stdout/stderr -> per-worker log file + the supervisor's
        logging tree under ``znicz_tpu.<tree>.w<rank>`` (a configured
        JSONL sink therefore interleaves every worker, rank-prefixed,
        on one machine-readable stream)."""
        log = logging.getLogger(f"znicz_tpu.{self.log_tree}.w{self.rank}")
        try:
            with open(self.log_path, "a") as sink:
                for line in self.proc.stdout:
                    line = line.rstrip("\n")
                    self.tail.append(line)
                    sink.write(line + "\n")
                    log.debug("%s", line)
        except (OSError, ValueError):
            pass                     # stream closed under us at teardown

    def update_progress(self, now: float) -> None:
        beat = _read_heartbeat(self.heartbeat_path)
        if beat is None:
            return
        _, progress = beat
        if progress != self.last_progress:
            self.last_progress = progress
            self.last_progress_change = now

    def heartbeat_age(self) -> Optional[float]:
        try:
            return time.time() - os.path.getmtime(self.heartbeat_path)
        except OSError:
            return None


#: historical private name (pre-ISSUE-13), kept for in-repo references
_Worker = WorkerProcess


class GoodputLedger:
    """Supervisor wall-time attribution (ISSUE 20): every second of the
    fleet's life is charged to exactly one category per rank —

    ==============  ====================================================
    ``productive``  round run windows that fed a surviving snapshot
                    (completed rounds fully; failed rounds up to the
                    newest valid snapshot's mtime)
    ``lost``        a failed round's remainder past that snapshot — the
                    compute a resume re-does
    ``snapshot``    teardown grace windows (SIGTERM is the launcher's
                    snapshot-then-exit)
    ``idle``        spawn windows, flight dumps, restart backoff — the
                    supervisor's own overhead
    ==============  ====================================================

    A monotonic cursor guarantees the categories tile the wall: each
    :meth:`advance` charges exactly cursor->now, so per rank the four
    sums reconstruct the supervisor's wall time (pinned by
    tests/test_elastic.py).  Every segment is donated to the
    ``znicz_goodput_*`` probe families, and :meth:`as_dict` doubles as
    the flight recorder's ``goodput`` plane, so a restart artifact
    carries the ledger of the round it post-mortems."""

    CATEGORIES = ("productive", "lost", "snapshot", "idle")

    def __init__(self) -> None:
        self.started = time.monotonic()
        self._cursor = self.started
        self._ranks: tuple = (0,)
        self.per_rank: dict = {}

    def _charge(self, category: str, dt: float) -> None:
        if dt <= 0.0:
            return
        for rank in self._ranks:
            cats = self.per_rank.setdefault(
                str(rank), dict.fromkeys(self.CATEGORIES, 0.0))
            cats[category] += dt
            _probe.goodput_note(category, rank, dt)

    def advance(self, category: str, ranks=None,
                until: Optional[float] = None) -> float:
        """Charge cursor->``until`` (default: now) as ``category`` to
        ``ranks`` (default: the previous segment's ranks)."""
        if ranks is not None:
            self._ranks = tuple(ranks) or (0,)
        now = time.monotonic() if until is None else until
        dt = now - self._cursor
        self._cursor = max(self._cursor, now)
        self._charge(category, dt)
        return dt

    def advance_split(self, boundary_s: float, before: str, after: str,
                      ranks=None) -> float:
        """Charge cursor->now as two categories: the first
        ``boundary_s`` seconds as ``before``, the remainder as
        ``after`` — the failed-round split (productive up to the
        surviving snapshot, lost past it).  A stale snapshot from an
        earlier round arrives as a negative/zero boundary and the whole
        window lands in ``after``."""
        if ranks is not None:
            self._ranks = tuple(ranks) or (0,)
        now = time.monotonic()
        dt = max(0.0, now - self._cursor)
        self._cursor = max(self._cursor, now)
        head = min(max(0.0, boundary_s), dt)
        self._charge(before, head)
        self._charge(after, dt - head)
        return dt

    def totals(self) -> dict:
        out = dict.fromkeys(self.CATEGORIES, 0.0)
        for cats in self.per_rank.values():
            for cat, seconds in cats.items():
                out[cat] += seconds
        return out

    def as_dict(self) -> dict:
        totals = self.totals()
        spent = sum(totals.values())
        return {"wall_s": time.monotonic() - self.started,
                "per_rank": {r: dict(c)
                             for r, c in sorted(self.per_rank.items())},
                "totals": totals,
                "ratio": (totals["productive"] / spent) if spent > 0.0
                else 0.0}


def spawn_worker(argv: Sequence[str], *, rank: int, log_path: str,
                 env: Optional[Mapping[str, str]] = None,
                 heartbeat_path: str = "",
                 log_tree: str = "elastic") -> WorkerProcess:
    """Spawn one supervised worker process (the shared spawn hook):
    stdout+stderr piped into the :class:`WorkerProcess` log pump, text
    mode, line buffered.  ``heartbeat_path`` may be "" for workers whose
    liveness is probed another way (the serving fleet probes HTTP
    ``/livez`` instead of heartbeat files)."""
    proc = subprocess.Popen(
        list(argv), env=dict(env) if env is not None else None,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        bufsize=1)
    return WorkerProcess(rank, proc, heartbeat_path, log_path,
                         log_tree=log_tree)


class ElasticReport:
    """What happened across the fleet's rounds."""

    def __init__(self) -> None:
        self.completed = False
        self.rounds: list[dict] = []
        self.restarts = 0
        self.worker_deaths: list[dict] = []
        self.resumed_from: list[str] = []
        self.rejected_snapshots: list[str] = []
        self.hang_events = 0
        self.flights: list[str] = []
        self.world_size = 0          # final round's world size
        self.goodput: dict = {}      # GoodputLedger.as_dict() at exit

    def as_dict(self) -> dict:
        return {"completed": self.completed, "rounds": self.rounds,
                "restarts": self.restarts,
                "worker_deaths": list(self.worker_deaths),
                "resumed_from": list(self.resumed_from),
                "rejected_snapshots": list(self.rejected_snapshots),
                "hang_events": self.hang_events,
                "flights": list(self.flights),
                "world_size": self.world_size,
                "goodput": dict(self.goodput)}


def _free_port(host: str) -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def run_elastic(worker_argv: Sequence[str], snap_dir: str, *,
                workers: int = 2,
                world_sizes: Optional[Sequence[int]] = None,
                policy: Optional[SupervisorPolicy] = None,
                prefix: Optional[str] = None,
                run_dir: Optional[str] = None,
                spmd: bool = True,
                coordinator_host: str = "127.0.0.1",
                env: Optional[Mapping[str, str]] = None,
                fault_plans: Optional[Mapping[int, object]] = None,
                poll_s: float = 0.05,
                term_grace: float = 5.0,
                heartbeat_interval: float = 0.25,
                heartbeat_timeout: Optional[float] = None,
                progress_timeout: Optional[float] = None,
                boot_timeout: Optional[float] = None,
                round_timeout: Optional[float] = None,
                fleet_port: Optional[int] = None,
                metrics_interval: float = 1.0,
                stop_event: Optional[threading.Event] = None
                ) -> ElasticReport:
    """Supervise an elastic worker fleet to completion.

    ``worker_argv`` is the CLI tail after ``python -m znicz_tpu`` (the
    workflow file, configs, flags); the fleet appends per-worker
    ``--coordinator/--num-processes/--process-id`` (when ``spmd``) and
    ``-w <snapshot>`` on resumed rounds.  ``world_sizes`` is the
    per-round worker count (last entry repeats; default: ``[workers]``).
    ``fault_plans`` maps rank -> :class:`FaultPlan` (or a pre-serialized
    string) injected into ROUND 0 workers' env only — a seeded kill
    drill fires once and resumed rounds run clean (a plan inherited
    from the supervisor's own env is deliberately scrubbed for the same
    reason).  Optional watch layers, each in seconds: ``heartbeat_
    timeout`` (stale heartbeat file = wedged process), ``progress_
    timeout`` (flat step counter after the first step = hung step —
    deliberately blind before step 1, where a long first compile is
    indistinguishable from a stall), ``boot_timeout`` (no first step
    within this long of launch = hung boot; size it above worst
    jax-import + compile time), ``round_timeout`` (whole-round
    backstop).  ``policy`` supplies the restart budget + backoff.
    ``fleet_port`` serves the fleet aggregator's merged telemetry
    (``/fleet/metrics[.prom]``, ``/fleet/status.json``) while the fleet
    runs (None = the aggregator still ingests worker snapshots so
    flight artifacts embed them, just no listener);
    ``metrics_interval`` is the workers' snapshot-export cadence.
    ``stop_event`` is a cooperative shutdown hook (ISSUE 14: the learn
    CLI supervises its trainer on a thread and must be able to retire
    it on SIGTERM): once set, the in-flight round is torn down
    gracefully (SIGTERM = snapshot-then-exit) and the report returns
    with a ``"stopped"`` round instead of a restart.

    Returns an :class:`ElasticReport`; raises :class:`ElasticExhausted`
    when the budget is spent.
    """
    policy = policy or SupervisorPolicy()
    log = Logger()
    report = ElasticReport()
    schedule = [int(w) for w in (world_sizes or [workers])]
    if any(w < 1 for w in schedule):
        raise ValueError(f"world sizes must be >= 1, got {schedule}")
    run_dir = run_dir or os.path.join(snap_dir, "elastic")
    os.makedirs(run_dir, exist_ok=True)
    os.makedirs(snap_dir, exist_ok=True)
    base_env = dict(env if env is not None else os.environ)
    # a plan in the SUPERVISOR'S env must not leak into every worker of
    # every round: hit counters reset with each fresh process, so an
    # inherited seeded kill would re-fire after every resume and the
    # fleet could never complete — plans reach workers only through
    # ``fault_plans`` (round 0, per rank)
    base_env.pop(faults.PLAN_ENV_VAR, None)
    # the fleet telemetry master view (ISSUE 11): sources re-registered
    # per round, embedded into every flight dump via the "fleet" plane;
    # staleness bound sized to the export cadence so a SIGKILL'd
    # worker's series drop out instead of reading live forever
    aggregator = _federation.FleetAggregator(
        stale_s=max(10.0 * metrics_interval, 5.0))
    # the goodput ledger (ISSUE 20): every supervisor second lands in
    # exactly one znicz_goodput_* family per rank.  Children pre-touched
    # for the whole schedule up front (the PR 11 delta-rule lesson: a
    # fleet rule over a series that first appears mid-incident reads as
    # a rate spike or never trips at all)
    ledger = GoodputLedger()
    _probe.goodput_pretouch(range(max(schedule)))
    goodput_plane = ledger.as_dict
    _flight.register_plane("goodput", goodput_plane)
    current: list = []       # the in-flight round's workers, shared with
    try:                     # the round loop so cleanup sees them all
        if fleet_port is not None:
            # inside the try: a bind failure must still run close(),
            # which unregisters the "fleet" flight plane this
            # aggregator registered at construction
            aggregator.serve(port=fleet_port)
        return _supervise_rounds(
            worker_argv, snap_dir, schedule, policy, prefix, run_dir,
            spmd, coordinator_host, base_env, fault_plans, poll_s,
            term_grace, heartbeat_interval, heartbeat_timeout,
            progress_timeout, boot_timeout, round_timeout, report, log,
            current, aggregator, metrics_interval, ledger, stop_event)
    finally:
        # ANY exit — completion, ElasticExhausted, KeyboardInterrupt,
        # a spawn OSError halfway through a round — must not orphan
        # live workers (they would keep training and writing snapshots
        # a later invocation silently resumes from)
        leaked = [w for w in current if w.proc.poll() is None]
        if leaked:
            log.warning(f"elastic: reaping {len(leaked)} live worker(s) "
                        f"on supervisor exit")
            # an abnormal exit mid-round: the round ran until now, the
            # reap is a snapshot window (SIGTERM = snapshot-then-exit)
            ledger.advance("productive")
            teardown_workers(leaked, term_grace, log)
            ledger.advance("snapshot")
        # flush the tail so the categories tile the supervisor's wall
        ledger.advance("idle")
        report.goodput = ledger.as_dict()
        _flight.unregister_plane("goodput", goodput_plane)
        aggregator.close()
        _probe.elastic_world_size(0)


def _supervise_rounds(worker_argv, snap_dir, schedule, policy, prefix,
                      run_dir, spmd, coordinator_host, base_env,
                      fault_plans, poll_s, term_grace,
                      heartbeat_interval, heartbeat_timeout,
                      progress_timeout, boot_timeout, round_timeout,
                      report, log, current, aggregator,
                      metrics_interval, ledger,
                      stop_event=None) -> ElasticReport:
    """:func:`run_elastic`'s round loop, split out so the caller's
    try/finally can guarantee teardown of ``current`` on ANY exit."""
    round_no = 0
    while True:
        if stop_event is not None and stop_event.is_set():
            # stop landed between rounds (e.g. during backoff): do not
            # spawn a round just to tear it down
            report.rounds.append({"round": round_no, "world": 0,
                                  "outcome": "stopped"})
            return report
        world = schedule[min(round_no, len(schedule) - 1)]
        resume = find_latest_valid_snapshot(
            snap_dir, prefix, rejected=report.rejected_snapshots)
        if resume is not None:
            report.resumed_from.append(resume)
            _probe.elastic_event("resume", round=round_no,
                                 snapshot=os.path.basename(resume))
        coordinator = None
        if spmd:
            coordinator = (f"{coordinator_host}:"
                           f"{_free_port(coordinator_host)}")
        current.clear()
        fleet: list = current          # shared with the caller's finally
        aggregator.clear_sources()     # this round's files replace last
        for rank in range(world):
            argv = [sys.executable, "-m", "znicz_tpu", *worker_argv]
            if spmd:
                argv += ["--coordinator", coordinator,
                         "--num-processes", str(world),
                         "--process-id", str(rank)]
            if resume is not None:
                argv += ["-w", resume]
            hb_path = os.path.join(run_dir, f"hb_r{round_no}_w{rank}")
            mx_path = os.path.join(run_dir,
                                   f"metrics_r{round_no}_w{rank}.json")
            worker_env = dict(base_env)
            worker_env[RANK_ENV] = str(rank)
            worker_env[WORLD_ENV] = str(world)
            worker_env[SNAP_DIR_ENV] = str(snap_dir)
            worker_env[HEARTBEAT_ENV] = hb_path
            worker_env[HEARTBEAT_INTERVAL_ENV] = repr(heartbeat_interval)
            worker_env[_federation.METRICS_EXPORT_ENV] = mx_path
            worker_env[_federation.METRICS_EXPORT_INTERVAL_ENV] = \
                repr(metrics_interval)
            aggregator.add_file_source(rank, mx_path)
            if round_no == 0 and fault_plans and rank in fault_plans:
                plan = fault_plans[rank]
                worker_env[faults.PLAN_ENV_VAR] = (
                    plan if isinstance(plan, str) else plan.to_env())
            fleet.append(spawn_worker(
                argv, rank=rank, env=worker_env, heartbeat_path=hb_path,
                log_path=os.path.join(run_dir,
                                      f"worker_r{round_no}_w{rank}.log")))
        _probe.elastic_world_size(world)
        log.info(f"elastic: round {round_no} up — {world} worker(s)"
                 + (f", resumed from {os.path.basename(resume)}"
                    if resume else ", cold start")
                 + (f", coordinator {coordinator}" if coordinator else ""))
        # everything since the last stamp — the spawn loop plus the
        # previous round's flight dump and restart backoff — is the
        # supervisor's own overhead, charged to this round's ranks
        ledger.advance("idle", ranks=range(world))
        round_started = time.monotonic()
        round_wall_started = time.time()   # snapshot mtimes are wall time
        deaths: list[dict] = []
        hung: list[dict] = []
        timed_out = False
        while True:
            now = time.monotonic()
            if stop_event is not None and stop_event.is_set():
                # cooperative shutdown (ISSUE 14): SIGTERM the round —
                # the launcher handler turns that into one final
                # snapshot — and return without a restart
                log.info("elastic: stop requested; retiring the round")
                ledger.advance("productive")   # the round ran until now
                teardown_workers(fleet, term_grace, log)
                ledger.advance("snapshot")     # SIGTERM grace window
                report.rounds.append({"round": round_no, "world": world,
                                      "outcome": "stopped"})
                report.world_size = world
                return report
            alive = [w for w in fleet if w.proc.poll() is None]
            if fleet[0].proc.poll() == 0:
                # rank 0 — the snapshot writer and history owner —
                # exited 0: the job's output is complete.  Check BEFORE
                # the deaths scan: when the writer finishes first, its
                # exit tears the jax.distributed coordinator down, and
                # a slower replica's resulting abort must read as a
                # redundant straggler, not as a death that fails a
                # finished round.  (A writer that exits NONZERO still
                # lands in the deaths scan below.)
                # replicas finishing moments behind the writer (the
                # election self-pacing keeps them within one poll) get
                # one grace window to exit on their own before the reap
                grace_end = time.monotonic() + term_grace
                while time.monotonic() < grace_end and \
                        any(w.proc.poll() is None for w in fleet):
                    time.sleep(poll_s)
                stragglers = [w.rank for w in fleet
                              if w.proc.poll() != 0]
                if stragglers:
                    log.info(f"elastic: rank 0 completed; reaping "
                             f"redundant straggler(s) {stragglers}")
                    teardown_workers([w for w in fleet if w.rank in stragglers],
                              term_grace, log)
                report.rounds.append({"round": round_no, "world": world,
                                      "outcome": "completed",
                                      "stragglers": stragglers})
                # the whole round window — including the straggler
                # grace — is productive: the job's output is complete
                ledger.advance("productive")
                report.completed = True
                report.world_size = world   # gauge zeroed by the caller
                log.info(f"elastic: completed at world size {world} "
                         f"after {report.restarts} restart(s)")
                return report
            deaths = [
                {"rank": w.rank, "code": w.proc.returncode,
                 "cause": "signal" if w.proc.returncode < 0 else "exit",
                 "tail": list(w.tail)[-10:]}
                for w in fleet
                if w.proc.poll() not in (None, 0)]
            if deaths:
                break
            for w in alive:
                w.update_progress(now)
                # wedged BEFORE hung: when the whole interpreter is
                # stuck (native deadlock, GIL held) the heartbeat
                # daemon freezes too, so mtime AND progress both stall
                # — the stale file is the discriminator, and checking
                # flat progress first would misfile every post-step-1
                # wedge as a mere hung step
                age = w.heartbeat_age()
                stale = heartbeat_timeout is not None and (
                    (age is not None and age > heartbeat_timeout) or
                    (age is None and now - w.started > heartbeat_timeout))
                if stale:
                    hung.append({"rank": w.rank, "cause": "wedged",
                                 "heartbeat_age": age})
                elif progress_timeout is not None and \
                        w.last_progress > 0 and \
                        now - w.last_progress_change > progress_timeout:
                    hung.append({"rank": w.rank, "cause": "hung",
                                 "progress": w.last_progress})
                elif boot_timeout is not None and w.last_progress <= 0 \
                        and now - w.started > boot_timeout:
                    # never reached step 1: a hang inside boot/compile,
                    # where the progress watch is deliberately blind
                    hung.append({"rank": w.rank, "cause": "boot",
                                 "progress": w.last_progress})
            if hung:
                break
            if round_timeout is not None and \
                    now - round_started > round_timeout:
                timed_out = True
                break
            time.sleep(poll_s)
        # -- failure round: record, tear down, dump, back off, relaunch --
        for death in deaths:
            report.worker_deaths.append(death)
            _probe.elastic_event("worker_death", cause=death["cause"],
                                 rank=death["rank"], code=death["code"])
            log.warning(f"elastic: worker {death['rank']} died "
                        f"(code {death['code']})")
        for event in hung:
            report.hang_events += 1
            _probe.elastic_event("worker_death", cause=event["cause"],
                                 rank=event["rank"])
            log.warning(f"elastic: worker {event['rank']} "
                        f"{event['cause']} "
                        f"(progress {event.get('progress')})")
        if timed_out:
            log.warning(f"elastic: round {round_no} exceeded "
                        f"{round_timeout}s; restarting")
        # goodput split for the failed round: productive up to the
        # newest snapshot that survives validation (that compute is
        # KEPT — the resume continues from it), lost past it (that
        # compute is re-done).  A snapshot from an earlier round has
        # mtime < round start and the whole window reads as lost.
        saved = find_latest_valid_snapshot(
            snap_dir, prefix, rejected=report.rejected_snapshots)
        saved_s = 0.0
        if saved is not None:
            try:
                saved_s = os.path.getmtime(saved) - round_wall_started
            except OSError:
                saved_s = 0.0
        ledger.advance_split(saved_s, "productive", "lost")
        teardown_workers(fleet, term_grace, log)
        ledger.advance("snapshot")     # SIGTERM grace window
        report.rounds.append({
            "round": round_no, "world": world, "outcome": "failed",
            "deaths": deaths, "hung": hung, "timed_out": timed_out})
        report.restarts += 1          # counts FAILED rounds (supervisor
        exhausted = report.restarts > policy.max_restarts   # semantics)
        if not exhausted:
            # the metric is documented as "the fleet relaunched": the
            # final failed round that only raises must not inflate it
            _probe.elastic_event("restart", round=round_no, world=world)
        if policy.flight_recorder:
            # the fleet-side post-mortem: which workers died, with what
            # codes, their last output lines, plus this process's whole
            # telemetry state — dumped BEFORE the relaunch overwrites
            # it.  One forced scrape first: the artifact's "fleet"
            # plane then embeds each worker's LAST exported registry
            # snapshot (the dead rank's included), ledger-checkable
            # without any live worker
            try:
                aggregator.refresh(force=True)
            except Exception:  # noqa: BLE001 — telemetry must not
                pass           # block the post-mortem
            try:
                report.flights.append(_flight.dump(
                    dir=run_dir,
                    reason="elastic_exhausted" if exhausted
                    else "elastic_restart",
                    extra={"round": round_no, "world": world,
                           "deaths": deaths, "hung": hung,
                           "timed_out": timed_out,
                           "restarts": report.restarts}))
            except Exception as exc:  # noqa: BLE001
                log.warning(f"elastic: flight dump failed: {exc!r}")
        if exhausted:
            raise ElasticExhausted(
                f"elastic fleet gave up after {report.restarts} failed "
                f"rounds ({policy.max_restarts} restart(s) allowed); "
                f"deaths: {report.worker_deaths}, hangs: "
                f"{report.hang_events}")
        policy.sleep(policy.restart_delay(report.restarts))
        round_no += 1


def teardown_workers(fleet: list, term_grace: float, log) -> None:
    """Kill a fleet's survivors (the shared retire hook): SIGTERM (the
    launcher handler turns it into snapshot-then-exit-143; serving
    workers drain and exit 0), bounded grace, then SIGKILL.  Every
    process is reaped.  A worker whose ``killed`` flag is already set
    was signaled by the caller and is NOT re-signaled — the serving
    CLIs restore the default SIGTERM disposition once their drain
    begins, so a second SIGTERM would kill a worker mid-drain (-15)
    and lose the requests it had admitted."""
    for w in fleet:
        if w.proc.poll() is None and not w.killed:
            w.killed = True
            try:
                w.proc.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + term_grace
    for w in fleet:
        while w.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.02)
        if w.killed and w.proc.poll() == TERMINATED_EXIT:
            log.info(f"elastic: worker {w.rank} terminated gracefully "
                     f"(snapshot-then-exit {TERMINATED_EXIT})")
        if w.proc.poll() is None:
            log.warning(f"elastic: worker {w.rank} survived SIGTERM "
                        f"{term_grace}s grace; SIGKILL")
            try:
                w.proc.kill()
            except OSError:
                pass
        try:
            w.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover — SIGKILL'd
            pass


# -- CLI ---------------------------------------------------------------------

def elastic_main(argv) -> int:
    """``python -m znicz_tpu elastic --workers N --snap-dir D
    <workflow.py> [worker args ...]`` — unknown flags pass through to the
    workers verbatim, so everything the plain CLI accepts works here."""
    import argparse

    p = argparse.ArgumentParser(
        prog="znicz_tpu elastic", allow_abbrev=False,
        description="coordinator-supervised elastic worker fleet")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--snap-dir", required=True,
                   help="shared snapshot directory (workers see it as "
                        "$ZNICZ_TPU_SNAP_DIR; rank 0 writes, others "
                        "verify)")
    p.add_argument("--prefix", default=None,
                   help="snapshot filename prefix filter for resume")
    p.add_argument("--run-dir", default=None,
                   help="fleet artifacts: worker logs, heartbeats, "
                        "flight dumps (default: <snap-dir>/elastic)")
    p.add_argument("--world-sizes", default=None, metavar="N,M,...",
                   help="per-round worker counts, e.g. 2,1 = start at "
                        "2, resume at 1 (default: --workers for every "
                        "round)")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--no-spmd", action="store_true",
                   help="do not join workers via jax.distributed "
                        "(independent replicated workers)")
    p.add_argument("--heartbeat-timeout", type=float, default=60.0)
    p.add_argument("--progress-timeout", type=float, default=None,
                   help="declare a worker hung when its step counter is "
                        "flat this long (off by default: size it above "
                        "the worst compile+step time)")
    p.add_argument("--boot-timeout", type=float, default=None,
                   help="declare a worker hung when it reaches no first "
                        "step this long after launch (off by default: "
                        "size it above worst jax-import + compile time)")
    p.add_argument("--round-timeout", type=float, default=None)
    p.add_argument("--term-grace", type=float, default=5.0)
    p.add_argument("--fleet-port", type=int, default=None,
                   help="serve the fleet aggregator's merged telemetry "
                        "(/fleet/metrics[.prom], /fleet/status.json) on "
                        "this port while the fleet runs (0 picks a free "
                        "one; default: no listener — worker snapshots "
                        "still feed flight artifacts)")
    p.add_argument("--fault-plan", action="append", default=[],
                   metavar="RANK=JSON",
                   help="arm a serialized FaultPlan (FaultPlan.to_env "
                        "output) in one ROUND-0 worker's env — the "
                        "seeded chaos drill hook; repeatable.  (A "
                        "ZNICZ_TPU_FAULT_PLAN in the supervisor's own "
                        "env is deliberately NOT inherited: it would "
                        "re-fire after every resume.)")
    args, worker_argv = p.parse_known_args(argv)
    if not worker_argv:
        p.error("no worker command given (expected a workflow .py and "
                "its flags after the elastic options)")
    fault_plans = {}
    for spec in args.fault_plan:
        rank_text, sep, plan_text = spec.partition("=")
        if not sep or not rank_text.isdigit():
            p.error(f"--fault-plan wants RANK=JSON, got {spec!r}")
        try:
            faults.FaultPlan.from_env(plan_text)  # validate loudly now
        except (ValueError, KeyError, TypeError) as exc:
            p.error(f"--fault-plan {rank_text}: bad plan JSON "
                    f"({exc!r})")
        fault_plans[int(rank_text)] = plan_text
    try:
        report = run_elastic(
            worker_argv, args.snap_dir, workers=args.workers,
            world_sizes=[int(w) for w in args.world_sizes.split(",")]
            if args.world_sizes else None,
            policy=SupervisorPolicy(max_restarts=args.max_restarts),
            prefix=args.prefix, run_dir=args.run_dir,
            spmd=not args.no_spmd, term_grace=args.term_grace,
            fault_plans=fault_plans,
            heartbeat_timeout=args.heartbeat_timeout,
            progress_timeout=args.progress_timeout,
            boot_timeout=args.boot_timeout,
            round_timeout=args.round_timeout,
            fleet_port=args.fleet_port)
    except ElasticExhausted as exc:
        print(f"elastic: {exc}", file=sys.stderr)
        return 1
    print(json.dumps(report.as_dict()))
    return 0
