"""Plotting units — rebuild of veles/plotter.py + veles/plotting_units.py
(AccumulatingPlotter, MatrixPlotter, ImagePlotter, Histogram) and the
graphics server.

The reference shipped plot state over a ZMQ PUB socket to a separate
matplotlib process (SURVEY.md §3.3 Graphics row).  The TPU-VM rebuild
renders in-process with the Agg backend straight to PNG files under
``root.common.dirs.plots`` — same unit-level hook points (gated on
``decision.epoch_ended``), no display dependency; ``stealth`` mode (CLI
-s) skips linking them entirely.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit

root.common.dirs.plots = getattr(root.common.dirs, "plots", None) or \
    "/root/repo/.data/plots"


def _agg_pyplot():
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    return plt


class Plotter(Unit):
    """Base render-to-file plotter (reference: veles/plotter.py ::
    Plotter).  Subclasses implement ``redraw(plt, fig)``."""

    def __init__(self, workflow=None, name: Optional[str] = None,
                 directory: Optional[str] = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.directory = directory or str(root.common.dirs.plots)
        self.render_count = 0
        self.last_path: Optional[str] = None

    def out_path(self) -> str:
        return os.path.join(self.directory, f"{self.name}.png")

    def run(self) -> None:
        plt = _agg_pyplot()
        fig = plt.figure(figsize=(6, 4), dpi=96)
        try:
            self.redraw(plt, fig)
            os.makedirs(self.directory, exist_ok=True)
            fig.savefig(self.out_path(), bbox_inches="tight")
            self.last_path = self.out_path()
            self.render_count += 1
        finally:
            plt.close(fig)

    def redraw(self, plt, fig) -> None:
        raise NotImplementedError


class AccumulatingPlotter(Plotter):
    """Metric-vs-epoch curve (reference: AccumulatingPlotter).  Reads the
    data-linked ``input`` scalar each run and appends."""

    def __init__(self, workflow=None, label: str = "metric",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = 0.0       # data-linked scalar (e.g. decision metric)
        self.values: list[float] = []

    def redraw(self, plt, fig) -> None:
        self.values.append(float(self.input))
        ax = fig.add_subplot(111)
        ax.plot(np.arange(1, len(self.values) + 1), self.values,
                marker="o", ms=3)
        ax.set_xlabel("epoch")
        ax.set_ylabel(self.name)
        ax.grid(True, alpha=0.3)


class MatrixPlotter(Plotter):
    """Confusion-matrix heatmap (reference: MatrixPlotter)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = None      # data-linked matrix

    def redraw(self, plt, fig) -> None:
        m = np.asarray(self.input)
        ax = fig.add_subplot(111)
        im = ax.imshow(m, cmap="viridis")
        fig.colorbar(im)
        ax.set_xlabel("target")
        ax.set_ylabel("predicted")
        if m.shape[0] <= 20:
            for i in range(m.shape[0]):
                for j in range(m.shape[1]):
                    ax.text(j, i, str(int(m[i, j])), ha="center",
                            va="center", fontsize=7, color="white")


class ImagePlotter(Plotter):
    """Render a batch sample / arbitrary 2-D array as an image
    (reference: ImagePlotter)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = None

    def redraw(self, plt, fig) -> None:
        img = np.asarray(self.input, np.float32)
        img = img[0] if img.ndim > 3 else img
        if img.ndim == 3 and img.shape[-1] == 1:
            img = img[..., 0]
        ax = fig.add_subplot(111)
        ax.imshow(img, cmap=None if img.ndim == 3 else "gray")
        ax.axis("off")


class Histogram(Plotter):
    """Value histogram of the linked array (reference: Histogram)."""

    def __init__(self, workflow=None, n_bins: int = 50, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = None
        self.n_bins = n_bins

    def redraw(self, plt, fig) -> None:
        vals = np.asarray(self.input.map_read() if hasattr(self.input,
                                                           "map_read")
                          else self.input).ravel()
        ax = fig.add_subplot(111)
        ax.hist(vals, bins=self.n_bins)
        ax.set_ylabel("count")
        ax.grid(True, alpha=0.3)
