"""znicz_tpu — a TPU-native deep-learning framework.

A from-scratch rebuild of the capabilities of cnxtech/veles.znicz
(Samsung VELES core framework + the Znicz neural-network plugin),
designed TPU-first:

- the user-facing model is the reference's: a ``Workflow`` graph of
  ``Unit`` objects wired by control links (``link_from``) and data links
  (``link_attrs``), with boolean gates, a ``Repeater`` training loop,
  paired forward/gradient units, loaders, decision/early-stopping,
  snapshot/resume, plotting and hyperparameter tuning
  (reference: veles/units.py :: Unit, veles/workflow.py :: Workflow);
- the execution model is idiomatic JAX/XLA: the accelerated segment of
  the graph (forwards -> evaluator -> gradient units) is traced once into
  a single pure step function, jitted, and ``shard_map``-ped over a
  ``jax.sharding.Mesh`` with ``lax.psum`` gradient reduction over ICI —
  replacing the reference's per-unit OpenCL/CUDA kernel enqueues and its
  ZeroMQ master-slave parameter server
  (reference: veles/accelerated_units.py :: AcceleratedUnit,
  veles/server.py :: Server, veles/client.py :: Client);
- hand-written kernels (fused SGD update, LRN, dropout PRNG, stochastic
  pooling, Kohonen argmin-update) are Pallas TPU kernels, with XLA-native
  lowerings as the always-available fallback
  (reference: veles.znicz ocl/*.cl + cuda/*.cu).

Blueprint: /root/repo/SURVEY.md.  Targets: /root/repo/BASELINE.md.
"""

__version__ = "0.1.0"

from znicz_tpu.core.config import root, Config
from znicz_tpu.core import prng
from znicz_tpu.core.memory import Array
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import Unit, TrivialUnit
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.core.plumbing import Repeater, StartPoint, EndPoint
from znicz_tpu.core.backends import Device, NumpyDevice, TPUDevice, AutoDevice

__all__ = [
    "root", "Config", "prng", "Array", "Bool", "Unit", "TrivialUnit",
    "Workflow", "Repeater", "StartPoint", "EndPoint",
    "Device", "NumpyDevice", "TPUDevice", "AutoDevice",
]
