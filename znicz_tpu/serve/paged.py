"""Block-paged KV arena — the memory plane of generative serving
(ISSUE 12).

The contiguous :class:`~znicz_tpu.serve.kvcache.KVDecoder` cache
reserves one power-of-two bucket strip per slot and pays an O(bucket)
device copy every time the shared buffer grows.  This module replaces
that with the vLLM-shaped alternative: ONE preallocated device buffer of
fixed-size pages ``(layers, n_pages, page, heads, head_dim)`` shared by
every slot, plus a host-side per-slot page table.  A long-tail request
stops reserving worst-case memory (it holds exactly the pages its
resident tokens span), ``grow`` becomes a page-table append instead of a
device copy, and the slot ceiling is set by tokens actually resident —
not ``slots × max_bucket``.

Layout and invariants:

- **page 0 is scratch** — a reserved /dev/null page.  Page-table
  padding entries, writes from empty batch slots, and the tail of an
  adopt scatter all land there; its content is garbage by contract and
  no live view ever exposes it unmasked.  The allocator hands out pages
  ``1..n_pages-1`` only.
- A slot's page table maps sequence rows ``[0, len(pages)·page)`` to
  arena pages; row ``r`` lives at ``(pages[r // page], r % page)``.
- Compiled-shape policy mirrors the bucket discipline everywhere else
  in the serve plane: decode/verify programs are keyed on the
  power-of-two *page-view width* (``view_bucket``), so steady-state
  traffic over mixed lengths recompiles nothing and ``compile_count``
  stays assertable.
- Pages freed by a finished request may be reissued immediately: the
  new owner's rows are either rewritten before exposure or masked by
  its own ``pos`` (the same stale-row argument the contiguous cache
  makes for re-adopted slots, per page instead of per strip).

The attention math is inherited from :class:`KVDecoder` (the SAME
layer-norm / mask constants / f32 online-softmax recipe the training
forward uses), so the paged path stays pinned against the full-pass
logits oracle through the contiguous reference: paged reads over
randomized page tables must equal contiguous-buffer reads
(tests/test_paged.py).  The single-query hot path can optionally run
the Pallas flash-decode kernel (``ops/pallas/decode.py``), which
gathers K/V through the page table inside the kernel.
"""

from __future__ import annotations

import threading

import numpy as np

from znicz_tpu.serve.engine import bucket_sizes
from znicz_tpu.serve.kvcache import KVDecoder


class ArenaExhausted(RuntimeError):
    """No free pages left in the shared KV arena.  At admission this is
    backpressure (the batcher leaves the request queued); mid-generation
    it is the eviction policy — the growing request fails loudly with an
    error sentinel naming the arena."""


class PageLedger:
    """Host-side page accounting for one arena: free list, usage
    counters and the orphan sweep.  Page 0 (scratch) is never issued.

    Thread-safe, though in steady state only the continuous batcher's
    worker thread allocates and frees; ``submit`` threads read the
    counters for the never-servable check.
    """

    def __init__(self, n_pages: int) -> None:
        if n_pages < 2:
            raise ValueError(f"arena needs >= 2 pages (page 0 is the "
                             f"reserved scratch page), got {n_pages}")
        self.n_pages = int(n_pages)
        # pop() order hands out low page ids first — determinism for the
        # property tests, irrelevant to correctness
        self._free = list(range(self.n_pages - 1, 0, -1))
        self._lock = threading.Lock()
        self.peak_used = 0

    @property
    def total(self) -> int:
        """Allocatable pages (scratch excluded)."""
        return self.n_pages - 1

    @property
    def used(self) -> int:
        with self._lock:
            return self.total - len(self._free)

    @property
    def free(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int) -> list:
        """Take ``n`` pages or raise :class:`ArenaExhausted` (all-or-
        nothing — a partial grant would orphan pages on the error
        path)."""
        with self._lock:
            if n > len(self._free):
                raise ArenaExhausted(
                    f"KV arena exhausted: need {n} pages, "
                    f"{len(self._free)} of {self.total} free")
            pages = [self._free.pop() for _ in range(n)]
            self.peak_used = max(self.peak_used,
                                 self.total - len(self._free))
            return pages

    def release(self, pages) -> None:
        with self._lock:
            free = set(self._free)
            for p in pages:
                p = int(p)
                if p <= 0 or p >= self.n_pages or p in free:
                    raise ValueError(f"release of page {p} not owned by "
                                     f"this ledger (double free?)")
                free.add(p)
                self._free.append(p)

    def reclaim(self, owned) -> int:
        """Orphan sweep (the PR 9 pid-unique-temp pattern, per page):
        free every used page NOT in ``owned`` — called after a crash
        path that may have lost a request between allocation and its
        page-table record.  Returns the number of pages reclaimed."""
        owned = {int(p) for p in owned}
        with self._lock:
            known = set(self._free) | owned
            orphans = [p for p in range(1, self.n_pages)
                       if p not in known]
            self._free.extend(orphans)
            return len(orphans)


class PagedKVDecoder(KVDecoder):
    """Bucketed incremental decoder over a shared block-paged KV arena.

    Extends :class:`KVDecoder` (prompt prefill, bucket policy, compile
    accounting and the single-request contiguous path are inherited)
    with the paged device plane:

    - ``adopt_paged(kv1, pages)`` — scatter a prefilled contiguous
      single-request cache into arena pages (admission);
    - ``decode_paged(page_table, pos, token)`` — one batched
      single-token step: write each slot's row through its page table,
      attend over the gathered page view;
    - ``verify_paged(page_table, pos, tokens)`` — the speculative
      target pass: write+attend ``q_len`` rows per slot in ONE
      dispatch, returning logits at every position (the acceptance
      harness feeds these straight to the greedy rule).

    ``page`` is the rows-per-page granularity; ``arena_pages`` sizes the
    shared buffer (default: worst case — every slot at ``max_len`` —
    plus the scratch page, so an unconfigured decoder can never lose to
    the contiguous layout; production sets it smaller and banks on the
    long tail).  ``use_pallas=True`` routes single-query decode
    attention through the Pallas flash-decode kernel (interpret mode on
    CPU) — OFF by default so the oracle pin rides one code path.
    """

    paged = True

    def __init__(self, params, heads: int, max_len: int = 256,
                 batch: int = 1, page: int = 16,
                 arena_pages: int | None = None,
                 use_pallas: bool = False) -> None:
        super().__init__(params, heads=heads, max_len=max_len,
                         batch=batch)
        self.page = int(page)
        if self.page < 1:
            raise ValueError(f"page must be >= 1, got {page}")
        self.max_pages = -(-self.max_len // self.page)
        self.page_buckets = bucket_sizes(self.max_pages)
        if arena_pages is None:
            arena_pages = self.batch * self.max_pages + 1
        self.arena_pages = int(arena_pages)
        if self.arena_pages < 2:
            raise ValueError(f"arena_pages={arena_pages}: need >= 2 "
                             f"(page 0 is the reserved scratch page)")
        self.ledger = PageLedger(self.arena_pages)
        self.use_pallas = bool(use_pallas)
        self._pdecode: dict = {}
        self._pverify: dict = {}
        self._padopt: dict = {}
        import jax
        import jax.numpy as jnp

        #: compiled Pallas needs TPU-tileable shapes; on every other
        #: backend the kernel runs interpreted (bit-for-bit the same
        #: recipe, minus the speed)
        self._pallas_interpret = jax.default_backend() != "tpu"
        if self.use_pallas and not self._pallas_interpret:
            from znicz_tpu.ops.pallas import decode as _pdk

            if not _pdk.supported(self.page, self.head_dim):
                # decide at CONSTRUCTION, not mid-request: compiled
                # Mosaic wants sublane pages / lane-sized heads —
                # anything else serves the jnp path with one warning
                self.warning(
                    f"pallas decode disabled: page={self.page}, "
                    f"head_dim={self.head_dim} not compilable "
                    f"(need page % 8 == 0, head_dim % 128 == 0); "
                    f"serving the jnp gather path")
                self.use_pallas = False
        dt = self._cast_policy()
        shape = (self.n_layers, self.arena_pages, self.page, self.heads,
                 self.head_dim)
        #: THE shared device arena — one buffer for every slot
        self._arena = {"k": jnp.zeros(shape, dt),
                       "v": jnp.zeros(shape, dt)}

    # -- page geometry -------------------------------------------------------
    def pages_for(self, n_rows: int) -> int:
        """Pages needed to hold ``n_rows`` sequence rows (min 1)."""
        return max(1, -(-int(n_rows) // self.page))

    def view_bucket(self, n_pages: int) -> int:
        """Smallest compiled page-view width covering ``n_pages``."""
        for b in self.page_buckets:
            if n_pages <= b:
                return b
        raise ValueError(f"{n_pages} pages > max_pages {self.max_pages} "
                         f"(max_len {self.max_len}, page {self.page})")

    def arena_bytes(self) -> int:
        """Device bytes held by the shared arena (both K and V)."""
        return int(self._arena["k"].nbytes + self._arena["v"].nbytes)

    # -- compiled program builders ------------------------------------------
    def _build_padopt(self, t_p: int):
        import jax
        import jax.numpy as jnp

        page = self.page
        n = self.pages_for(t_p)
        pad = n * page - t_p

        def adopt(kv, kv1, pages):
            out = {}
            for name in ("k", "v"):
                c1 = kv1[name]                   # (L, 1, t_p, H, Dh)
                if pad:
                    c1 = jnp.pad(c1, ((0, 0), (0, 0), (0, pad), (0, 0),
                                      (0, 0)))
                c1 = c1.reshape(self.n_layers, n, page, self.heads,
                                self.head_dim)
                # chunks beyond the request's owned pages carry masked
                # bucket padding; their `pages` entries are scratch
                out[name] = kv[name].at[:, pages].set(c1)
            return out

        # donate the arena (arg 0) so the splice is in-place off-CPU
        return jax.jit(adopt, donate_argnums=(0,) if self._donate
                       else ())

    def _paged_attend(self, jnp, q, ka, va, pt, pos):
        """Single-query attention over the gathered page view — q
        ``(B, 1, H, Dh)``, arena layer ``ka/va (N, page, H, Dh)``,
        ``pt (B, P)``, ``pos (B,)``; rows past each slot's ``pos`` (and
        every scratch-padding page) are masked with the shared -1e30
        constant, exactly like the contiguous decode."""
        B = q.shape[0]
        t_view = pt.shape[1] * self.page
        kc = ka[pt].reshape(B, t_view, self.heads, self.head_dim)
        vc = va[pt].reshape(B, t_view, self.heads, self.head_dim)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                       preferred_element_type=jnp.float32)
        s = s / np.sqrt(self.head_dim).astype(s.dtype)
        kpos = jnp.arange(t_view)
        dead = kpos[None, :] > pos[:, None]
        s = jnp.where(dead[:, None, None, :],
                      jnp.asarray(-1e30, s.dtype), s)
        return self._attend(jnp, s, vc).reshape(B, 1, -1)

    def _build_pdecode(self, p_view: int):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.parallel.transformer import _layer_norm

        H, Dh, page = self.heads, self.head_dim, self.page
        cdt = self._cast_policy()
        use_pallas = self.use_pallas
        interp = self._pallas_interpret

        def decode(params, kv, pt, pos, token):
            ps = jax.tree.map(lambda w: w.astype(cdt), params)
            B = token.shape[0]
            x = ps["emb"][token][:, None, :]         # (B, 1, d)
            pg_w = jnp.take_along_axis(pt, (pos // page)[:, None],
                                       axis=1)[:, 0]
            off = pos % page
            for li, p in enumerate(ps["blocks"]):
                h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
                q = (h @ p["wq"]).reshape(B, 1, H, Dh)
                k1 = (h @ p["wk"]).reshape(B, H, Dh)
                v1 = (h @ p["wv"]).reshape(B, H, Dh)
                # write THIS slot's row through the page table, then
                # attend over the view including it (mask is kpos > pos,
                # row pos itself attends — same as the contiguous step)
                kv = {"k": kv["k"].at[li, pg_w, off].set(k1),
                      "v": kv["v"].at[li, pg_w, off].set(v1)}
                ka, va = kv["k"][li], kv["v"][li]
                if use_pallas:
                    from znicz_tpu.ops.pallas.decode import \
                        paged_flash_decode
                    o = paged_flash_decode(q[:, 0], ka, va, pt, pos + 1,
                                           interpret=interp)
                    o = o.astype(va.dtype).reshape(B, 1, -1)
                else:
                    o = self._paged_attend(jnp, q, ka, va, pt, pos)
                x = x + o @ p["wo"]
                m = _layer_norm(x, p["ln2_g"], p["ln2_b"])
                x = x + (jax.nn.gelu(m @ p["w1"] + p["b1"]) @ p["w2"]
                         + p["b2"])
            logits = (x @ ps["head"]).astype(jnp.float32)
            return kv, logits[:, 0]

        return jax.jit(decode, donate_argnums=self._donate)

    def _build_pverify(self, key):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.parallel.transformer import _layer_norm

        p_view, q_len = key
        H, Dh, page = self.heads, self.head_dim, self.page
        cdt = self._cast_policy()
        t_view = p_view * page

        def verify(params, kv, pt, pos, tokens):
            ps = jax.tree.map(lambda w: w.astype(cdt), params)
            B = tokens.shape[0]
            x = ps["emb"][tokens]                    # (B, Q, d)
            rows = pos[:, None] + jnp.arange(q_len)[None, :]  # (B, Q)
            pg_w = jnp.take_along_axis(pt, rows // page, axis=1)
            off = rows % page
            kpos = jnp.arange(t_view)
            li = 0
            for p in ps["blocks"]:
                h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
                q = (h @ p["wq"]).reshape(B, q_len, H, Dh)
                k1 = (h @ p["wk"]).reshape(B, q_len, H, Dh)
                v1 = (h @ p["wv"]).reshape(B, q_len, H, Dh)
                kv = {"k": kv["k"].at[li, pg_w, off].set(k1),
                      "v": kv["v"].at[li, pg_w, off].set(v1)}
                kc = kv["k"][li][pt].reshape(B, t_view, H, Dh)
                vc = kv["v"][li][pt].reshape(B, t_view, H, Dh)
                s = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                               preferred_element_type=jnp.float32)
                s = s / np.sqrt(Dh).astype(s.dtype)
                # per-query causal frontier: query i (row pos+i) sees
                # rows <= pos+i — draft rows beyond it stay invisible
                dead = kpos[None, None, :] > rows[:, :, None]
                s = jnp.where(dead[:, None, :, :],
                              jnp.asarray(-1e30, s.dtype), s)
                o = self._attend(jnp, s, vc).reshape(B, q_len, -1)
                x = x + o @ p["wo"]
                m = _layer_norm(x, p["ln2_g"], p["ln2_b"])
                x = x + (jax.nn.gelu(m @ p["w1"] + p["b1"]) @ p["w2"]
                         + p["b2"])
                li += 1
            logits = (x @ ps["head"]).astype(jnp.float32)
            return kv, logits                        # (B, Q, V)

        return jax.jit(verify, donate_argnums=self._donate)

    @property
    def _donate(self) -> tuple:
        """Donate the arena buffers so decode updates in place on
        accelerators; CPU XLA cannot honor the donation (it would warn
        per program), so the copy stays explicit there."""
        import jax

        return (1,) if jax.default_backend() != "cpu" else ()

    # -- public paged API ----------------------------------------------------
    def adopt_paged(self, kv1, pages) -> None:
        """Scatter a prefilled single-request contiguous cache
        ``kv1 (L, 1, T_p, H, Dh)`` into the arena at ``pages`` — the
        admission splice.  ``pages`` may be SHORTER than the prefill
        bucket spans (a 130-token prompt in a 256 bucket owns 9 pages,
        not 16): the scatter's tail chunks — masked bucket padding — are
        routed to the scratch page."""
        t_p = int(kv1["k"].shape[2])
        n = self.pages_for(t_p)
        if len(pages) > n:
            raise ValueError(f"{len(pages)} pages for a {t_p}-row "
                             f"prefill ({n} chunks)")
        fn = self._program(self._padopt, t_p, self._build_padopt,
                           "padopt")
        pg = np.zeros(n, np.int32)                   # tail -> scratch
        pg[:len(pages)] = np.asarray(pages, np.int32)
        self._arena = fn(self._arena, kv1, pg)

    def _check_view(self, page_table, pos, rows_ahead: int):
        pt = np.asarray(page_table, np.int32)
        pos = np.asarray(pos, np.int32)
        if pt.ndim != 2 or pt.shape[0] != self.batch:
            raise ValueError(f"page_table must be ({self.batch}, "
                             f"view); got {pt.shape}")
        p_view = pt.shape[1]
        if p_view not in self.page_buckets:
            raise ValueError(f"page-table view {p_view} is not a "
                             f"compiled bucket {self.page_buckets}")
        if pos.min() < 0 or int(pos.max()) + rows_ahead > p_view * \
                self.page:
            # same clamp hazard as the contiguous decode: an
            # out-of-view row would silently write a wrong page
            raise ValueError(
                f"rows [{int(pos.min())}, {int(pos.max()) + rows_ahead}"
                f") outside the {p_view * self.page}-row page view")
        return pt, pos, p_view

    def decode_paged(self, page_table, pos, token) -> np.ndarray:
        """One batched decode step through the page table; updates the
        shared arena in place (functionally: the arena buffer is
        rebound) and returns host logits ``(batch, vocab)``."""
        pt, pos, p_view = self._check_view(page_table, pos, 1)
        fn = self._program(self._pdecode, p_view, self._build_pdecode,
                           "pdecode")
        self._arena, logits = fn(self._params, self._arena, pt, pos,
                                 np.asarray(token, np.int32))
        with self._lock:
            self.decode_steps += 1
            self.tokens_decoded += int(pos.size)
        return np.asarray(logits)

    def verify_paged(self, page_table, pos, tokens) -> np.ndarray:
        """The speculative target pass: process ``tokens (batch, Q)``
        (last accepted token + Q-1 draft proposals) in one dispatch,
        writing Q rows per slot, and return logits ``(batch, Q, vocab)``
        — position ``i``'s row predicts the token after ``tokens[:i]``,
        which is exactly what the greedy acceptance rule compares."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 2:
            raise ValueError(f"verify tokens must be (batch, q); got "
                             f"{tokens.shape}")
        q_len = tokens.shape[1]
        pt, pos, p_view = self._check_view(page_table, pos, q_len)
        fn = self._program(self._pverify, (p_view, q_len),
                           self._build_pverify, "pverify")
        self._arena, logits = fn(self._params, self._arena, pt, pos,
                                 tokens)
        with self._lock:
            self.decode_steps += 1
            self.tokens_decoded += int(tokens.size)
        return np.asarray(logits)

    def warmup(self, spec_k: int | None = None) -> int:
        """Materialize every compiled shape — prompt prefills, adopt
        scatters, decode per page-view bucket, and (when ``spec_k`` is
        given) the verify program per view — so live traffic compiles
        nothing.  All warmup writes land on the scratch page."""
        import time

        t0 = time.perf_counter()
        for b in self.buckets:
            kv1, _ = self.prefill([0], bucket=b)
            self.adopt_paged(kv1, [])                # all-scratch splice
        zeros = np.zeros(self.batch, np.int32)
        for pv in self.page_buckets:
            pt = np.zeros((self.batch, pv), np.int32)
            self.decode_paged(pt, zeros, zeros)
            # verify writes spec_k+1 rows, so live traffic can only
            # ever dispatch it at views that hold them (the batcher's
            # _ensure_pages guarantees pages*page >= pos+k+1) — a
            # narrower view would just crash warmup here
            if spec_k and pv * self.page >= spec_k + 1:
                self.verify_paged(pt, zeros,
                                  np.zeros((self.batch, spec_k + 1),
                                           np.int32))
        dt = time.perf_counter() - t0
        self.info(f"paged warmup: {len(self.buckets)} prefill buckets "
                  f"+ {len(self.page_buckets)} page views in {dt:.2f}s "
                  f"— {self.compile_count} programs compiled")
        return self.compile_count

    def stats(self) -> dict:
        out = super().stats()
        out.update({
            "paged": True, "page": self.page,
            "arena_pages": self.arena_pages,
            "pages_total": self.ledger.total,
            "pages_used": self.ledger.used,
            "pages_peak": self.ledger.peak_used,
            "arena_bytes": self.arena_bytes(),
            "use_pallas": self.use_pallas,
        })
        return out


def truncate_draft(params, n_layers: int):
    """Derive a layer-truncated draft from a target param pytree: same
    embedding, same head (same charmap vocab by construction), first
    ``n_layers`` blocks.  Early-exit drafting — the zero-extra-training
    way to get a cheaper proposer whose logits track the target's."""
    blocks = params["blocks"]
    n_layers = int(n_layers)
    if not 1 <= n_layers < len(blocks):
        raise ValueError(f"draft needs 1 <= n_layers < {len(blocks)}, "
                         f"got {n_layers}")
    return {"emb": np.asarray(params["emb"], np.float32),
            "head": np.asarray(params["head"], np.float32),
            "blocks": [{k: np.asarray(a, np.float32)
                        for k, a in blk.items()}
                       for blk in blocks[:n_layers]]}
