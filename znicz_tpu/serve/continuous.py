"""Continuous batching for generative decode — the admission half of
the generative serving plane (ISSUE 10).

``MicroBatcher`` drains whole batches: every request in a batch enters
and leaves together, which is right for one-shot forward passes and
wrong for autoregressive traffic (a 200-token generation would hold a
4-token one hostage).  The continuous batcher instead keeps ONE decode
batch running forever over a fixed-width *slot map*: every decode step
advances all occupied slots by one token, finished requests free their
slot mid-flight, and newly admitted requests prefill and join the very
next step — no drain, no stragglers, the vLLM/Orca scheduling shape on
top of :class:`~znicz_tpu.serve.kvcache.KVDecoder`'s bucketed cache.

Contract (the serve plane's invariant, extended to streams): **every
admitted request gets exactly one terminal event** — ``done`` after its
tokens, or an error sentinel — never silence, never a duplicate:

- **backpressure**: a full wait queue rejects at ``submit`` with the
  serve plane's :class:`~znicz_tpu.serve.batcher.QueueFull` (HTTP 503);
- **deadlines**: a request whose deadline lapses (queued OR
  mid-generation) gets a terminal error sentinel naming the deadline;
- **abort**: ``TokenStream.cancel()`` frees the slot at the next step
  and counts the request abandoned;
- **chaos**: a crash inside the decode loop (fault site
  ``generate.step``, or a real engine failure) fails every ACTIVE
  stream with the error sentinel and keeps the worker serving — queued
  requests still get their turn;
- **graceful drain**: ``stop(drain=True)`` rejects new arrivals but
  decodes everything admitted to completion.

ISSUE 12 adds the memory/speed plane on top: with a
:class:`~znicz_tpu.serve.paged.PagedKVDecoder` the batcher admits
against the PAGE budget (a queued request waits for free arena pages,
not a worst-case bucket), ``grow`` is a page-table append, eviction on
arena exhaustion fails the growing request loudly, and a crash-path
sweep keeps the page ledger exact (``pages_used == Σ live slot
pages``).  With a ``draft`` decoder each step becomes a speculative
round — the draft proposes ``spec_k`` tokens, the target verifies all
of them in one batched pass, and greedy streams stay token-identical
to non-speculative decode by construction (every emitted token is the
target's own greedy choice).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import probe as _probe
from znicz_tpu.observe import trace as _trace
from znicz_tpu.observe.federation import next_request_id, request_track
from znicz_tpu.resilience.faults import fault_hook
from znicz_tpu.serve.batcher import QueueFull
from znicz_tpu.serve.kvcache import KVDecoder, TokenSampler
from znicz_tpu.serve.metrics import GenerateMetrics
from znicz_tpu.serve.paged import ArenaExhausted


class GenerationError(RuntimeError):
    """Terminal error sentinel carried by a :class:`TokenStream`."""


class TokenStream:
    """Client handle for one generation: a bounded-unbounded event
    queue the batcher worker feeds.  Events are plain dicts —
    ``{"token": id}`` per token, then exactly one terminal event:
    ``{"done": True, "reason": ...}`` or ``{"error": msg, "done":
    True}`` — the same shapes ``POST /generate`` streams as ndjson.
    """

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 request_id: str | None = None) -> None:
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        #: distributed-tracing correlation key (ISSUE 11): minted at
        #: HTTP admission (or here for direct submits) and carried by
        #: every phase span this request emits
        self.request_id = request_id or next_request_id()
        self.tokens: list = []
        self.t_submit = time.monotonic()
        self.ttft_s: float | None = None
        #: batcher step counter when the first/last token landed — the
        #: continuous-join pin reads these (a late joiner must finish at
        #: a LOWER step count than a long early request)
        self.first_token_step: int | None = None
        self.finish_step: int | None = None
        self._events: queue.Queue = queue.Queue()
        self._terminal: dict | None = None
        self._cancelled = threading.Event()

    # -- batcher side --------------------------------------------------------
    def _push_token(self, token: int) -> None:
        self.tokens.append(token)
        self._events.put({"token": int(token)})

    def _push_terminal(self, event: dict) -> None:
        self._terminal = event
        self._events.put(event)

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    # -- client side ---------------------------------------------------------
    def cancel(self) -> None:
        """Ask the batcher to free this request's slot at the next
        step; the stream still receives its terminal event (``reason:
        "aborted"``)."""
        self._cancelled.set()

    def next_event(self, timeout: float | None = None) -> dict:
        """Blocking pop of the next event; raises ``TimeoutError`` when
        ``timeout`` lapses with nothing produced."""
        try:
            return self._events.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no stream event within {timeout}s") from None

    def __iter__(self):
        """Yield token ids until the terminal event; a terminal error
        sentinel raises :class:`GenerationError`."""
        while True:
            event = self._events.get()
            if "error" in event:
                raise GenerationError(event["error"])
            if event.get("done"):
                return
            yield event["token"]

    def result(self, timeout_s: float | None = None) -> list:
        """Collect the full generation; raises on the error sentinel."""
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        while self._terminal is None or not self._events.empty():
            remaining = None if deadline is None else \
                max(0.001, deadline - time.monotonic())
            event = self.next_event(timeout=remaining)
            if "error" in event:
                raise GenerationError(event["error"])
            if event.get("done"):
                return list(self.tokens)
        if "error" in (self._terminal or {}):
            raise GenerationError(self._terminal["error"])
        return list(self.tokens)


class _GenRequest:
    __slots__ = ("stream", "prompt", "max_new", "sampler", "deadline",
                 "pos", "next_token", "emitted", "finished", "track",
                 "t0_perf", "first_perf", "pages", "draft_pages")

    def __init__(self, stream: TokenStream, prompt: np.ndarray,
                 max_new: int, sampler: TokenSampler,
                 deadline: float | None) -> None:
        self.stream = stream
        self.prompt = prompt
        self.max_new = max_new
        self.sampler = sampler
        self.deadline = deadline            # monotonic stamp or None
        self.pos = 0                        # next cache row to write
        self.next_token = 0                 # token to feed next step
        self.emitted = 0
        self.finished = False
        #: arena pages this request holds (paged decoder only) — the
        #: page table maps row r to (pages[r // page], r % page)
        self.pages: list = []
        self.draft_pages: list = []
        #: trace anchors (ISSUE 11): every phase span of this request
        #: lands on one synthetic per-request track
        self.track = request_track(stream.request_id)
        self.t0_perf = time.perf_counter()      # admission (queue start)
        self.first_perf: float | None = None    # first token sampled

    @property
    def greedy(self) -> bool:
        """Greedy requests ride the speculative acceptance rule; sampled
        ones take one token per round from the verify logits' position 0
        (their exact decode distribution — speculation never distorts
        sampling)."""
        return self.sampler.temperature == 0.0 or self.sampler.top_k == 1

    @property
    def total_budget(self) -> int:
        return len(self.prompt) + self.max_new


class ContinuousBatcher(Logger):
    """Run a :class:`KVDecoder`'s batched decode loop with per-step
    slot admission and retirement.

    ``decoder.batch`` is the slot width; ``max_queue`` bounds requests
    waiting for a slot (admission beyond it fails fast with
    :class:`QueueFull`); ``default_timeout_s`` is the per-request
    deadline when ``submit`` gets none.  With a contiguous
    :class:`KVDecoder` the shared KV cache starts at the smallest
    bucket covering the first admissions and grows (never shrinks) to
    the bucket ceiling of what is admitted; with a
    :class:`~znicz_tpu.serve.paged.PagedKVDecoder` requests hold arena
    pages instead and admission/growth/eviction ride the page ledger.
    Either way each compiled shape materializes once (or zero times
    after ``decoder.warmup()``) and steady state recompiles nothing.

    ``draft`` (paged only) switches every step to a speculative
    draft+verify round proposing ``spec_k`` tokens — greedy streams
    stay token-identical to plain decode; sampled ones keep their
    exact seeded distribution.
    """

    def __init__(self, decoder: KVDecoder, max_queue: int = 64,
                 default_timeout_s: float = 60.0,
                 metrics: GenerateMetrics | None = None,
                 draft: KVDecoder | None = None,
                 spec_k: int = 4,
                 on_complete=None) -> None:
        super().__init__()
        #: feedback hook (ISSUE 14): called as ``on_complete(request_id,
        #: prompt_ids, tokens)`` from THE single terminal path, COMPLETED
        #: requests only — exactly the traffic the ledger counts
        #: ``completed``, so the learn plane's spool and the admission
        #: ledger can never disagree on what "accepted" means.  A hook
        #: failure is logged, never fatal to the decode loop.
        self._on_complete = on_complete
        self.decoder = decoder
        #: paged decoders (serve/paged.py) swap the shared bucket cache
        #: for the block-paged arena: admission and growth ride the page
        #: ledger and QueueFull/eviction track PAGES, not the slot map
        self._paged = bool(getattr(decoder, "paged", False))
        self._draft = draft
        self._spec_k = int(spec_k)
        if self._spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if draft is not None:
            if not self._paged or not getattr(draft, "paged", False):
                raise ValueError(
                    "speculative decoding needs PagedKVDecoder for both "
                    "target and draft (the contiguous path has no "
                    "multi-row verify)")
            if draft.batch != decoder.batch:
                raise ValueError(f"draft batch {draft.batch} != target "
                                 f"batch {decoder.batch}")
            if draft.vocab != decoder.vocab:
                raise ValueError(f"draft vocab {draft.vocab} != target "
                                 f"vocab {decoder.vocab} — the draft "
                                 "must speak the same charmap")
            if draft.max_len < decoder.max_len:
                raise ValueError(f"draft max_len {draft.max_len} < "
                                 f"target max_len {decoder.max_len}")
        self.slots: list = [None] * decoder.batch
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.metrics = metrics if metrics is not None else \
            GenerateMetrics()
        if self._paged:
            self.metrics.on_pages(decoder.ledger.used,
                                  decoder.ledger.total)
        if draft is not None:
            # pre-touch both counter children so fleet delta rules see
            # the 0 baseline (the PR 11 test-won lesson)
            self.metrics.on_spec(0, 0)
        self.step_count = 0
        self._kv = None
        self._bucket = 0
        self._pending: list = []
        self._cond = threading.Condition()
        self._closing = False
        self._drain = True
        # ISSUE 11 satellite: flight artifacts dumped in this process
        # embed the live admission ledger (admitted/completed/failed/
        # abandoned), so a post-mortem checks ledger equality without a
        # live scrape.  One provider object so stop() can unregister
        # exactly what it registered (newest batcher wins the name).
        self._flight_plane = self.metrics.snapshot
        _flight.register_plane("generate_ledger", self._flight_plane)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._worker.start()

    @property
    def draining(self) -> bool:
        return self._closing

    # -- client side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               timeout_s: float | None = None,
               request_id: str | None = None) -> TokenStream:
        """Admit one generation; returns its :class:`TokenStream`.
        Raises :class:`QueueFull` under backpressure or during drain,
        ``ValueError`` on never-servable input (bad ids, budget beyond
        the decoder's ``max_len``).  ``request_id`` threads an
        HTTP-admission trace id through; direct callers get one
        minted."""
        ids = np.asarray(prompt, np.int32).ravel()
        if ids.size < 1:
            raise ValueError("empty prompt")
        if ids.min() < 0 or ids.max() >= self.decoder.vocab:
            raise ValueError(
                f"token ids must be in [0, {self.decoder.vocab}); got "
                f"range [{ids.min()}, {ids.max()}]")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        # never admissible — bad input, not backpressure (400, not 503):
        # the check runs HERE, before any slot or prefill is burned, and
        # the error names the configured limit
        self.decoder.bucket_for(ids.size + max_new_tokens)
        if self._paged:
            need = self.decoder.pages_for(ids.size + max_new_tokens)
            if need > self.decoder.ledger.total:
                raise ValueError(
                    f"request budget of {ids.size + max_new_tokens} "
                    f"tokens needs {need} arena pages but the arena "
                    f"holds only {self.decoder.ledger.total} "
                    f"(page size {self.decoder.page}; raise "
                    f"--arena-pages)")
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got "
                             f"{timeout_s}")
        sampler = TokenSampler(seed=seed, temperature=temperature,
                               top_k=top_k)
        stream = TokenStream(ids.size, max_new_tokens,
                             request_id=request_id)
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        req = _GenRequest(stream, ids, max_new_tokens, sampler, deadline)
        with self._cond:
            if self._closing:
                self.metrics.on_reject()
                raise QueueFull("generate batcher is draining")
            if len(self._pending) >= self.max_queue:
                self.metrics.on_reject()
                raise QueueFull(f"generate queue full "
                                f"({len(self._pending)}/{self.max_queue})")
            self._pending.append(req)
            self.metrics.on_admit()
            self._cond.notify_all()
        return stream

    # -- worker side ---------------------------------------------------------
    def _finish(self, req: _GenRequest, event: dict) -> None:
        """THE single terminal-event path — exactly once per admitted
        request, whatever the cause."""
        if req.finished:
            return
        req.finished = True
        req.stream.finish_step = self.step_count
        if req.first_perf is not None:
            # the decode phase span: first sampled token -> terminal
            # event, on the request's own trace track (per-step timing
            # lives in the batched generate.decode_step spans; this one
            # makes a single request's tail attributable end to end)
            t1 = time.perf_counter()
            _trace.TRACER.complete(
                "generate.decode", req.first_perf, t1 - req.first_perf,
                tid=req.track, rid=req.stream.request_id,
                n_tokens=req.emitted)
        req.stream._push_terminal(event)
        self._release_pages(req)
        if "error" in event:
            self.metrics.on_failed()
        elif event.get("reason") == "aborted":
            self.metrics.on_abandoned()
        else:
            self.metrics.on_complete()
            if self._on_complete is not None:
                try:
                    self._on_complete(req.stream.request_id,
                                      req.prompt.tolist(),
                                      list(req.stream.tokens))
                except Exception as exc:  # noqa: BLE001 — feedback must
                    self.warning(              # never kill the worker
                        f"on_complete feedback hook failed: {exc!r}")

    def _release_pages(self, req: _GenRequest) -> None:
        """Return a finished request's arena pages — called from the ONE
        terminal path, so every exit (done/deadline/cancel/crash) frees
        exactly what admission and growth allocated."""
        if req.pages:
            self.decoder.ledger.release(req.pages)
            req.pages = []
        if req.draft_pages:
            self._draft.ledger.release(req.draft_pages)
            req.draft_pages = []
        if self._paged:
            self.metrics.on_pages(self.decoder.ledger.used,
                                  self.decoder.ledger.total)

    def _emit_token(self, req: _GenRequest, token: int) -> None:
        if req.emitted == 0:
            req.stream.ttft_s = time.monotonic() - req.stream.t_submit
            req.stream.first_token_step = self.step_count
            req.first_perf = time.perf_counter()
            self.metrics.on_first_token(req.stream.ttft_s)
        req.stream._push_token(token)
        req.emitted += 1
        self.metrics.on_tokens(1)

    def _retire_if_done(self, req: _GenRequest, slot: int,
                        now: float) -> bool:
        """Post-emit terminal checks; True when the slot was freed."""
        if req.emitted >= req.max_new:
            self._finish(req, {"done": True, "reason": "length",
                               "n_tokens": req.emitted})
        elif req.stream.cancelled:
            self._finish(req, {"done": True, "reason": "aborted",
                               "n_tokens": req.emitted})
        elif req.deadline is not None and now > req.deadline:
            self._finish(req, {
                "error": f"deadline exceeded after {req.emitted} tokens "
                         f"({now - req.stream.t_submit:.3f}s)",
                "done": True})
        if req.finished:
            self.slots[slot] = None
            return True
        return False

    def _can_admit(self, req: _GenRequest) -> bool:
        """Paged admission gate: the request's PROMPT pages must be free
        in the arena (and the draft's, under speculation) — the rest of
        its budget grows page by page as it decodes.  A gated request
        stays queued; running slots free pages as they finish."""
        need = self.decoder.pages_for(len(req.prompt))
        if self.decoder.ledger.free < need:
            return False
        if self._draft is not None and \
                self._draft.ledger.free < self._draft.pages_for(
                    len(req.prompt)):
            return False
        return True

    def _admit(self) -> None:
        """Move pending requests into free slots: prefill the prompt,
        splice the cache in, emit the first token (TTFT stops here).
        Contiguous decoders grow the one shared bucket cache before the
        splice; paged decoders allocate prompt pages from the arena and
        scatter the prefill through the page table."""
        while True:
            with self._cond:
                free = [i for i, s in enumerate(self.slots) if s is None]
                if not free or not self._pending:
                    return
                if self._paged and not self._can_admit(self._pending[0]):
                    if any(s is not None for s in self.slots):
                        return          # pages free up as slots finish
                    # nothing is running yet the arena says full: only a
                    # leak can cause this — sweep, then fail loudly if
                    # the request still does not fit
                    self._sweep_orphan_pages()
                    if not self._can_admit(self._pending[0]):
                        req = self._pending.pop(0)
                        self._finish(req, {
                            "error": "KV arena exhausted with no live "
                                     "generations (page leak?)",
                            "done": True})
                        continue
                req = self._pending.pop(0)
            now = time.monotonic()
            # queue-wait phase span: admission -> leaving the wait queue
            # (expired/cancelled requests keep theirs — the span IS the
            # evidence the queue killed them)
            t_dequeue = time.perf_counter()
            _trace.TRACER.complete(
                "generate.queue", req.t0_perf, t_dequeue - req.t0_perf,
                tid=req.track, rid=req.stream.request_id)
            if req.stream.cancelled:
                self._finish(req, {"done": True, "reason": "aborted",
                                   "n_tokens": 0})
                continue
            if req.deadline is not None and now > req.deadline:
                self._finish(req, {
                    "error": f"deadline exceeded after "
                             f"{now - req.stream.t_submit:.3f}s in queue",
                    "done": True})
                continue
            slot = free[0]
            t_prefill = time.perf_counter()
            try:
                logits = self._attach_paged(req, slot) if self._paged \
                    else self._attach_contiguous(req, slot)
            except Exception as exc:  # noqa: BLE001 — this request only
                self.error(f"prefill failed: {exc!r}")
                # _finish releases any pages already allocated, so a
                # failure between alloc and the page-table record cannot
                # orphan arena pages
                self._finish(req, {"error": f"prefill failed: {exc!r}",
                                   "done": True})
                continue
            _trace.TRACER.complete(
                "generate.prefill", t_prefill,
                time.perf_counter() - t_prefill, tid=req.track,
                rid=req.stream.request_id, prompt_len=len(req.prompt),
                slot=slot)
            # anatomy plane (ISSUE 20): prompt attach / KV prefill as a
            # phase of the serving plane's step taxonomy
            _probe.anatomy_phase("serve", "prefill",
                                 time.perf_counter() - t_prefill,
                                 t0=t_prefill)
            req.pos = len(req.prompt)
            self.slots[slot] = req
            token = req.sampler.sample(logits)
            req.next_token = token
            self._emit_token(req, token)
            self._retire_if_done(req, slot, time.monotonic())
        # (unreachable)

    def _attach_contiguous(self, req: _GenRequest, slot: int):
        """PR 10 admission: grow the one shared bucket cache to the
        budget ceiling of everything live, prefill at the REQUEST's own
        bucket (a short prompt must not pay a long request's
        O(bucket^2) attention pass), splice via adopt."""
        need = self.decoder.bucket_for(max(
            [req.total_budget] +
            [r.total_budget for r in self.slots if r is not None]))
        if self._kv is None:
            self._kv = self.decoder.alloc(need)
            self._bucket = need
        elif need > self._bucket:
            self._kv = self.decoder.grow(self._kv, need)
            self._bucket = need
        kv1, logits = self.decoder.prefill(
            req.prompt, bucket=self.decoder.bucket_for(req.total_budget))
        self._kv = self.decoder.adopt(self._kv, kv1, slot)
        return logits

    def _attach_paged(self, req: _GenRequest, slot: int):
        """Paged admission: allocate the PROMPT's pages only (the rest
        of the budget appends page by page as the generation grows),
        prefill at the prompt's own bucket, scatter into the arena.
        Pages are recorded on the request the moment they are allocated,
        so the error path (``_finish`` -> ``_release_pages``) can never
        orphan them."""
        dec = self.decoder
        req.pages = dec.ledger.alloc(dec.pages_for(len(req.prompt)))
        kv1, logits = dec.prefill(
            req.prompt, bucket=dec.bucket_for(len(req.prompt)))
        dec.adopt_paged(kv1, req.pages)
        if self._draft is not None:
            d = self._draft
            req.draft_pages = d.ledger.alloc(
                d.pages_for(len(req.prompt)))
            kv1d, _ = d.prefill(req.prompt,
                                bucket=d.bucket_for(len(req.prompt)))
            d.adopt_paged(kv1d, req.draft_pages)
        self.metrics.on_pages(dec.ledger.used, dec.ledger.total)
        return logits

    # -- paged stepping -------------------------------------------------------
    def _ensure_pages(self, req: _GenRequest, slot: int,
                      rows: int) -> bool:
        """grow() as a page-table append: extend the request's page
        tables until they cover ``rows`` sequence rows.  Exhaustion is
        the eviction policy — the GROWING request fails loudly with an
        error sentinel naming the arena (its pages free immediately;
        everything else keeps decoding)."""
        pairs = [(self.decoder, req.pages)]
        if self._draft is not None:
            pairs.append((self._draft, req.draft_pages))
        for dec, pages in pairs:
            while len(pages) * dec.page < rows:
                try:
                    pages.extend(dec.ledger.alloc(1))
                except ArenaExhausted as exc:
                    self.warning(f"evicting {req.stream.request_id}: "
                                 f"{exc}")
                    self._finish(req, {
                        "error": f"KV arena exhausted after "
                                 f"{req.emitted} tokens: {exc}",
                        "done": True})
                    self.slots[slot] = None
                    return False
        return True

    def _page_table(self, dec, attr: str) -> np.ndarray:
        """Assemble the device-facing page table for one decoder: a
        ``(slots, view)`` int32 array at the compiled view bucket
        covering the widest live slot; empty slots and padding entries
        point at the scratch page (their writes land in /dev/null and
        their reads are masked)."""
        widest = max(len(getattr(r, attr))
                     for r in self.slots if r is not None)
        pt = np.zeros((len(self.slots), dec.view_bucket(widest)),
                      np.int32)
        for i, req in enumerate(self.slots):
            if req is not None:
                pages = getattr(req, attr)
                pt[i, :len(pages)] = pages
        return pt

    def _sweep_orphan_pages(self) -> int:
        """Reconcile the arena against the slot map (the PR 9
        pid-unique-temp sweep pattern): any used page no live request
        owns is reclaimed.  Steady state never produces orphans — the
        sweep guards the crash path, and the chaos drill asserts the
        ledger closes (``pages_used == Σ live slot pages``) after it."""
        if not self._paged:
            return 0
        n = self.decoder.ledger.reclaim(
            [p for r in self.slots if r is not None for p in r.pages])
        if self._draft is not None:
            n += self._draft.ledger.reclaim(
                [p for r in self.slots if r is not None
                 for p in r.draft_pages])
        if n:
            self.warning(f"swept {n} orphaned arena pages")
        self.metrics.on_pages(self.decoder.ledger.used,
                              self.decoder.ledger.total)
        return n

    def page_ledger(self) -> dict:
        """Arena accounting for post-mortems and tests: used pages per
        the allocator vs pages owned by live slots — equal whenever the
        worker is quiescent."""
        if not self._paged:
            return {"paged": False}
        with self._cond:
            owned = sum(len(r.pages) for r in self.slots
                        if r is not None)
            draft_owned = sum(len(r.draft_pages) for r in self.slots
                              if r is not None)
        out = {"paged": True,
               "pages_used": self.decoder.ledger.used,
               "pages_owned": owned,
               "pages_total": self.decoder.ledger.total,
               "pages_peak": self.decoder.ledger.peak_used}
        if self._draft is not None:
            out["draft_pages_used"] = self._draft.ledger.used
            out["draft_pages_owned"] = draft_owned
        return out

    def _spec_round(self, pt, ptd, pos, tok):
        """Draft-then-verify: the draft proposes k tokens per slot
        (k+1 single-token steps — the last one writes the k-th
        proposal's K/V so an all-accepted round leaves the draft cache
        current), then the target judges all k+1 positions in ONE
        batched verify pass.  Returns ``(proposals (B, k), verify
        logits (B, k+1, V))``."""
        k = self._spec_k
        feeds = tok.copy()
        proposals = np.zeros((len(self.slots), k), np.int32)
        for j in range(k + 1):
            dlogits = self._draft.decode_paged(ptd, pos + j, feeds)
            if j < k:
                feeds = np.argmax(dlogits, axis=1).astype(np.int32)
                proposals[:, j] = feeds
        tokens = np.concatenate([tok[:, None], proposals], axis=1)
        return proposals, self.decoder.verify_paged(pt, pos, tokens)

    def _step_paged(self) -> None:
        """One batched round over the paged arena: plain single-token
        decode, or a speculative draft+verify round emitting 1..k+1
        tokens per greedy slot."""
        k = self._spec_k if self._draft is not None else 0
        if k:
            # a verify pass writes k+1 rows per slot UNCONDITIONALLY —
            # a slot within k tokens of its budget would be forced past
            # pages_for(budget) (spurious eviction in a tight arena)
            # and, at the max_len boundary, past the widest compiled
            # page view.  Rather than compile per-remaining q shapes,
            # the round degrades to plain decode whenever any live slot
            # is that close to its end — its final tokens were arriving
            # one-per-step anyway.
            head = min((r.total_budget - r.pos - 1
                        for r in self.slots if r is not None),
                       default=0)
            if head < k:
                k = 0
            # an all-sampled batch gains nothing from a round (each
            # slot takes one token off verify position 0 anyway) but
            # would pay k+1 draft dispatches + the wide verify for it
            elif not any(r.greedy for r in self.slots
                         if r is not None):
                k = 0
        for i, req in enumerate(self.slots):
            if req is not None and \
                    not self._ensure_pages(req, i, req.pos + k + 1):
                continue                     # evicted: arena exhausted
        live = [(i, r) for i, r in enumerate(self.slots)
                if r is not None]
        if not live:
            return
        pos = np.zeros(len(self.slots), np.int32)
        tok = np.zeros(len(self.slots), np.int32)
        for i, req in live:
            pos[i] = req.pos
            tok[i] = req.next_token
        pt = self._page_table(self.decoder, "pages")
        t_step = time.perf_counter()
        if k:
            proposals, vlogits = self._spec_round(
                pt, self._page_table(self._draft, "draft_pages"), pos,
                tok)
        else:
            logits = self.decoder.decode_paged(pt, pos, tok)
        self.step_count += 1
        _trace.TRACER.complete("generate.decode_step", t_step,
                               time.perf_counter() - t_step,
                               step=self.step_count, active=len(live),
                               paged=True, spec_k=k)
        # anatomy plane (ISSUE 20): a speculative round is a "verify"
        # phase (draft proposals + the target's batched judgment); a
        # plain round is one "decode" dispatch
        _probe.anatomy_phase("serve", "verify" if k else "decode",
                             time.perf_counter() - t_step, t0=t_step)
        now = time.monotonic()
        for i, req in live:
            if req.stream.cancelled or (req.deadline is not None and
                                        now > req.deadline):
                self._retire_if_done(req, i, now)
                continue
            if not k:
                emitted = [req.sampler.sample(logits[i])]
            elif req.greedy:
                g = np.argmax(vlogits[i], axis=-1)
                a = 0
                while a < k and proposals[i, a] == g[a]:
                    a += 1
                # a accepted drafts + the target's own token at the
                # first mismatch (or the bonus token when all matched):
                # every emitted token IS the target's greedy choice, so
                # the stream is token-identical to non-speculative
                # decode by construction
                emitted = [int(t) for t in proposals[i, :a]] + [int(g[a])]
                self.metrics.on_spec(a, k - a)
            else:
                # sampled request: position 0 of the verify logits IS
                # its exact next-token distribution — one token per
                # round, distribution untouched
                emitted = [req.sampler.sample(vlogits[i, 0])]
            for token in emitted:
                req.pos += 1
                req.next_token = int(token)
                self._emit_token(req, int(token))
                if req.emitted >= req.max_new:
                    break
            self._retire_if_done(req, i, now)

    def _step(self) -> None:
        """One batched decode step over the occupied slots."""
        # chaos hook (site "generate.step"): an injected crash here
        # exercises the fail-all-active path and the stream error
        # sentinel — the kill-mid-decode drill's anchor
        fault_hook("generate.step", batcher=self)
        if self._paged:
            self._step_paged()
            return
        pos = np.zeros(len(self.slots), np.int32)
        tok = np.zeros(len(self.slots), np.int32)
        active = 0
        for i, req in enumerate(self.slots):
            if req is not None:
                pos[i] = req.pos
                tok[i] = req.next_token
                active += 1
        t_step = time.perf_counter()
        self._kv, logits = self.decoder.decode(self._kv, pos, tok)
        self.step_count += 1
        # one batched decode-step span per step (worker thread): a
        # request's share of it is bracketed by its first_token_step /
        # finish_step counters
        _trace.TRACER.complete("generate.decode_step", t_step,
                               time.perf_counter() - t_step,
                               step=self.step_count, active=active)
        _probe.anatomy_phase("serve", "decode",
                             time.perf_counter() - t_step, t0=t_step)
        now = time.monotonic()
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            # cancel/deadline between steps: retire without sampling
            if req.stream.cancelled or (req.deadline is not None and
                                        now > req.deadline):
                self._retire_if_done(req, i, now)
                continue
            req.pos += 1
            token = req.sampler.sample(logits[i])
            req.next_token = token
            self._emit_token(req, token)
            self._retire_if_done(req, i, now)

    def _fail_active(self, exc: Exception) -> None:
        """A decode-loop crash poisons every in-flight stream (their
        cache state is unknowable mid-step) — each gets its error
        sentinel and the worker keeps serving the queue."""
        for i, req in enumerate(self.slots):
            if req is not None:
                self._finish(req, {"error": f"decode failed: {exc!r}",
                                   "done": True})
                self.slots[i] = None

    def _flush_pending(self, exc: Exception) -> None:
        while True:
            with self._cond:
                if not self._pending:
                    return
                req = self._pending.pop(0)
            self._finish(req, {"error": str(exc), "done": True})

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and \
                        all(s is None for s in self.slots) and \
                        not self._closing:
                    self._cond.wait()
                closing = self._closing
                drain = self._drain
            if closing and not drain:
                self._fail_active(QueueFull("generate batcher shut down"))
                self._flush_pending(QueueFull("generate batcher shut "
                                              "down"))
                return
            try:
                self._admit()
                if any(s is not None for s in self.slots):
                    self._step()
            except Exception as exc:  # noqa: BLE001 — the worker must
                # outlive anything one decode step can throw
                self.error(f"decode step crashed: {exc!r}")
                self._fail_active(exc)
                # a crash between a page allocation and its page-table
                # record could strand arena pages — reconcile before
                # serving the queue again
                self._sweep_orphan_pages()
            with self._cond:
                active = sum(s is not None for s in self.slots)
                queued = len(self._pending)
            self.metrics.on_slots(active, queued)
            if closing and active == 0 and queued == 0:
                return

    # -- lifecycle -----------------------------------------------------------
    def stop(self, drain: bool = True,
             join_timeout_s: float = 30.0) -> bool:
        """Stop admitting.  ``drain=True`` decodes everything admitted
        to completion; ``drain=False`` fails queued and active requests
        loudly.  Returns True when the worker exited in time."""
        with self._cond:
            self._closing = True
            self._drain = drain
            self._cond.notify_all()
        self._worker.join(timeout=join_timeout_s)
        _flight.unregister_plane("generate_ledger", self._flight_plane)
        return not self._worker.is_alive()

    def __enter__(self) -> "ContinuousBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)
