"""Continuous batching for generative decode — the admission half of
the generative serving plane (ISSUE 10).

``MicroBatcher`` drains whole batches: every request in a batch enters
and leaves together, which is right for one-shot forward passes and
wrong for autoregressive traffic (a 200-token generation would hold a
4-token one hostage).  The continuous batcher instead keeps ONE decode
batch running forever over a fixed-width *slot map*: every decode step
advances all occupied slots by one token, finished requests free their
slot mid-flight, and newly admitted requests prefill and join the very
next step — no drain, no stragglers, the vLLM/Orca scheduling shape on
top of :class:`~znicz_tpu.serve.kvcache.KVDecoder`'s bucketed cache.

Contract (the serve plane's invariant, extended to streams): **every
admitted request gets exactly one terminal event** — ``done`` after its
tokens, or an error sentinel — never silence, never a duplicate:

- **backpressure**: a full wait queue rejects at ``submit`` with the
  serve plane's :class:`~znicz_tpu.serve.batcher.QueueFull` (HTTP 503);
- **deadlines**: a request whose deadline lapses (queued OR
  mid-generation) gets a terminal error sentinel naming the deadline;
- **abort**: ``TokenStream.cancel()`` frees the slot at the next step
  and counts the request abandoned;
- **chaos**: a crash inside the decode loop (fault site
  ``generate.step``, or a real engine failure) fails every ACTIVE
  stream with the error sentinel and keeps the worker serving — queued
  requests still get their turn;
- **graceful drain**: ``stop(drain=True)`` rejects new arrivals but
  decodes everything admitted to completion.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import trace as _trace
from znicz_tpu.observe.federation import next_request_id, request_track
from znicz_tpu.resilience.faults import fault_hook
from znicz_tpu.serve.batcher import QueueFull
from znicz_tpu.serve.kvcache import KVDecoder, TokenSampler
from znicz_tpu.serve.metrics import GenerateMetrics


class GenerationError(RuntimeError):
    """Terminal error sentinel carried by a :class:`TokenStream`."""


class TokenStream:
    """Client handle for one generation: a bounded-unbounded event
    queue the batcher worker feeds.  Events are plain dicts —
    ``{"token": id}`` per token, then exactly one terminal event:
    ``{"done": True, "reason": ...}`` or ``{"error": msg, "done":
    True}`` — the same shapes ``POST /generate`` streams as ndjson.
    """

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 request_id: str | None = None) -> None:
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        #: distributed-tracing correlation key (ISSUE 11): minted at
        #: HTTP admission (or here for direct submits) and carried by
        #: every phase span this request emits
        self.request_id = request_id or next_request_id()
        self.tokens: list = []
        self.t_submit = time.monotonic()
        self.ttft_s: float | None = None
        #: batcher step counter when the first/last token landed — the
        #: continuous-join pin reads these (a late joiner must finish at
        #: a LOWER step count than a long early request)
        self.first_token_step: int | None = None
        self.finish_step: int | None = None
        self._events: queue.Queue = queue.Queue()
        self._terminal: dict | None = None
        self._cancelled = threading.Event()

    # -- batcher side --------------------------------------------------------
    def _push_token(self, token: int) -> None:
        self.tokens.append(token)
        self._events.put({"token": int(token)})

    def _push_terminal(self, event: dict) -> None:
        self._terminal = event
        self._events.put(event)

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    # -- client side ---------------------------------------------------------
    def cancel(self) -> None:
        """Ask the batcher to free this request's slot at the next
        step; the stream still receives its terminal event (``reason:
        "aborted"``)."""
        self._cancelled.set()

    def next_event(self, timeout: float | None = None) -> dict:
        """Blocking pop of the next event; raises ``TimeoutError`` when
        ``timeout`` lapses with nothing produced."""
        try:
            return self._events.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no stream event within {timeout}s") from None

    def __iter__(self):
        """Yield token ids until the terminal event; a terminal error
        sentinel raises :class:`GenerationError`."""
        while True:
            event = self._events.get()
            if "error" in event:
                raise GenerationError(event["error"])
            if event.get("done"):
                return
            yield event["token"]

    def result(self, timeout_s: float | None = None) -> list:
        """Collect the full generation; raises on the error sentinel."""
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        while self._terminal is None or not self._events.empty():
            remaining = None if deadline is None else \
                max(0.001, deadline - time.monotonic())
            event = self.next_event(timeout=remaining)
            if "error" in event:
                raise GenerationError(event["error"])
            if event.get("done"):
                return list(self.tokens)
        if "error" in (self._terminal or {}):
            raise GenerationError(self._terminal["error"])
        return list(self.tokens)


class _GenRequest:
    __slots__ = ("stream", "prompt", "max_new", "sampler", "deadline",
                 "pos", "next_token", "emitted", "finished", "track",
                 "t0_perf", "first_perf")

    def __init__(self, stream: TokenStream, prompt: np.ndarray,
                 max_new: int, sampler: TokenSampler,
                 deadline: float | None) -> None:
        self.stream = stream
        self.prompt = prompt
        self.max_new = max_new
        self.sampler = sampler
        self.deadline = deadline            # monotonic stamp or None
        self.pos = 0                        # next cache row to write
        self.next_token = 0                 # token to feed next step
        self.emitted = 0
        self.finished = False
        #: trace anchors (ISSUE 11): every phase span of this request
        #: lands on one synthetic per-request track
        self.track = request_track(stream.request_id)
        self.t0_perf = time.perf_counter()      # admission (queue start)
        self.first_perf: float | None = None    # first token sampled

    @property
    def total_budget(self) -> int:
        return len(self.prompt) + self.max_new


class ContinuousBatcher(Logger):
    """Run a :class:`KVDecoder`'s batched decode loop with per-step
    slot admission and retirement.

    ``decoder.batch`` is the slot width; ``max_queue`` bounds requests
    waiting for a slot (admission beyond it fails fast with
    :class:`QueueFull`); ``default_timeout_s`` is the per-request
    deadline when ``submit`` gets none.  The shared KV cache starts at
    the smallest bucket covering the first admissions and grows (never
    shrinks) to the bucket ceiling of what is admitted — each bucket's
    programs compile once (or zero times after ``decoder.warmup()``),
    and steady-state decode over mixed request lengths within a bucket
    recompiles nothing.
    """

    def __init__(self, decoder: KVDecoder, max_queue: int = 64,
                 default_timeout_s: float = 60.0,
                 metrics: GenerateMetrics | None = None) -> None:
        super().__init__()
        self.decoder = decoder
        self.slots: list = [None] * decoder.batch
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.metrics = metrics if metrics is not None else \
            GenerateMetrics()
        self.step_count = 0
        self._kv = None
        self._bucket = 0
        self._pending: list = []
        self._cond = threading.Condition()
        self._closing = False
        self._drain = True
        # ISSUE 11 satellite: flight artifacts dumped in this process
        # embed the live admission ledger (admitted/completed/failed/
        # abandoned), so a post-mortem checks ledger equality without a
        # live scrape.  One provider object so stop() can unregister
        # exactly what it registered (newest batcher wins the name).
        self._flight_plane = self.metrics.snapshot
        _flight.register_plane("generate_ledger", self._flight_plane)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._worker.start()

    @property
    def draining(self) -> bool:
        return self._closing

    # -- client side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               timeout_s: float | None = None,
               request_id: str | None = None) -> TokenStream:
        """Admit one generation; returns its :class:`TokenStream`.
        Raises :class:`QueueFull` under backpressure or during drain,
        ``ValueError`` on never-servable input (bad ids, budget beyond
        the decoder's ``max_len``).  ``request_id`` threads an
        HTTP-admission trace id through; direct callers get one
        minted."""
        ids = np.asarray(prompt, np.int32).ravel()
        if ids.size < 1:
            raise ValueError("empty prompt")
        if ids.min() < 0 or ids.max() >= self.decoder.vocab:
            raise ValueError(
                f"token ids must be in [0, {self.decoder.vocab}); got "
                f"range [{ids.min()}, {ids.max()}]")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        # never admissible — bad input, not backpressure (400, not 503)
        self.decoder.bucket_for(ids.size + max_new_tokens)
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got "
                             f"{timeout_s}")
        sampler = TokenSampler(seed=seed, temperature=temperature,
                               top_k=top_k)
        stream = TokenStream(ids.size, max_new_tokens,
                             request_id=request_id)
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        req = _GenRequest(stream, ids, max_new_tokens, sampler, deadline)
        with self._cond:
            if self._closing:
                self.metrics.on_reject()
                raise QueueFull("generate batcher is draining")
            if len(self._pending) >= self.max_queue:
                self.metrics.on_reject()
                raise QueueFull(f"generate queue full "
                                f"({len(self._pending)}/{self.max_queue})")
            self._pending.append(req)
            self.metrics.on_admit()
            self._cond.notify_all()
        return stream

    # -- worker side ---------------------------------------------------------
    def _finish(self, req: _GenRequest, event: dict) -> None:
        """THE single terminal-event path — exactly once per admitted
        request, whatever the cause."""
        if req.finished:
            return
        req.finished = True
        req.stream.finish_step = self.step_count
        if req.first_perf is not None:
            # the decode phase span: first sampled token -> terminal
            # event, on the request's own trace track (per-step timing
            # lives in the batched generate.decode_step spans; this one
            # makes a single request's tail attributable end to end)
            t1 = time.perf_counter()
            _trace.TRACER.complete(
                "generate.decode", req.first_perf, t1 - req.first_perf,
                tid=req.track, rid=req.stream.request_id,
                n_tokens=req.emitted)
        req.stream._push_terminal(event)
        if "error" in event:
            self.metrics.on_failed()
        elif event.get("reason") == "aborted":
            self.metrics.on_abandoned()
        else:
            self.metrics.on_complete()

    def _emit_token(self, req: _GenRequest, token: int) -> None:
        if req.emitted == 0:
            req.stream.ttft_s = time.monotonic() - req.stream.t_submit
            req.stream.first_token_step = self.step_count
            req.first_perf = time.perf_counter()
            self.metrics.on_first_token(req.stream.ttft_s)
        req.stream._push_token(token)
        req.emitted += 1
        self.metrics.on_tokens(1)

    def _retire_if_done(self, req: _GenRequest, slot: int,
                        now: float) -> bool:
        """Post-emit terminal checks; True when the slot was freed."""
        if req.emitted >= req.max_new:
            self._finish(req, {"done": True, "reason": "length",
                               "n_tokens": req.emitted})
        elif req.stream.cancelled:
            self._finish(req, {"done": True, "reason": "aborted",
                               "n_tokens": req.emitted})
        elif req.deadline is not None and now > req.deadline:
            self._finish(req, {
                "error": f"deadline exceeded after {req.emitted} tokens "
                         f"({now - req.stream.t_submit:.3f}s)",
                "done": True})
        if req.finished:
            self.slots[slot] = None
            return True
        return False

    def _admit(self) -> None:
        """Move pending requests into free slots: prefill the prompt,
        splice the cache in, emit the first token (TTFT stops here).
        Bucket growth happens before the splice so every live slot
        rides one shared cache."""
        while True:
            with self._cond:
                free = [i for i, s in enumerate(self.slots) if s is None]
                if not free or not self._pending:
                    return
                req = self._pending.pop(0)
            now = time.monotonic()
            # queue-wait phase span: admission -> leaving the wait queue
            # (expired/cancelled requests keep theirs — the span IS the
            # evidence the queue killed them)
            t_dequeue = time.perf_counter()
            _trace.TRACER.complete(
                "generate.queue", req.t0_perf, t_dequeue - req.t0_perf,
                tid=req.track, rid=req.stream.request_id)
            if req.stream.cancelled:
                self._finish(req, {"done": True, "reason": "aborted",
                                   "n_tokens": 0})
                continue
            if req.deadline is not None and now > req.deadline:
                self._finish(req, {
                    "error": f"deadline exceeded after "
                             f"{now - req.stream.t_submit:.3f}s in queue",
                    "done": True})
                continue
            slot = free[0]
            t_prefill = time.perf_counter()
            try:
                need = self.decoder.bucket_for(max(
                    [req.total_budget] +
                    [r.total_budget for r in self.slots if r is not None]))
                if self._kv is None:
                    self._kv = self.decoder.alloc(need)
                    self._bucket = need
                elif need > self._bucket:
                    self._kv = self.decoder.grow(self._kv, need)
                    self._bucket = need
                # prefill at the REQUEST's own bucket, not the shared
                # one: a short prompt must not pay a long request's
                # O(bucket^2) attention pass — adopt() grows the result
                # to the shared bucket (zeros past the prompt, masked)
                kv1, logits = self.decoder.prefill(
                    req.prompt,
                    bucket=self.decoder.bucket_for(req.total_budget))
                self._kv = self.decoder.adopt(self._kv, kv1, slot)
            except Exception as exc:  # noqa: BLE001 — this request only
                self.error(f"prefill failed: {exc!r}")
                self._finish(req, {"error": f"prefill failed: {exc!r}",
                                   "done": True})
                continue
            _trace.TRACER.complete(
                "generate.prefill", t_prefill,
                time.perf_counter() - t_prefill, tid=req.track,
                rid=req.stream.request_id, prompt_len=len(req.prompt),
                slot=slot)
            req.pos = len(req.prompt)
            self.slots[slot] = req
            token = req.sampler.sample(logits)
            req.next_token = token
            self._emit_token(req, token)
            self._retire_if_done(req, slot, time.monotonic())
        # (unreachable)

    def _step(self) -> None:
        """One batched decode step over the occupied slots."""
        # chaos hook (site "generate.step"): an injected crash here
        # exercises the fail-all-active path and the stream error
        # sentinel — the kill-mid-decode drill's anchor
        fault_hook("generate.step", batcher=self)
        pos = np.zeros(len(self.slots), np.int32)
        tok = np.zeros(len(self.slots), np.int32)
        active = 0
        for i, req in enumerate(self.slots):
            if req is not None:
                pos[i] = req.pos
                tok[i] = req.next_token
                active += 1
        t_step = time.perf_counter()
        self._kv, logits = self.decoder.decode(self._kv, pos, tok)
        self.step_count += 1
        # one batched decode-step span per step (worker thread): a
        # request's share of it is bracketed by its first_token_step /
        # finish_step counters
        _trace.TRACER.complete("generate.decode_step", t_step,
                               time.perf_counter() - t_step,
                               step=self.step_count, active=active)
        now = time.monotonic()
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            # cancel/deadline between steps: retire without sampling
            if req.stream.cancelled or (req.deadline is not None and
                                        now > req.deadline):
                self._retire_if_done(req, i, now)
                continue
            req.pos += 1
            token = req.sampler.sample(logits[i])
            req.next_token = token
            self._emit_token(req, token)
            self._retire_if_done(req, i, now)

    def _fail_active(self, exc: Exception) -> None:
        """A decode-loop crash poisons every in-flight stream (their
        cache state is unknowable mid-step) — each gets its error
        sentinel and the worker keeps serving the queue."""
        for i, req in enumerate(self.slots):
            if req is not None:
                self._finish(req, {"error": f"decode failed: {exc!r}",
                                   "done": True})
                self.slots[i] = None

    def _flush_pending(self, exc: Exception) -> None:
        while True:
            with self._cond:
                if not self._pending:
                    return
                req = self._pending.pop(0)
            self._finish(req, {"error": str(exc), "done": True})

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and \
                        all(s is None for s in self.slots) and \
                        not self._closing:
                    self._cond.wait()
                closing = self._closing
                drain = self._drain
            if closing and not drain:
                self._fail_active(QueueFull("generate batcher shut down"))
                self._flush_pending(QueueFull("generate batcher shut "
                                              "down"))
                return
            try:
                self._admit()
                if any(s is not None for s in self.slots):
                    self._step()
            except Exception as exc:  # noqa: BLE001 — the worker must
                # outlive anything one decode step can throw
                self.error(f"decode step crashed: {exc!r}")
                self._fail_active(exc)
            with self._cond:
                active = sum(s is not None for s in self.slots)
                queued = len(self._pending)
            self.metrics.on_slots(active, queued)
            if closing and active == 0 and queued == 0:
                return

    # -- lifecycle -----------------------------------------------------------
    def stop(self, drain: bool = True,
             join_timeout_s: float = 30.0) -> bool:
        """Stop admitting.  ``drain=True`` decodes everything admitted
        to completion; ``drain=False`` fails queued and active requests
        loudly.  Returns True when the worker exited in time."""
        with self._cond:
            self._closing = True
            self._drain = drain
            self._cond.notify_all()
        self._worker.join(timeout=join_timeout_s)
        _flight.unregister_plane("generate_ledger", self._flight_plane)
        return not self._worker.is_alive()

    def __enter__(self) -> "ContinuousBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)
