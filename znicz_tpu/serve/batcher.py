"""Dynamic micro-batcher — the admission half of the serving plane.

The device sustains throughput only when requests arrive in batches, but
clients arrive one at a time; the batcher decouples the two the way the
reference decoupled libVeles inference from the master process.  A
bounded queue feeds a single worker that coalesces concurrent requests
into one engine batch, up to ``engine.max_batch`` rows or ``max_wait_ms``
after the first request of the batch — the classic
latency/utilization knob.

Contract (every admitted request gets exactly one response):

- **backpressure**: a full queue rejects at submit time with
  :class:`QueueFull` — a fast 503, never a silent drop or an unbounded
  queue;
- **deadlines**: a request whose deadline lapses while queued fails with
  :class:`DeadlineExceeded` at service time — a loud timeout, never a
  stale answer;
- **oversize chunking**: a request larger than ``max_batch`` is split
  into chunks that ride separate engine batches and is reassembled in
  submission order before the response resolves;
- **graceful drain**: ``stop(drain=True)`` rejects new arrivals but
  services everything already admitted before the worker exits.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import trace as _trace
from znicz_tpu.observe.federation import next_request_id, request_track
from znicz_tpu.serve.metrics import ServingMetrics


class QueueFull(RuntimeError):
    """Backpressure: the bounded queue has no room (HTTP 503)."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline lapsed before service (HTTP 504)."""


class _Request:
    """One client request; ``parts`` collects per-chunk outputs."""

    __slots__ = ("future", "deadline", "t_submit", "parts", "remaining",
                 "failed", "rid", "t0_perf")

    def __init__(self, n_chunks: int, deadline, t_submit: float,
                 rid: str) -> None:
        self.future: Future = Future()
        self.deadline = deadline            # monotonic stamp or None
        self.t_submit = t_submit
        self.parts: list = [None] * n_chunks
        self.remaining = n_chunks
        self.failed = False
        self.rid = rid                      # trace correlation key
        self.t0_perf = time.perf_counter()  # admission span anchor


class _Chunk:
    __slots__ = ("req", "index", "x")

    def __init__(self, req: _Request, index: int, x: np.ndarray) -> None:
        self.req = req
        self.index = index
        self.x = x


class MicroBatcher(Logger):
    """Coalesce concurrent requests into engine batches.

    ``engine``: a :class:`znicz_tpu.serve.engine.BatchEngine` (or any
    object with ``max_batch``, ``input_shape`` and ``run(x)``).
    ``max_wait_ms``: how long the worker holds an underfull batch open
    for stragglers.  ``max_queue``: queue bound in chunks — admission
    beyond it fails fast.  ``default_timeout_s``: per-request deadline
    when ``submit`` gets none.
    """

    def __init__(self, engine, max_wait_ms: float = 2.0,
                 max_queue: int = 128, default_timeout_s: float = 30.0,
                 metrics: ServingMetrics | None = None) -> None:
        super().__init__()
        self.engine = engine
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closing = False
        # flight artifacts embed the predict plane's admission ledger
        # too (ISSUE 11 satellite; see ContinuousBatcher)
        self._flight_plane = self.metrics.snapshot
        _flight.register_plane("serve_ledger", self._flight_plane)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="micro-batcher")
        self._worker.start()

    @property
    def draining(self) -> bool:
        """True once stop() began: no new admissions (healthz surfaces
        this as 503 "draining" so load balancers bleed traffic off)."""
        return self._closing

    # -- client side ---------------------------------------------------------
    def submit(self, x, timeout_s: float | None = None,
               request_id: str | None = None) -> Future:
        """Admit one request; returns a Future resolving to the output
        rows in submission order.  Raises :class:`QueueFull` immediately
        under backpressure or during drain.  ``request_id`` threads an
        HTTP-admission trace id through (one minted otherwise)."""
        x = np.ascontiguousarray(x, np.float32)
        if x.ndim == 1:
            x = x[None]
        shape = getattr(self.engine, "input_shape", None)
        if shape is not None and x.shape[1:] != tuple(shape):
            raise ValueError(f"input shape {x.shape[1:]} != model input "
                             f"{tuple(shape)}")
        if x.shape[0] == 0:
            raise ValueError("empty batch")
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got {timeout_s}")
        now = time.monotonic()
        deadline = now + timeout_s if timeout_s is not None else None
        step = self.engine.max_batch
        n_chunks = (x.shape[0] + step - 1) // step
        if n_chunks > self.max_queue:
            # not backpressure: this request can NEVER be admitted, so
            # a retryable QueueFull would mislead — fail as bad input
            raise ValueError(
                f"request of {x.shape[0]} rows needs {n_chunks} chunks, "
                f"more than the whole queue ({self.max_queue}); raise "
                "max_queue/max_batch or split the request")
        req = _Request(n_chunks=n_chunks, deadline=deadline, t_submit=now,
                       rid=request_id or next_request_id())
        chunks = [_Chunk(req, i, x[o:o + step])
                  for i, o in enumerate(range(0, x.shape[0], step))]
        with self._cond:
            if self._closing:
                self.metrics.on_reject()
                raise QueueFull("batcher is draining")
            if len(self._queue) + len(chunks) > self.max_queue:
                self.metrics.on_reject()
                raise QueueFull(
                    f"queue full ({len(self._queue)}/{self.max_queue})")
            self._queue.extend(chunks)
            self.metrics.on_admit(len(chunks))
            self._cond.notify_all()
        return req.future

    def predict(self, x, timeout_s: float | None = None) -> np.ndarray:
        """Blocking convenience: submit and wait for the response."""
        return self.submit(x, timeout_s=timeout_s).result()

    # -- worker side ---------------------------------------------------------
    def _fail(self, req: _Request, exc: Exception) -> None:
        if not req.failed:
            req.failed = True
            # the ONE place requests terminally fail — counted per
            # REQUEST (not per chunk/batch), so the admission ledger
            # closes exactly: admitted == completed + failed
            self.metrics.on_request_failed()
            try:
                req.future.set_exception(exc)
            except Exception:   # client cancelled the future: gone, fine
                pass

    def _take(self, now: float, capacity: int | None = None):
        """Pop the next serviceable chunk under the lock; expired
        requests fail loudly here (the only place chunks leave the
        queue).  Returns None when the queue is empty or when the next
        serviceable chunk would not fit ``capacity`` rows (that chunk
        stays queued for the next batch)."""
        while self._queue:
            chunk = self._queue[0]
            req = chunk.req
            expired = req.deadline is not None and now > req.deadline
            if req.failed or expired:   # sibling timed out / deadline
                self._queue.popleft()
                self.metrics.on_dequeue()
                if expired and not req.failed:
                    self.metrics.on_timeout()
                    self._fail(req, DeadlineExceeded(
                        f"deadline lapsed after "
                        f"{now - req.t_submit:.3f}s in queue"))
                continue
            if capacity is not None and len(chunk.x) > capacity:
                return None             # would overflow the batch
            self._queue.popleft()
            self.metrics.on_dequeue()
            return chunk
        return None

    def _gather(self):
        """Block for the first chunk, then coalesce stragglers up to
        ``max_batch`` rows or ``max_wait_ms``.  Returns (chunks, rows),
        or (None, 0) when closing with an empty queue."""
        with self._cond:
            while True:
                chunk = self._take(time.monotonic())
                if chunk is not None:
                    break
                if self._closing:
                    return None, 0
                self._cond.wait()   # submit()/stop() notify_all
            batch = [chunk]
            rows = len(chunk.x)
            hold_until = time.monotonic() + self.max_wait_ms / 1000.0
            while rows < self.engine.max_batch:
                now = time.monotonic()
                if self._queue:
                    chunk = self._take(now, self.engine.max_batch - rows)
                    if chunk is not None:
                        batch.append(chunk)
                        rows += len(chunk.x)
                        continue
                    if self._queue:
                        break           # next chunk would overflow
                    continue            # queue drained by expiry; recheck
                if self._closing or now >= hold_until:
                    break
                self._cond.wait(hold_until - now)
            return batch, rows

    def _service(self, batch: list, rows: int) -> None:
        self.metrics.on_batch(rows)
        t_infer = time.perf_counter()
        try:
            # concatenate inside the guard: with no engine input_shape
            # declared, mismatched per-request widths surface here and
            # must fail the batch, not the worker
            x = batch[0].x if len(batch) == 1 else \
                np.concatenate([c.x for c in batch], axis=0)
            y = self.engine.run(x)
        except Exception as exc:  # noqa: BLE001 — fail the batch, serve on
            self.metrics.on_error()
            self.error(f"engine failed on batch of {rows}: {exc!r}")
            for chunk in batch:
                self._fail(chunk.req, exc)
            return
        now = time.monotonic()
        now_perf = time.perf_counter()
        # one engine-dispatch span per coalesced batch (worker thread —
        # strictly sequential, so batch spans nest cleanly)
        _trace.TRACER.complete("serve.infer", t_infer,
                               now_perf - t_infer, rows=rows,
                               chunks=len(batch))
        offset = 0
        for chunk in batch:
            n = len(chunk.x)
            req = chunk.req
            req.parts[chunk.index] = y[offset:offset + n]
            offset += n
            req.remaining -= 1
            if req.remaining == 0 and not req.failed:
                out = req.parts[0] if len(req.parts) == 1 else \
                    np.concatenate(req.parts, axis=0)
                try:
                    req.future.set_result(out)
                except Exception:   # cancelled mid-service: the worker
                    # must outlive any client's Future — and the ledger
                    # must still close: a cancelled request reached its
                    # terminal state (the client walked away), so it
                    # counts failed, keeping admitted == completed +
                    # failed exact
                    req.failed = True
                    self.metrics.on_request_failed()
                    continue
                self.metrics.on_complete(now - req.t_submit)
                # whole-request span (admission -> response resolved)
                # on the request's own trace track
                _trace.TRACER.complete(
                    "serve.request", req.t0_perf,
                    time.perf_counter() - req.t0_perf,
                    tid=request_track(req.rid), rid=req.rid,
                    chunks=len(req.parts))

    def _loop(self) -> None:
        while True:
            batch, rows = self._gather()
            if batch is None:
                return
            try:
                self._service(batch, rows)
            except Exception as exc:  # noqa: BLE001 — the worker must
                # outlive anything a batch can throw (reassembly bugs,
                # metric sinks); affected requests fail loudly instead
                self.error(f"batch service crashed: {exc!r}")
                for chunk in batch:
                    self._fail(chunk.req, exc)

    # -- lifecycle -----------------------------------------------------------
    def stop(self, drain: bool = True, join_timeout_s: float = 30.0) -> bool:
        """Stop admitting.  ``drain=True`` services everything already
        queued; ``drain=False`` fails queued requests with QueueFull.
        Returns True when the worker actually exited — False means the
        drain outlived ``join_timeout_s`` and the worker is still going
        (callers must not tear down the engine underneath it)."""
        with self._cond:
            self._closing = True
            if not drain:
                while self._queue:
                    chunk = self._queue.popleft()
                    self.metrics.on_dequeue()
                    self._fail(chunk.req, QueueFull("batcher shut down"))
            self._cond.notify_all()
        self._worker.join(timeout=join_timeout_s)
        _flight.unregister_plane("serve_ledger", self._flight_plane)
        return not self._worker.is_alive()

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)
