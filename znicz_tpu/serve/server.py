"""HTTP front end + CLI for the serving plane.

The reference exposed trained models through a RESTful endpoint
(veles/loader/restful.py) backed by the libZnicz C++ runtime; this is
the production-shaped rebuild: requests enter a bounded queue, the
micro-batcher coalesces them into bucketed engine batches, and the
telemetry needed to operate the thing is one GET away.

    POST /predict   {"input": [[...], ...], "timeout_s": 5}
                    -> 200 {"output": [...]}
                    |  400 bad request  | 503 queue full (backpressure)
                    |  504 deadline exceeded
    GET  /metrics   -> serving + engine counters (metrics.py schema)
    GET  /healthz   -> {"status": "ok"}  (200 while accepting traffic)
    GET  /          -> model metadata (PredictionServer-compatible)

CLI:  python -m znicz_tpu serve <package.npz> [--port N] [--max-batch N]
          [--max-wait-ms F] [--max-queue N] [--native] [--no-warmup]
          [--no-aot]

A package carrying ahead-of-time executables (``python -m znicz_tpu
aot``, docs/COMPILE.md) boots with ``compile_count == 0`` when its
backend fingerprint matches this host; otherwise the loader logs the
mismatch and warmup JIT-compiles each bucket through the persistent
compilation cache as before.
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.serve.batcher import DeadlineExceeded, MicroBatcher, QueueFull
from znicz_tpu.serve.engine import BatchEngine, load_backend


class ServeServer(Logger):
    """The assembled serving plane: engine + batcher + HTTP."""

    def __init__(self, model, port: int = 0, max_batch: int | None = None,
                 max_wait_ms: float = 2.0, max_queue: int = 128,
                 default_timeout_s: float = 30.0,
                 warmup: bool = True) -> None:
        super().__init__()
        if isinstance(model, BatchEngine):
            if max_batch is not None and max_batch != model.max_batch:
                raise ValueError(
                    f"max_batch={max_batch} conflicts with the supplied "
                    f"engine's max_batch={model.max_batch}; configure it "
                    "on the engine")
            self.engine = model
        else:
            self.engine = BatchEngine(
                model, max_batch=64 if max_batch is None else max_batch)
        if warmup and self.engine.input_shape is not None:
            self.engine.warmup()
        self.batcher = MicroBatcher(self.engine, max_wait_ms=max_wait_ms,
                                    max_queue=max_queue,
                                    default_timeout_s=default_timeout_s)
        self.metrics = self.batcher.metrics
        self.port = int(port)
        self._httpd = None
        self._thread = None

    # -- payloads ------------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """Serving + engine counters — the ``GET /metrics`` document,
        also registered into web_status.py's ``/status.json``."""
        return {"serving": self.metrics.snapshot(),
                "engine": self.engine.stats()}

    def meta_snapshot(self) -> dict:
        return {"model": self.engine.meta,
                "n_requests": self.metrics.admitted,
                "max_batch": self.engine.max_batch}

    # -- HTTP ----------------------------------------------------------------
    def start(self) -> int:
        plane = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, doc: dict, headers=()) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    self._reply(200, plane.metrics_snapshot())
                elif self.path.startswith("/healthz"):
                    draining = plane.batcher.draining
                    self._reply(503 if draining else 200,
                                {"status": "draining" if draining
                                 else "ok"})
                else:
                    self._reply(200, plane.meta_snapshot())

            def do_POST(self):
                if not self.path.startswith("/predict"):
                    self._reply(404, {"error": "POST /predict"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n))
                    future = plane.batcher.submit(
                        doc["input"], timeout_s=doc.get("timeout_s"))
                except QueueFull as exc:
                    self._reply(503, {"error": str(exc)},
                                headers=(("Retry-After", "1"),))
                    return
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                try:
                    out = future.result()
                except DeadlineExceeded as exc:
                    self._reply(504, {"error": str(exc)})
                    return
                except QueueFull as exc:    # non-drain shutdown flushed it
                    self._reply(503, {"error": str(exc)},
                                headers=(("Retry-After", "1"),))
                    return
                except Exception as exc:  # noqa: BLE001 — engine failure
                    self._reply(500, {"error": str(exc)})
                    return
                self._reply(200, {"output": np.asarray(out).tolist()})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()
        self.info(f"serving on http://127.0.0.1:{self.port}/ "
                  f"(buckets {list(self.engine.buckets)})")
        return self.port

    def stop(self, drain: bool = True) -> None:
        """Graceful shutdown, in load-balancer-observable order: the
        batcher drains FIRST — while it does, ``/healthz`` answers 503
        "draining" and new ``/predict`` admissions get 503 QueueFull —
        then the listener closes, and the engine backend is released
        only if the drain actually finished (a worker still grinding
        through the queue must not lose its backend mid-batch)."""
        drained = self.batcher.stop(drain=drain)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if drained:
            self.engine.close()
        else:
            self.warning("drain still in progress past the join timeout;"
                         " leaving the engine open for the worker")


# -- CLI ---------------------------------------------------------------------

def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="znicz_tpu serve",
        description="serve a forward package over HTTP with dynamic "
                    "micro-batching")
    p.add_argument("package", help="path to a utils/export.py .npz package")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port (0 picks a free one)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="largest coalesced batch (bucket ceiling)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="how long an underfull batch waits for stragglers")
    p.add_argument("--max-queue", type=int, default=128,
                   help="queue bound in chunks; beyond it -> 503")
    p.add_argument("--timeout-s", type=float, default=30.0,
                   help="default per-request deadline")
    p.add_argument("--native", action="store_true",
                   help="serve through the C++ runtime (no JAX in the "
                        "request path) when buildable")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-compiling the batch buckets")
    p.add_argument("--no-aot", action="store_true",
                   help="ignore embedded ahead-of-time executables and "
                        "JIT every bucket (docs/COMPILE.md)")
    p.add_argument("--smoke-test", action="store_true",
                   help="start, serve one self-request, exit (CI probe)")
    return p


def serve_main(argv) -> int:
    args = build_serve_parser().parse_args(argv)
    try:
        backend = load_backend(args.package, prefer_native=args.native,
                               aot=not args.no_aot)
    except (OSError, ValueError, RuntimeError) as exc:
        print(f"serve: cannot load {args.package!r}: {exc}")
        return 2
    server = ServeServer(backend, port=args.port, max_batch=args.max_batch,
                         max_wait_ms=args.max_wait_ms,
                         max_queue=args.max_queue,
                         default_timeout_s=args.timeout_s,
                         warmup=not args.no_warmup)
    port = server.start()
    if args.smoke_test:
        import urllib.request

        shape = server.engine.input_shape or (1,)
        x = np.zeros((2,) + tuple(shape), np.float32)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps({"input": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        ok = len(out["output"]) == 2
        print(json.dumps({"smoke": "ok" if ok else "bad",
                          "port": port,
                          "metrics": server.metrics_snapshot()}))
        server.stop()
        return 0 if ok else 1
    # serve until SIGTERM (docker/k8s stop) or Ctrl-C — both drain
    done = threading.Event()
    import signal

    prev = signal.signal(signal.SIGTERM, lambda *a: done.set())
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev)
    print("serve: draining...")
    server.stop()
    return 0
