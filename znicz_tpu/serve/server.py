"""HTTP front end + CLI for the serving plane.

The reference exposed trained models through a RESTful endpoint
(veles/loader/restful.py) backed by the libZnicz C++ runtime; this is
the production-shaped rebuild: requests enter a bounded queue, the
micro-batcher coalesces them into bucketed engine batches, and the
telemetry needed to operate the thing is one GET away.

    POST /predict   {"input": [[...], ...], "timeout_s": 5}
                    -> 200 {"output": [...]}
                    |  400 bad request  | 503 queue full (backpressure)
                    |  504 deadline exceeded
    GET  /metrics       -> serving + engine counters (metrics.py schema)
    GET  /metrics.prom  -> process registry, Prometheus text (the fleet
                           aggregator's scrape target, ISSUE 11)
    GET  /trace.json    -> this worker's span ring, rank-anchored for
                           the fleet trace merge
    GET  /healthz   -> {"status": "ok"}  (200 while accepting traffic)
    GET  /livez     -> 200 while the process serves HTTP at all (the
                       fleet router's restart probe, ISSUE 13)
    GET  /readyz    -> 200 ready + package fingerprint | 503 draining
                       (the fleet router's routing gate)
    GET  /          -> model metadata (PredictionServer-compatible)

CLI:  python -m znicz_tpu serve <package.npz> [--port N] [--max-batch N]
          [--max-wait-ms F] [--max-queue N] [--native] [--no-warmup]
          [--no-aot]

A package carrying ahead-of-time executables (``python -m znicz_tpu
aot``, docs/COMPILE.md) boots with ``compile_count == 0`` when its
backend fingerprint matches this host; otherwise the loader logs the
mismatch and warmup JIT-compiles each bucket through the persistent
compilation cache as before.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import trace as _trace
from znicz_tpu.observe.federation import next_request_id, request_track
from znicz_tpu.serve.batcher import DeadlineExceeded, MicroBatcher, QueueFull
from znicz_tpu.serve.engine import BatchEngine, load_backend


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared HTTP scaffolding for both serving planes: silent access
    log, one JSON reply helper, one healthz shape — the predict and
    generate front ends must never drift on the envelope load balancers
    and scrapers read."""

    def log_message(self, *args):
        pass

    def _reply(self, code: int, doc: dict, headers=()) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_healthz(self, draining: bool) -> None:
        self._reply(503 if draining else 200,
                    {"status": "draining" if draining else "ok"})

    # -- liveness vs readiness (ISSUE 13) ------------------------------------
    # The fleet router routes on READINESS and restarts on LIVENESS,
    # the two questions k8s-style probes keep distinct: a draining or
    # mid-reboot worker is alive (do not replace it) but must stop
    # receiving traffic before its drain completes.  /healthz keeps its
    # historical shape (alive-and-accepting) for existing monitors.
    def _reply_livez(self) -> None:
        """``GET /livez``: 200 while the process serves HTTP at all —
        draining included.  Only a dead listener fails this probe."""
        self._reply(200, {"status": "ok"})

    def _reply_readyz(self, draining: bool, package=None) -> None:
        """``GET /readyz``: 200 only while this worker should receive
        NEW traffic; carries the package fingerprint so a rolling
        weight update can gate on what the worker actually serves."""
        doc = {"status": "draining" if draining else "ready"}
        if package is not None:
            doc["package"] = package
        self._reply(503 if draining else 200, doc)

    def _request_id(self) -> str:
        """The request's trace id: honor an ``X-Request-Id`` minted
        upstream (the fleet router's router->worker correlation key,
        ISSUE 13) so every phase span of one request shares a track
        across processes; mint one only at the true admission edge."""
        return self.headers.get("X-Request-Id") or next_request_id()

    def _reply_prom(self) -> None:
        """``GET /metrics.prom``: the process-global registry in
        Prometheus text — the fleet aggregator's scrape target on BOTH
        serving planes (ISSUE 11)."""
        from znicz_tpu.observe import REGISTRY

        body = REGISTRY.render_prometheus().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_trace(self) -> None:
        """``GET /trace.json``: this worker's tracer ring (request
        phase spans included), rank-anchored so
        ``federation.merge_traces`` / ``/fleet/trace.json`` can align
        it with its peers."""
        from znicz_tpu.observe import TRACER

        self._reply(200, TRACER.export_dict())


class ServeServer(Logger):
    """The assembled serving plane: engine + batcher + HTTP."""

    def __init__(self, model, port: int = 0, max_batch: int | None = None,
                 max_wait_ms: float = 2.0, max_queue: int = 128,
                 default_timeout_s: float = 30.0,
                 warmup: bool = True, package_info: dict | None = None,
                 feedback=None) -> None:
        super().__init__()
        #: content fingerprint of the package this worker booted from
        #: (utils/naming.py package_fingerprint) — served on /readyz so
        #: rolling weight updates can verify adoption (ISSUE 13)
        self.package_info = package_info
        #: learn-plane spool (ISSUE 14): answered predictions append as
        #: labeled (input, output) pairs with request-id provenance
        self.feedback = feedback
        if isinstance(model, BatchEngine):
            if max_batch is not None and max_batch != model.max_batch:
                raise ValueError(
                    f"max_batch={max_batch} conflicts with the supplied "
                    f"engine's max_batch={model.max_batch}; configure it "
                    "on the engine")
            self.engine = model
        else:
            self.engine = BatchEngine(
                model, max_batch=64 if max_batch is None else max_batch)
        if warmup and self.engine.input_shape is not None:
            self.engine.warmup()
        self.batcher = MicroBatcher(self.engine, max_wait_ms=max_wait_ms,
                                    max_queue=max_queue,
                                    default_timeout_s=default_timeout_s)
        self.metrics = self.batcher.metrics
        self.port = int(port)
        self._httpd = None
        self._thread = None

    # -- payloads ------------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """Serving + engine counters — the ``GET /metrics`` document,
        also registered into web_status.py's ``/status.json``."""
        return {"serving": self.metrics.snapshot(),
                "engine": self.engine.stats()}

    def meta_snapshot(self) -> dict:
        return {"model": self.engine.meta,
                "n_requests": self.metrics.admitted,
                "max_batch": self.engine.max_batch,
                "package": self.package_info}

    # -- HTTP ----------------------------------------------------------------
    def start(self) -> int:
        plane = self

        class Handler(_JsonHandler):
            def do_GET(self):
                if self.path.startswith("/metrics.prom"):
                    self._reply_prom()
                elif self.path.startswith("/metrics"):
                    self._reply(200, plane.metrics_snapshot())
                elif self.path.startswith("/trace.json"):
                    self._reply_trace()
                elif self.path.startswith("/livez"):
                    self._reply_livez()
                elif self.path.startswith("/readyz"):
                    self._reply_readyz(plane.batcher.draining,
                                       plane.package_info)
                elif self.path.startswith("/healthz"):
                    self._reply_healthz(plane.batcher.draining)
                else:
                    self._reply(200, plane.meta_snapshot())

            def do_POST(self):
                if not self.path.startswith("/predict"):
                    self._reply(404, {"error": "POST /predict"})
                    return
                rid = self._request_id()     # router-minted or admission
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n))
                    future = plane.batcher.submit(
                        doc["input"], timeout_s=doc.get("timeout_s"),
                        request_id=rid)
                except QueueFull as exc:
                    self._reply(503, {"error": str(exc)},
                                headers=(("Retry-After", "1"),))
                    return
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                try:
                    out = future.result()
                except DeadlineExceeded as exc:
                    self._reply(504, {"error": str(exc)})
                    return
                except QueueFull as exc:    # non-drain shutdown flushed it
                    self._reply(503, {"error": str(exc)},
                                headers=(("Retry-After", "1"),))
                    return
                except Exception as exc:  # noqa: BLE001 — engine failure
                    self._reply(500, {"error": str(exc)})
                    return
                out_rows = np.asarray(out).tolist()
                if plane.feedback is not None:
                    try:
                        plane.feedback.append_predict(
                            rid, doc["input"], out_rows)
                    except Exception as exc:  # noqa: BLE001 — feedback
                        plane.warning(     # must never fail a request
                            f"feedback append failed: {exc!r}")
                self._reply(200, {"output": out_rows},
                            headers=(("X-Request-Id", rid),))

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()
        self.info(f"serving on http://127.0.0.1:{self.port}/ "
                  f"(buckets {list(self.engine.buckets)})")
        return self.port

    def stop(self, drain: bool = True) -> None:
        """Graceful shutdown, in load-balancer-observable order: the
        batcher drains FIRST — while it does, ``/healthz`` answers 503
        "draining" and new ``/predict`` admissions get 503 QueueFull —
        then the listener closes, and the engine backend is released
        only if the drain actually finished (a worker still grinding
        through the queue must not lose its backend mid-batch)."""
        drained = self.batcher.stop(drain=drain)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if drained:
            self.engine.close()
        else:
            self.warning("drain still in progress past the join timeout;"
                         " leaving the engine open for the worker")


# -- generative serving plane (ISSUE 10) -------------------------------------

def encode_chars(text: str, charmap) -> list:
    """THE charmap text encoder (id <- character), shared by the HTTP
    front end and the CLI so out-of-vocab handling cannot drift: every
    character must be in the model's vocab — unknown characters fail
    loudly instead of aliasing to id 0."""
    stoi = {c: i for i, c in enumerate(charmap)}
    missing = sorted({c for c in text if c not in stoi})
    if missing:
        raise ValueError(f"prompt contains characters outside the "
                         f"model vocab: {missing[:8]!r}")
    return [stoi[c] for c in text]

class GenerateServer(Logger):
    """The assembled generative plane: KV-cache decoder + continuous
    batcher + streaming HTTP.

    ::

        POST /generate  {"prompt": "text"} | {"tokens": [ids]},
                        "max_tokens": 32, "temperature": 0.0,
                        "top_k": 0, "seed": 0, "timeout_s": 60,
                        "stream": true
            -> 200 ndjson stream: {"token": id[, "text": "c"]} per
               token, then EXACTLY ONE terminal line — {"done": true,
               "reason": "length", "n_tokens": N} or the error sentinel
               {"error": "...", "done": true} (a stream NEVER just goes
               quiet — the chaos drill pins this)
            |  200 single JSON document with "stream": false
            |  400 bad input | 503 queue full | 504 deadline (non-
               stream mode; streamed deadlines arrive as the sentinel)
        GET  /metrics       -> {"generate": ..., "decoder": ...}
        GET  /metrics.prom  -> process registry, Prometheus text
        GET  /trace.json    -> span ring incl. per-request phase spans
                               (queue/prefill/decode/stream, linked by
                               request id on one synthetic track)
        GET  /healthz       -> 200 ok | 503 draining
        GET  /livez         -> 200 while the process serves HTTP
        GET  /readyz        -> 200 ready + package fingerprint
                               | 503 draining (router routing gate)
        GET  /              -> model metadata

    ``charmap`` (id -> character, from the LM package) enables text
    prompts and per-token ``"text"`` fields; tokens-only models speak
    raw ids.
    """

    def __init__(self, batcher, charmap=None, port: int = 0,
                 name: str = "lm", package_info: dict | None = None) -> None:
        super().__init__()
        #: /readyz fingerprint, same contract as ServeServer (ISSUE 13)
        self.package_info = package_info
        self.batcher = batcher
        self.decoder = batcher.decoder
        self.metrics = batcher.metrics
        self.name = name
        self.charmap = list(charmap) if charmap else None
        self.port = int(port)
        self._httpd = None
        self._thread = None

    # -- text codec ----------------------------------------------------------
    def encode(self, text: str) -> list:
        if self.charmap is None:
            raise ValueError("this model has no charmap; send "
                             "{\"tokens\": [...]} instead of a text "
                             "prompt")
        return encode_chars(text, self.charmap)

    def decode_text(self, ids) -> str:
        if self.charmap is None:
            return ""
        return "".join(self.charmap[i] for i in ids
                       if 0 <= i < len(self.charmap))

    # -- payloads ------------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        return {"generate": self.metrics.snapshot(),
                "decoder": self.decoder.stats()}

    def meta_snapshot(self) -> dict:
        return {"model": {"name": self.name, "kind": "lm",
                          "vocab": self.decoder.vocab,
                          "charmap": self.charmap is not None},
                "max_len": self.decoder.max_len,
                "slots": self.decoder.batch,
                "paged": bool(getattr(self.decoder, "paged", False)),
                "speculative": self.batcher._draft is not None,
                "package": self.package_info,
                "n_requests": self.metrics.snapshot()["admitted"]}

    def _submit_doc(self, doc: dict, request_id: str | None = None):
        """Parse one /generate body and admit it; returns the stream.
        Raises ValueError (400) / QueueFull (503)."""
        if "tokens" in doc:
            ids = [int(t) for t in doc["tokens"]]
        elif "prompt" in doc:
            ids = self.encode(str(doc["prompt"]))
        else:
            raise ValueError('body needs "prompt" or "tokens"')
        return self.batcher.submit(
            ids,
            max_new_tokens=int(doc.get("max_tokens", 32)),
            temperature=float(doc.get("temperature", 0.0)),
            top_k=int(doc.get("top_k", 0)),
            seed=int(doc.get("seed", 0)),
            timeout_s=doc.get("timeout_s"),
            request_id=request_id)

    # -- HTTP ----------------------------------------------------------------
    def start(self) -> int:
        plane = self

        class Handler(_JsonHandler):
            def do_GET(self):
                if self.path.startswith("/metrics.prom"):
                    self._reply_prom()
                elif self.path.startswith("/metrics"):
                    self._reply(200, plane.metrics_snapshot())
                elif self.path.startswith("/trace.json"):
                    self._reply_trace()
                elif self.path.startswith("/livez"):
                    self._reply_livez()
                elif self.path.startswith("/readyz"):
                    self._reply_readyz(plane.batcher.draining,
                                       plane.package_info)
                elif self.path.startswith("/healthz"):
                    self._reply_healthz(plane.batcher.draining)
                else:
                    self._reply(200, plane.meta_snapshot())

            def _slack(self, timeout_s) -> float:
                """How long to wait on the stream before declaring the
                worker wedged: the request's own deadline (explicit, or
                the batcher's configured default — NOT a hardcoded
                constant a --timeout-s flag would silently undercut)
                plus grace."""
                return (timeout_s or plane.batcher.default_timeout_s
                        or 60.0) + 30.0

            def _stream_events(self, stream, timeout_s) -> None:
                """ndjson relay: every event the batcher emits becomes
                one flushed line; a client that hangs up cancels the
                generation (abandoned-request accounting).  The relay
                itself is the request's ``generate.stream`` phase span
                — queue/prefill/decode cover the worker side, this one
                covers the wire."""
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("X-Request-Id", stream.request_id)
                self.end_headers()      # no Content-Length: close-delimited
                # terminal events are guaranteed; the slack only guards
                # a wedged worker from pinning this handler thread
                slack = self._slack(timeout_s)
                t_stream = time.perf_counter()
                n_events = 0
                try:
                    while True:
                        try:
                            event = stream.next_event(timeout=slack)
                        except TimeoutError:
                            # the client gets a terminal error NOW;
                            # cancel so a later-recovering worker frees
                            # the slot instead of decoding for a gone
                            # client
                            stream.cancel()
                            event = {"error": "stream stalled (worker "
                                     "unresponsive)", "done": True}
                        if "token" in event and plane.charmap is not None:
                            event = {**event, "text":
                                     plane.decode_text([event["token"]])}
                        try:
                            self.wfile.write(
                                (json.dumps(event) + "\n").encode())
                            self.wfile.flush()
                            n_events += 1
                        except (BrokenPipeError, ConnectionResetError,
                                OSError):
                            stream.cancel()  # client hung up: free the
                            return           # slot, count it abandoned
                        if event.get("done"):
                            return
                finally:
                    _trace.TRACER.complete(
                        "generate.stream", t_stream,
                        time.perf_counter() - t_stream,
                        tid=request_track(stream.request_id),
                        rid=stream.request_id, events=n_events)

            def do_POST(self):
                if not self.path.startswith("/generate"):
                    self._reply(404, {"error": "POST /generate"})
                    return
                rid = self._request_id()     # router-minted or admission
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n))
                    if not isinstance(doc, dict):
                        raise ValueError("body must be a JSON object")
                    stream = plane._submit_doc(doc, request_id=rid)
                except QueueFull as exc:
                    self._reply(503, {"error": str(exc)},
                                headers=(("Retry-After", "1"),))
                    return
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                if doc.get("stream", True):
                    self._stream_events(stream, doc.get("timeout_s"))
                    return
                from znicz_tpu.serve.continuous import GenerationError
                try:
                    ids = stream.result(
                        timeout_s=self._slack(doc.get("timeout_s")))
                except GenerationError as exc:
                    code = 504 if "deadline" in str(exc) else 500
                    self._reply(code, {"error": str(exc),
                                       "n_tokens": len(stream.tokens)})
                    return
                except TimeoutError as exc:
                    stream.cancel()     # free the slot for a client
                    self._reply(500, {"error": str(exc)})  # that's gone
                    return
                self._reply(200, {"tokens": ids,
                                  "text": plane.decode_text(ids),
                                  "reason": "length",
                                  "n_tokens": len(ids),
                                  "request_id": stream.request_id},
                            headers=(("X-Request-Id",
                                      stream.request_id),))

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="generate-http")
        self._thread.start()
        self.info(f"generating on http://127.0.0.1:{self.port}/ "
                  f"({self.decoder.batch} slots, max_len "
                  f"{self.decoder.max_len})")
        return self.port

    def stop(self, drain: bool = True) -> None:
        """Same load-balancer-observable order as ``ServeServer``: the
        batcher drains first (healthz says 503 draining, new /generate
        admissions 503), then the listener closes."""
        self.batcher.stop(drain=drain)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# -- CLI ---------------------------------------------------------------------

def build_generate_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="znicz_tpu generate",
        description="generate tokens from an LM package — one-shot to "
                    "stdout, or a streaming HTTP server with "
                    "continuous batching")
    p.add_argument("package", help="path to a utils/export.py LM "
                                   "package (export_lm / char_lm "
                                   "lm_export)")
    p.add_argument("--prompt", default=None,
                   help="text prompt (one-shot mode unless --serve)")
    p.add_argument("--tokens", default=None,
                   help="comma-separated token ids instead of --prompt")
    p.add_argument("--max-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples (seeded, reproducible)")
    p.add_argument("--top-k", type=int, default=0,
                   help="truncate sampling to the k most likely (0 = "
                        "full vocab)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-len", type=int, default=256,
                   help="cache-length ceiling (prompt + generation)")
    p.add_argument("--serve", action="store_true",
                   help="serve POST /generate with continuous batching "
                        "instead of a one-shot generation")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port (0 picks a free one)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode-batch width (concurrent generations)")
    p.add_argument("--max-queue", type=int, default=64,
                   help="requests waiting for a slot; beyond it -> 503")
    p.add_argument("--timeout-s", type=float, default=60.0,
                   help="default per-request deadline")
    p.add_argument("--no-paged", action="store_true",
                   help="serve from per-slot contiguous cache buckets "
                        "instead of the block-paged KV arena")
    p.add_argument("--page-size", type=int, default=16,
                   help="KV arena rows per page (paged serving)")
    p.add_argument("--arena-pages", type=int, default=0,
                   help="total KV arena pages shared by all slots "
                        "(0 = worst case: slots x max_len rows); "
                        "smaller values bank on the long tail and set "
                        "the real slot ceiling")
    p.add_argument("--speculative", action="store_true",
                   help="speculative decoding: the package's draft "
                        "model (or --draft-layers) proposes, the "
                        "target verifies — greedy output is "
                        "token-identical to plain decode")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens proposed per speculative round")
    p.add_argument("--draft-layers", type=int, default=0,
                   help="with --speculative and no draft in the "
                        "package: truncate the target to its first N "
                        "layers as the draft")
    p.add_argument("--pallas-decode", action="store_true",
                   help="route single-query decode attention through "
                        "the Pallas flash-decode kernel (interpret "
                        "mode off-TPU)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-compiling the cache buckets")
    p.add_argument("--feedback-spool", default=None, metavar="DIR",
                   help="append every COMPLETED generation (prompt + "
                        "continuation + request id) to this learn-"
                        "plane spool directory (docs/LEARNING.md) — "
                        "the train-while-serve feedback source")
    p.add_argument("--smoke-test", action="store_true",
                   help="start, stream one self-request, exit (CI "
                        "probe)")
    return p


def _parse_prompt(args, charmap) -> list:
    if args.tokens is not None:
        return [int(t) for t in args.tokens.split(",") if t.strip()]
    if args.prompt is None:
        raise ValueError("need --prompt or --tokens")
    if not charmap:
        raise ValueError("this package has no charmap; use --tokens")
    return encode_chars(args.prompt, charmap)


def generate_main(argv) -> int:
    from znicz_tpu.serve.continuous import ContinuousBatcher
    from znicz_tpu.serve.kvcache import KVDecoder, TokenSampler
    from znicz_tpu.utils.export import load_lm

    args = build_generate_parser().parse_args(argv)
    try:
        params, meta = load_lm(args.package)
    except (OSError, ValueError, KeyError) as exc:
        print(f"generate: cannot load {args.package!r}: {exc}")
        return 2
    charmap = meta.get("charmap")
    serve_mode = args.serve or args.smoke_test
    if not serve_mode:
        # one-shot: stream the generation to stdout as it decodes
        try:
            ids = _parse_prompt(args, charmap)
            decoder = KVDecoder(params, heads=meta["heads"],
                                max_len=args.max_len, batch=1)
            sampler = TokenSampler(seed=args.seed,
                                   temperature=args.temperature,
                                   top_k=args.top_k)

            def on_token(tok: int) -> None:
                if charmap:
                    print(charmap[tok], end="", flush=True)
                else:
                    print(tok, end=" ", flush=True)

            out = decoder.generate(ids, args.max_tokens, sampler,
                                   on_token=on_token)
        except ValueError as exc:
            print(f"generate: {exc}")
            return 2
        print()
        print(json.dumps({"n_tokens": len(out),
                          "prompt_tokens": len(ids),
                          "decoder": decoder.stats()}),
              file=__import__("sys").stderr)
        return 0
    draft = None
    if args.no_paged:
        if args.speculative:
            print("generate: --speculative needs the paged arena "
                  "(drop --no-paged)")
            return 2
        decoder = KVDecoder(params, heads=meta["heads"],
                            max_len=args.max_len, batch=args.slots)
        if not args.no_warmup:
            decoder.warmup()
    else:
        from znicz_tpu.serve.paged import PagedKVDecoder, truncate_draft
        from znicz_tpu.utils.export import load_lm_draft

        decoder = PagedKVDecoder(
            params, heads=meta["heads"], max_len=args.max_len,
            batch=args.slots, page=args.page_size,
            arena_pages=args.arena_pages or None,
            use_pallas=args.pallas_decode)
        if args.speculative:
            if args.spec_k < 1:
                print(f"generate: --spec-k must be >= 1, got "
                      f"{args.spec_k}")
                return 2
            dparams, dmeta = load_lm_draft(args.package)
            dheads = dmeta["heads"] if dmeta else meta["heads"]
            if dparams is None and args.draft_layers:
                dparams = truncate_draft(params, args.draft_layers)
            if dparams is None:
                print("generate: --speculative needs a draft model in "
                      "the package (export_lm draft_params=...) or "
                      "--draft-layers N")
                return 2
            # the draft's k+1 single-query steps per round ARE the
            # flash-decode shape — the kernel flag covers both decoders
            draft = PagedKVDecoder(
                dparams, heads=dheads, max_len=args.max_len,
                batch=args.slots, page=args.page_size,
                arena_pages=args.arena_pages or None,
                use_pallas=args.pallas_decode)
        if not args.no_warmup:
            decoder.warmup(spec_k=args.spec_k if args.speculative
                           else None)
            if draft is not None:
                draft.warmup()
    on_complete = None
    if args.feedback_spool:
        # the learn plane's traffic tap (ISSUE 14): completed
        # generations land in the crash-safe spool the trainer tails
        from znicz_tpu.learn.spool import FeedbackSpool

        on_complete = FeedbackSpool(args.feedback_spool).append_generate
    batcher = ContinuousBatcher(decoder, max_queue=args.max_queue,
                                default_timeout_s=args.timeout_s,
                                draft=draft, spec_k=args.spec_k,
                                on_complete=on_complete)
    from znicz_tpu.utils.naming import package_fingerprint

    server = GenerateServer(batcher, charmap=charmap, port=args.port,
                            name=meta.get("name", "lm"),
                            package_info=package_fingerprint(args.package))
    port = server.start()
    if args.smoke_test:
        import urllib.request

        body = {"max_tokens": 8, "temperature": 0.0}
        if charmap:
            body["prompt"] = charmap[0]
        else:
            body["tokens"] = [0]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        lines = []
        with urllib.request.urlopen(req, timeout=60) as r:
            for raw in r:
                lines.append(json.loads(raw))
        ok = len(lines) >= 2 and lines[-1].get("done") and \
            all("token" in ln for ln in lines[:-1])
        print(json.dumps({"smoke": "ok" if ok else "bad", "port": port,
                          "events": len(lines),
                          "metrics": server.metrics_snapshot()}))
        server.stop()
        return 0 if ok else 1
    done = threading.Event()
    import signal

    prev = signal.signal(signal.SIGTERM, lambda *a: done.set())
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    try:
        # the handler stays installed THROUGH the drain: restoring the
        # default first would let a second SIGTERM (an impatient
        # supervisor, a k8s double-signal) kill the worker mid-drain
        # and lose every request it had admitted
        print("generate: draining...")
        server.stop()
    finally:
        signal.signal(signal.SIGTERM, prev)
    return 0


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="znicz_tpu serve",
        description="serve a forward package over HTTP with dynamic "
                    "micro-batching")
    p.add_argument("package", help="path to a utils/export.py .npz package")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port (0 picks a free one)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="largest coalesced batch (bucket ceiling)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="how long an underfull batch waits for stragglers")
    p.add_argument("--max-queue", type=int, default=128,
                   help="queue bound in chunks; beyond it -> 503")
    p.add_argument("--timeout-s", type=float, default=30.0,
                   help="default per-request deadline")
    p.add_argument("--native", action="store_true",
                   help="serve through the C++ runtime (no JAX in the "
                        "request path) when buildable")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-compiling the batch buckets")
    p.add_argument("--no-aot", action="store_true",
                   help="ignore embedded ahead-of-time executables and "
                        "JIT every bucket (docs/COMPILE.md)")
    p.add_argument("--feedback-spool", default=None, metavar="DIR",
                   help="append every answered prediction (labeled "
                        "input/output pair + request id) to this "
                        "learn-plane spool directory (docs/LEARNING.md)")
    p.add_argument("--smoke-test", action="store_true",
                   help="start, serve one self-request, exit (CI probe)")
    return p


def serve_main(argv) -> int:
    args = build_serve_parser().parse_args(argv)
    try:
        backend = load_backend(args.package, prefer_native=args.native,
                               aot=not args.no_aot)
    except (OSError, ValueError, RuntimeError) as exc:
        print(f"serve: cannot load {args.package!r}: {exc}")
        return 2
    from znicz_tpu.utils.naming import package_fingerprint

    feedback = None
    if args.feedback_spool:
        from znicz_tpu.learn.spool import FeedbackSpool

        feedback = FeedbackSpool(args.feedback_spool)
    server = ServeServer(backend, port=args.port, max_batch=args.max_batch,
                         max_wait_ms=args.max_wait_ms,
                         max_queue=args.max_queue,
                         default_timeout_s=args.timeout_s,
                         warmup=not args.no_warmup,
                         package_info=package_fingerprint(args.package),
                         feedback=feedback)
    port = server.start()
    if args.smoke_test:
        import urllib.request

        shape = server.engine.input_shape or (1,)
        x = np.zeros((2,) + tuple(shape), np.float32)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps({"input": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        ok = len(out["output"]) == 2
        print(json.dumps({"smoke": "ok" if ok else "bad",
                          "port": port,
                          "metrics": server.metrics_snapshot()}))
        server.stop()
        return 0 if ok else 1
    # serve until SIGTERM (docker/k8s stop) or Ctrl-C — both drain
    done = threading.Event()
    import signal

    prev = signal.signal(signal.SIGTERM, lambda *a: done.set())
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    try:
        # handler stays installed through the drain (see generate_main)
        print("serve: draining...")
        server.stop()
    finally:
        signal.signal(signal.SIGTERM, prev)
    return 0
