"""znicz_tpu.serve — dynamic micro-batching inference runtime.

The serving plane between the export runtime (utils/export.py,
native/infer.py) and HTTP: a bounded request queue with backpressure
(batcher.py), a bucketed batch engine that never recompiles in steady
state (engine.py), serving telemetry (metrics.py), and the HTTP front
end + ``python -m znicz_tpu serve`` CLI (server.py).

Reference lineage: the veles stack split serving (libVeles/libZnicz +
RESTful loader) from training; this subsystem is that split rebuilt
throughput-first — device efficiency decoupled from client arrival
patterns by micro-batching, the way weight-update resharding decouples
optimizer cost from replica count.
"""

from znicz_tpu.serve.batcher import DeadlineExceeded, MicroBatcher, QueueFull
from znicz_tpu.serve.continuous import (ContinuousBatcher, GenerationError,
                                        TokenStream)
from znicz_tpu.serve.engine import BatchEngine, bucket_sizes, load_backend
from znicz_tpu.serve.kvcache import KVDecoder, TokenSampler
from znicz_tpu.serve.metrics import (GenerateMetrics, LatencyHistogram,
                                     ServingMetrics)
from znicz_tpu.serve.paged import (ArenaExhausted, PagedKVDecoder,
                                   PageLedger, truncate_draft)
from znicz_tpu.serve.server import (GenerateServer, ServeServer,
                                    generate_main, serve_main)

__all__ = [
    "ArenaExhausted", "BatchEngine", "ContinuousBatcher",
    "DeadlineExceeded", "GenerateMetrics", "GenerateServer",
    "GenerationError", "KVDecoder", "LatencyHistogram", "MicroBatcher",
    "PagedKVDecoder", "PageLedger", "QueueFull", "ServeServer",
    "ServingMetrics", "TokenSampler", "TokenStream", "bucket_sizes",
    "generate_main", "load_backend", "serve_main", "truncate_draft",
]
