"""KV-cache incremental decode — the device core of the generative
serving plane (ISSUE 10).

Autoregressive serving recomputes nothing: each request's attention
keys/values live in a preallocated device cache, ``prefill`` runs the
prompt once (filling the cache and yielding the first next-token
logits), and every subsequent token is one ``decode`` step that writes
a single cache row and attends over the rows written so far.  Cache
buffers are padded to power-of-two *cache-length buckets*
(``engine.bucket_sizes`` — the serve plane's one compile-shape policy),
so each program compiles once per bucket and steady-state decoding
triggers **zero** recompiles across mixed request lengths within a
bucket; ``compile_count`` makes that assertable exactly like
``BatchEngine``.

The decode math deliberately mirrors the training transformer
(``parallel/transformer.py``) op by op — the same ``_layer_norm``, the
same ``masked_scores`` scale/mask constants, the same f32 softmax
accumulators ``ring_attention`` uses at ring size 1, the same compute-
dtype cast policy — and the whole path is pinned against the full-pass
:func:`~znicz_tpu.parallel.transformer.make_logits_fn` oracle: greedy
decode through the cache must reproduce N full forward passes token for
token (tests/test_generate.py).  Dense FFN blocks only; MoE decode is
refused loudly (expert routing under a one-token batch is a different
serving problem).

Sampling stays on the host: :class:`TokenSampler` is seeded
temperature / top-k sampling over the returned logits, so a fixed
``(seed, temperature, top_k)`` triple reproduces a generation exactly
and the compiled programs stay sampling-free (no per-request PRNG state
threading through jit).
"""

from __future__ import annotations

import threading

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.serve.engine import bucket_sizes


class TokenSampler:
    """Seeded, deterministic next-token sampling over host logits.

    ``temperature == 0`` (or ``top_k == 1``) is greedy argmax — ties
    break toward the lowest id, matching ``np.argmax`` on both the
    cache path and the full-forward oracle.  Otherwise logits are
    temperature-scaled, optionally truncated to the ``top_k`` largest,
    and sampled from the renormalized softmax with this sampler's own
    ``numpy`` Generator — one sampler per request, so concurrent
    generations never share PRNG state.
    """

    def __init__(self, seed: int = 0, temperature: float = 1.0,
                 top_k: int = 0) -> None:
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        self.seed = int(seed)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.rng = np.random.default_rng(self.seed)

    def sample(self, logits: np.ndarray) -> int:
        z = np.asarray(logits, np.float64).ravel()
        if self.temperature == 0.0 or self.top_k == 1:
            return int(np.argmax(z))
        z = z / self.temperature
        if self.top_k and self.top_k < z.size:
            # keep the top_k largest; the cutoff uses partition so ties
            # at the boundary keep every value >= the k-th largest
            cut = np.partition(z, -self.top_k)[-self.top_k]
            z = np.where(z >= cut, z, -np.inf)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self.rng.choice(z.size, p=p))


class KVDecoder(Logger):
    """Bucketed incremental decoder over a transformer param pytree.

    ``params``: the ``parallel/transformer.py`` pytree (``emb``,
    ``head``, ``blocks``) as numpy or jax arrays; placed on device
    once.  ``heads`` cannot be derived from the arrays and must be
    given; everything else (layers, d, ff, vocab) is read off the
    shapes.  ``max_len`` bounds prompt+generation length and defines
    the bucket set; ``batch`` is the fixed slot width compiled into the
    batched ``decode`` program (1 for single-request use, >1 for the
    continuous batcher).

    Compiled programs, one per cache-length bucket:

    - ``prefill(params, tokens(1,T), length) -> (kv, logits(V,))`` —
      full prompt pass, cache for all T rows, logits at ``length-1``;
    - ``decode(params, kv, pos(B,), token(B,)) -> (kv, logits(B,V))``
      — write row ``pos`` per slot, attend over rows ``<= pos``;
    - ``adopt(kv_batch, kv1, slot) -> kv_batch`` — splice a prefilled
      single-request cache into a batch slot (continuous admission).

    ``warmup()`` materializes every bucket's programs so steady state
    compiles nothing; ``compile_count`` counts first-executions exactly
    like ``BatchEngine.compile_count``.
    """

    def __init__(self, params, heads: int, max_len: int = 256,
                 batch: int = 1) -> None:
        super().__init__()
        import jax

        if any("ew1" in blk for blk in params["blocks"]):
            raise NotImplementedError(
                "KV-cache decode supports dense FFN blocks only; MoE "
                "decode (expert routing at batch-of-one) is not wired")
        self.n_layers = len(params["blocks"])
        self.vocab, self.d = (int(s) for s in np.shape(params["emb"]))
        self.ff = int(np.shape(params["blocks"][0]["w1"])[1])
        self.heads = int(heads)
        if self.d % self.heads:
            raise ValueError(f"heads={heads} must divide d={self.d}")
        self.head_dim = self.d // self.heads
        self.max_len = int(max_len)
        self.batch = int(batch)
        if self.batch < 1:
            raise ValueError(f"batch must be >= 1, got {batch}")
        self.buckets = bucket_sizes(self.max_len)
        self._params = jax.device_put(jax.tree.map(
            lambda a: np.asarray(a, np.float32), params))
        self._prefill: dict = {}     # bucket -> jitted
        self._decode: dict = {}
        self._adopt: dict = {}
        self._seen: set = set()      # (kind, bucket) first-executions
        self.compile_count = 0
        self.prefill_count = 0
        self.decode_steps = 0        # batched decode dispatches
        self.tokens_decoded = 0      # slot-tokens produced by decode
        self._lock = threading.Lock()
        from znicz_tpu import compilecache
        compilecache.ensure()

    # -- shape policy --------------------------------------------------------
    def bucket_for(self, total_len: int) -> int:
        """Smallest cache bucket covering ``total_len`` tokens."""
        if total_len < 1:
            raise ValueError("empty sequence")
        if total_len > self.max_len:
            # admission-time rejection (400, never a burned slot): the
            # message names the configured limit so a client knows what
            # to shrink — prompt + max_tokens must fit --max-len
            raise ValueError(
                f"sequence of {total_len} tokens (prompt + max_tokens) "
                f"exceeds this server's max_len {self.max_len} "
                f"(--max-len)")
        for b in self.buckets:
            if total_len <= b:
                return b
        return self.max_len

    def _count(self, kind: str, bucket: int) -> None:
        with self._lock:
            if (kind, bucket) not in self._seen:
                self._seen.add((kind, bucket))
                self.compile_count += 1
                self.debug(f"compiling {kind} for cache bucket {bucket} "
                           f"({self.compile_count} programs)")

    # -- compiled program builders ------------------------------------------
    def _cast_policy(self):
        from znicz_tpu.parallel.transformer import _default_compute_dtype
        return _default_compute_dtype(None)

    def _attend(self, jnp, s, v_cache):
        """Softmax attention from f32 scores ``s (B,H,Q,T)`` and cached
        values ``(B,T,H,Dh)`` — the exact online-softmax recipe
        ``ring_attention`` applies at ring size 1 (f32 max/exp/sum
        accumulators, values matmul at the value dtype with an f32
        accumulator), so the cache path and the training forward agree
        to the last rounding."""
        m = s.max(axis=-1)
        p = jnp.exp(s - m[..., None])
        l = p.sum(axis=-1)
        o = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_cache.dtype),
                       v_cache, preferred_element_type=jnp.float32)
        o = (o / l[..., None]).astype(v_cache.dtype)
        return jnp.transpose(o, (0, 2, 1, 3))        # (B, Q, H, Dh)

    def _build_prefill(self, bucket: int):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.ops.attention import masked_scores
        from znicz_tpu.parallel.transformer import _layer_norm

        H, Dh = self.heads, self.head_dim
        cdt = self._cast_policy()

        def prefill(params, tokens, length):
            # tokens (1, bucket) int32, padded past `length`; the padded
            # rows compute garbage K/V that decode overwrites before any
            # mask exposes them (row pos is written before it is read)
            ps = jax.tree.map(lambda w: w.astype(cdt), params)
            x = ps["emb"][tokens]                    # (1, T, d)
            b, t = x.shape[:2]
            kpos = jnp.arange(t)
            ks, vs = [], []
            for p in ps["blocks"]:
                h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
                q = (h @ p["wq"]).reshape(b, t, H, Dh)
                k = (h @ p["wk"]).reshape(b, t, H, Dh)
                v = (h @ p["wv"]).reshape(b, t, H, Dh)
                ks.append(k)
                vs.append(v)
                s = masked_scores(jnp, q, k, True)   # causal, f32
                s = jnp.where((kpos >= length)[None, None, None, :],
                              jnp.asarray(-1e30, s.dtype), s)
                o = self._attend(jnp, s, v).reshape(b, t, -1)
                x = x + o @ p["wo"]
                m = _layer_norm(x, p["ln2_g"], p["ln2_b"])
                x = x + (jax.nn.gelu(m @ p["w1"] + p["b1"]) @ p["w2"]
                         + p["b2"])
            logits = (x @ ps["head"]).astype(jnp.float32)
            last = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, axis=0, keepdims=False)
            return {"k": jnp.stack(ks), "v": jnp.stack(vs)}, last

        return jax.jit(prefill)

    def _build_decode(self, bucket: int):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.parallel.transformer import _layer_norm

        H, Dh = self.heads, self.head_dim
        cdt = self._cast_policy()
        write = jax.vmap(
            lambda cache, new, p: jax.lax.dynamic_update_slice(
                cache, new, (p, 0, 0)))              # over the slot dim

        def decode(params, kv, pos, token):
            # kv {"k"/"v": (L, B, T, H, Dh)}; pos (B,) row to write (==
            # current length); token (B,) the token to process
            ps = jax.tree.map(lambda w: w.astype(cdt), params)
            B = token.shape[0]
            x = ps["emb"][token][:, None, :]         # (B, 1, d)
            kpos = jnp.arange(bucket)
            ks, vs = [], []
            for li, p in enumerate(ps["blocks"]):
                h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
                q = (h @ p["wq"]).reshape(B, 1, H, Dh)
                k1 = (h @ p["wk"]).reshape(B, 1, H, Dh)
                v1 = (h @ p["wv"]).reshape(B, 1, H, Dh)
                kc = write(kv["k"][li], k1, pos)
                vc = write(kv["v"][li], v1, pos)
                ks.append(kc)
                vs.append(vc)
                s = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                               preferred_element_type=jnp.float32)
                s = s / np.sqrt(Dh).astype(s.dtype)
                # keys past this slot's current position are unwritten
                # (or stale rows of a previous occupant): same -1e30
                # mask constant as masked_scores
                dead = kpos[None, :] > pos[:, None]  # (B, T)
                s = jnp.where(dead[:, None, None, :],
                              jnp.asarray(-1e30, s.dtype), s)
                o = self._attend(jnp, s, vc).reshape(B, 1, -1)
                x = x + o @ p["wo"]
                m = _layer_norm(x, p["ln2_g"], p["ln2_b"])
                x = x + (jax.nn.gelu(m @ p["w1"] + p["b1"]) @ p["w2"]
                         + p["b2"])
            logits = (x @ ps["head"]).astype(jnp.float32)
            return {"k": jnp.stack(ks), "v": jnp.stack(vs)}, logits[:, 0]

        return jax.jit(decode)

    def _build_adopt(self, bucket: int):
        import jax

        def adopt(kv, kv1, slot):
            return jax.tree.map(
                lambda c, c1: jax.lax.dynamic_update_slice(
                    c, c1, (0, slot) + (0,) * (c.ndim - 2)), kv, kv1)

        return jax.jit(adopt)

    def _program(self, cache: dict, bucket: int, builder, kind: str):
        if bucket not in cache:
            cache[bucket] = builder(bucket)
        self._count(kind, bucket)
        return cache[bucket]

    # -- public API ----------------------------------------------------------
    def alloc(self, bucket: int):
        """Zeroed batch cache for ``bucket`` — ``{"k"/"v"}`` of shape
        ``(layers, batch, bucket, heads, head_dim)`` on device."""
        import jax.numpy as jnp

        shape = (self.n_layers, self.batch, bucket, self.heads,
                 self.head_dim)
        dt = self._cast_policy()
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def grow(self, kv, new_bucket: int):
        """Pad a batch cache out to a larger bucket (zeros past the old
        length — every live row index is below it, and per-slot ``pos``
        masks keep the padding invisible).  Bucket transitions are the
        only place cache shapes change; within a bucket nothing ever
        recompiles."""
        import jax.numpy as jnp

        old = kv["k"].shape[2]
        if new_bucket < old:
            raise ValueError(f"grow to {new_bucket} < current {old}")
        if new_bucket == old:
            return kv
        pad = [(0, 0)] * 5
        pad[2] = (0, new_bucket - old)
        return {name: jnp.pad(c, pad) for name, c in kv.items()}

    def prefill(self, tokens, bucket: int | None = None):
        """Run the prompt through the full pass: ``tokens`` (1-D int
        sequence) -> ``(kv1, logits)`` — a single-request cache
        ``(L, 1, bucket, H, Dh)`` plus the next-token logits as a host
        f32 vector.  With ``batch == 1`` the returned cache feeds
        :meth:`decode` directly; the continuous batcher splices it into
        a slot via :meth:`adopt`."""
        ids = np.asarray(tokens, np.int32).ravel()
        if ids.size < 1:
            raise ValueError("empty prompt")
        if ids.min() < 0 or ids.max() >= self.vocab:
            raise ValueError(f"token ids must be in [0, {self.vocab}); "
                             f"got range [{ids.min()}, {ids.max()}]")
        bucket = self.bucket_for(ids.size) if bucket is None else bucket
        if ids.size > bucket:
            raise ValueError(f"prompt of {ids.size} tokens > bucket "
                             f"{bucket}")
        fn = self._program(self._prefill, bucket, self._build_prefill,
                           "prefill")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :ids.size] = ids
        kv1, logits = fn(self._params, padded, np.int32(ids.size))
        with self._lock:
            self.prefill_count += 1
        return kv1, np.asarray(logits)

    def decode(self, kv, pos, token):
        """One batched decode step: ``pos``/``token`` arrays of width
        ``batch`` -> ``(kv, logits (batch, vocab))`` with logits on
        host.  Slots whose row is not meant to advance simply get their
        next cache row overwritten again later — the caller (continuous
        batcher) owns slot liveness."""
        bucket = int(kv["k"].shape[2])
        pos = np.asarray(pos, np.int32)
        if pos.max() >= bucket or pos.min() < 0:
            # dynamic_update_slice CLAMPS out-of-range starts — a write
            # past the cache (or a negative position landing on row 0)
            # would silently corrupt a live row instead of failing; the
            # batcher grows the bucket before this
            raise ValueError(f"decode positions [{int(pos.min())}, "
                             f"{int(pos.max())}] outside cache bucket "
                             f"{bucket}; grow() first")
        fn = self._program(self._decode, bucket, self._build_decode,
                           "decode")
        kv, logits = fn(self._params, kv, pos,
                        np.asarray(token, np.int32))
        with self._lock:
            self.decode_steps += 1
            self.tokens_decoded += int(np.asarray(pos).size)
        return kv, np.asarray(logits)

    def adopt(self, kv, kv1, slot: int):
        """Splice a prefilled single-request cache into batch ``slot``."""
        bucket = int(kv["k"].shape[2])
        if int(kv1["k"].shape[2]) != bucket:
            kv1 = self.grow(kv1, bucket)
        fn = self._program(self._adopt, bucket, self._build_adopt,
                           "adopt")
        return fn(kv, kv1, np.int32(slot))

    def warmup(self) -> int:
        """Materialize every bucket's programs (prefill + decode, and
        adopt when batched) so live traffic compiles nothing; returns
        ``compile_count``."""
        import time

        t0 = time.perf_counter()
        for b in self.buckets:
            kv1, _ = self.prefill([0], bucket=b)
            if self.batch == 1:
                kv = kv1
            else:
                kv = self.adopt(self.alloc(b), kv1, 0)
            # row 0 is always in range (bucket 1 has nothing else);
            # warmup only needs the program materialized, not a real
            # generation
            self.decode(kv, np.zeros(self.batch, np.int32),
                        np.zeros(self.batch, np.int32))
        dt = time.perf_counter() - t0
        self.info(f"warmup: {len(self.buckets)} cache buckets in "
                  f"{dt:.2f}s — {self.compile_count} programs compiled")
        return self.compile_count

    # -- single-request convenience -----------------------------------------
    def generate(self, prompt, max_new_tokens: int,
                 sampler: TokenSampler | None = None,
                 on_token=None) -> list:
        """Prefill + decode loop for a lone request (``batch == 1``):
        returns the generated ids; ``on_token(id)`` streams them as
        produced.  The CLI one-shot mode and the bit-equivalence pin
        run through exactly this path."""
        if self.batch != 1:
            raise ValueError("generate() needs a batch=1 decoder; the "
                             "continuous batcher owns batched decode")
        # default is GREEDY (temperature 0), matching the CLI default —
        # an unconfigured generate() must be reproducible
        sampler = sampler if sampler is not None else \
            TokenSampler(temperature=0.0)
        ids = np.asarray(prompt, np.int32).ravel()
        bucket = self.bucket_for(ids.size + max_new_tokens)
        kv, logits = self.prefill(ids, bucket=bucket)
        out = []
        pos = ids.size
        for _ in range(max_new_tokens):
            tok = sampler.sample(logits)
            out.append(tok)
            if on_token is not None:
                on_token(tok)
            if len(out) == max_new_tokens:
                break
            kv, batch_logits = self.decode(
                kv, np.asarray([pos], np.int32),
                np.asarray([tok], np.int32))
            logits = batch_logits[0]
            pos += 1
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "n_layers": self.n_layers, "d": self.d,
                "heads": self.heads, "ff": self.ff, "vocab": self.vocab,
                "max_len": self.max_len, "batch": self.batch,
                "buckets": list(self.buckets),
                "compile_count": self.compile_count,
                "prefill_count": self.prefill_count,
                "decode_steps": self.decode_steps,
                "tokens_decoded": self.tokens_decoded,
            }
