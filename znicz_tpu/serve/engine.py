"""Bucketed batch execution engine — the device half of the serving
plane.

XLA recompiles a jitted function for every new input shape, so a naive
server that forwards whatever batch size arrived compiles continuously
under real traffic (batch 3, then 7, then 5, ...).  The engine instead
pads every batch up to a small fixed set of bucket shapes — powers of
two up to ``max_batch`` — so warmup compiles each bucket exactly once
and steady-state serving triggers **zero** recompiles.  An explicit
``compile_count`` / ``run_count`` pair makes that property assertable
(tests and the ``serve`` bench check ``compile_count`` stays flat after
warmup) instead of inferred from wall-clock jitter.

Backends: ``utils.export.ExportedForward`` (jitted JAX), ``native.infer
.NativeForward`` (C++ runtime, no JAX in the serving path — declares
``static_shapes = False`` so the engine skips padding entirely), or any
``array -> array`` callable.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from znicz_tpu import observe
from znicz_tpu.core.logger import Logger
from znicz_tpu.resilience.faults import fault_hook


def bucket_sizes(max_batch: int) -> tuple:
    """Powers of two up to ``max_batch``; ``max_batch`` itself is always
    the final bucket so one compile covers the full admission range."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def load_backend(path: str, prefer_native: bool = False,
                 aot: bool = True):
    """Load a utils/export.py forward package as an engine backend:
    the C++ ``NativeForward`` when requested and buildable (the no-JAX
    serving path), else the jitted ``ExportedForward``.  ``aot=False``
    ignores embedded ahead-of-time executables (the ``--no-aot`` serve
    flag); with the default, a fingerprint-matching package boots with
    zero JIT compiles."""
    if prefer_native:
        from znicz_tpu.native import infer

        if infer.available():
            return infer.NativeForward(path)
    from znicz_tpu.utils.export import ExportedForward

    return ExportedForward(path, aot=aot)


class BatchEngine(Logger):
    """Serve ``model(x) -> y`` at a fixed set of batch shapes.

    ``model``: an ``ExportedForward``, ``NativeForward``, a path to a
    forward package (.npz), or any callable over a float32 batch array.
    ``input_shape`` is taken from the model when it carries one.
    ``run()`` is thread-safe (jit dispatch is not reentrant-safe); the
    micro-batcher funnels through a single worker anyway, but direct
    callers (PredictionServer compat) may be concurrent.
    """

    def __init__(self, model, max_batch: int = 64,
                 input_shape=None) -> None:
        super().__init__()
        if isinstance(model, str):
            model = load_backend(model)
        self.model = model
        self.max_batch = int(max_batch)
        self.buckets = bucket_sizes(self.max_batch)
        #: jitted backends compile per shape -> pad to buckets; backends
        #: that declare static_shapes=False (native C++) run any batch
        self.static_shapes = bool(getattr(model, "static_shapes", True))
        shape = input_shape if input_shape is not None else \
            getattr(model, "input_shape", None)
        self.input_shape = tuple(shape) if shape is not None else None
        self.meta = dict(getattr(model, "meta", {}) or {})
        self.compile_count = 0      # buckets materialized (first-run pads)
        self.aot_count = 0          # buckets served by AOT executables
        self.run_count = 0          # batches executed
        self.rows_served = 0
        self._seen_buckets: set = set()
        self._lock = threading.Lock()
        # compile-latency plane (ISSUE 7): serve boot is a primary
        # compile site — no-op for jax-free backends (native C++)
        from znicz_tpu import compilecache
        compilecache.ensure()

    # -- shape policy --------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        if n < 1:
            raise ValueError("empty batch")
        if n > self.max_batch:
            raise ValueError(f"batch {n} > max_batch {self.max_batch} "
                             "(the micro-batcher chunks oversize requests)")
        if not self.static_shapes:
            return n
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch

    def warmup(self, input_shape=None) -> int:
        """Run one zero batch per bucket so every serving shape is
        compiled (or its AOT executable validated) before traffic
        arrives; returns the compile count — 0 on a full ahead-of-time
        boot.  Boot cost is one greppable summary line: bucket count,
        total seconds, compiled vs AOT split, persistent-cache hits."""
        shape = input_shape if input_shape is not None else self.input_shape
        if shape is None:
            raise ValueError("warmup needs input_shape (the model does "
                             "not declare one)")
        self.input_shape = tuple(shape)
        if not self.static_shapes:
            # native path: no per-shape compilation; one probe run
            # validates the package end to end
            self.run(np.zeros((1,) + self.input_shape, np.float32))
            return 0
        from znicz_tpu.observe import probe as _probe

        hits0, _misses0 = _probe.compile_cache_stats()
        t0 = time.perf_counter()
        for b in self.buckets:
            self.run(np.zeros((b,) + self.input_shape, np.float32))
        dt = time.perf_counter() - t0
        hits, _misses = _probe.compile_cache_stats()
        self.info(f"warmup: {len(self.buckets)} buckets in {dt:.2f}s — "
                  f"{self.compile_count} compiled, {self.aot_count} "
                  f"aot-precompiled, {hits - hits0} persistent-cache "
                  f"hits")
        return self.compile_count

    # -- execution -----------------------------------------------------------
    def run(self, x) -> np.ndarray:
        """Execute one batch: pad to the bucket shape, run the model,
        slice the answer back to the true row count."""
        # chaos hook (site "serve.run"): injected crashes/hangs exercise
        # the batcher's error propagation and the server's 5xx path
        fault_hook("serve.run", engine=self)
        x = np.ascontiguousarray(x, np.float32)
        if x.ndim == 1:
            x = x[None]
        if self.input_shape is not None and x.shape[1:] != self.input_shape:
            raise ValueError(f"input shape {x.shape[1:]} != model input "
                             f"{self.input_shape}")
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if bucket != n:
            pad = np.zeros((bucket - n,) + x.shape[1:], np.float32)
            x = np.concatenate([x, pad], axis=0)
        compiled = False
        with self._lock:
            if self.static_shapes and bucket not in self._seen_buckets:
                self._seen_buckets.add(bucket)
                if bucket in getattr(self.model, "precompiled_buckets",
                                     ()):
                    # ahead-of-time executable: materializing it is a
                    # deserialized-program first run, NOT a compile —
                    # the zero-JIT boot contract (compile_count == 0)
                    # is asserted on exactly this distinction
                    self.aot_count += 1
                    self.debug(f"bucket {bucket} from AOT executable "
                               f"({self.aot_count} precompiled)")
                else:
                    self.compile_count += 1
                    compiled = True
                    self.debug(f"compiling bucket {bucket} "
                               f"({self.compile_count}/{len(self.buckets)})")
            t0 = time.perf_counter()
            y = np.asarray(self.model(x))
            dt = time.perf_counter() - t0
            self.run_count += 1
            self.rows_served += n
        if compiled and observe.enabled():
            # shared telemetry plane: a bucket materializing after warmup
            # is the steady-state-recompile smell the serve bench asserts
            # against — make it scrapeable and visible on the timeline,
            # and record how long the cold bucket cost (the compile-
            # latency baseline, znicz_compile_seconds + compile.cold
            # span)
            observe.counter("znicz_serve_engine_compiles_total",
                            "engine buckets compiled").inc()
            observe.instant("serve.compile", bucket=bucket)
            observe.compile_observed("BatchEngine", dt, bucket=bucket)
        return y[:n]

    def stats(self) -> dict:
        """Engine-side counters, merged into ``GET /metrics``."""
        with self._lock:
            return {
                "max_batch": self.max_batch,
                "buckets": list(self.buckets),
                "static_shapes": self.static_shapes,
                "compile_count": self.compile_count,
                "aot_count": self.aot_count,
                "run_count": self.run_count,
                "rows_served": self.rows_served,
            }

    def close(self) -> None:
        close = getattr(self.model, "close", None)
        if callable(close):
            close()
