"""Serving telemetry — the observability half of the serving plane
(reference lineage: the veles web_status dashboard tracked *training*
progress; a traffic-serving runtime needs the request-side mirror).

Everything is stdlib + O(1) per event: fixed-bucket latency histogram
(p50/p95/p99 read off the cumulative bucket counts, no per-request
sample retention), an exact coalesced-batch-size histogram, admission /
rejection / timeout counters, a queue-depth gauge, and QPS both
since-start and over a short sliding window.  ``snapshot()`` returns a
plain JSON-able dict — the wire schema served by ``GET /metrics`` and
merged into web_status.py's ``/status.json``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from znicz_tpu.observe import probe as _probe
from znicz_tpu.observe import registry as _metrics
from znicz_tpu.observe.registry import quantile_from_buckets

#: Fixed latency bucket upper bounds in milliseconds.  Spanning 0.5 ms
#: (in-process hits on a warm engine) to 8 s (drain under overload);
#: requests beyond the last edge land in the +Inf bucket.
LATENCY_BUCKETS_MS = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 4000, 8000)

# shared-registry mirror (ISSUE 5): the per-instance snapshot() below
# stays the /status.json wire schema; these donate the same events into
# the process-global plane GET /metrics scrapes.  Counters aggregate
# across ServingMetrics instances (process-lifetime, Prometheus
# semantics); the QPS/queue-depth gauges follow the newest instance —
# one serving plane per process is the deployed shape.
_M_REQUESTS = _metrics.counter(
    "znicz_serve_requests_total", "serving requests by outcome",
    labelnames=("event",))
_M_LATENCY = _metrics.histogram(
    "znicz_serve_latency_seconds", "request latency (admit -> complete)",
    buckets=tuple(b / 1000.0 for b in LATENCY_BUCKETS_MS))
_M_BATCHES = _metrics.counter(
    "znicz_serve_batches_total", "coalesced engine batches dispatched")
_M_BATCH_ROWS = _metrics.counter(
    "znicz_serve_batch_rows_total", "rows across coalesced batches")
_M_QUEUE = _metrics.gauge("znicz_serve_queue_depth",
                          "admitted chunks awaiting service")
_M_QPS = _metrics.gauge("znicz_serve_qps",
                        "completions/sec over the sliding window "
                        "(newest serving plane)")


class LatencyHistogram:
    """Fixed-bucket histogram with percentile estimation.

    Percentiles are linearly interpolated inside the winning bucket
    (Prometheus ``histogram_quantile`` convention), so accuracy is
    bounded by bucket width — the standard serving trade-off against
    unbounded sample storage.
    """

    def __init__(self, buckets_ms=LATENCY_BUCKETS_MS) -> None:
        self.edges = tuple(float(b) for b in buckets_ms)
        self.counts = [0] * (len(self.edges) + 1)   # +1: overflow bucket
        self.total = 0
        self.sum_ms = 0.0

    def record(self, latency_s: float) -> None:
        ms = latency_s * 1000.0
        i = 0
        for i, edge in enumerate(self.edges):       # noqa: B007
            if ms <= edge:
                break
        else:
            i = len(self.edges)
        self.counts[i] += 1
        self.total += 1
        self.sum_ms += ms

    def percentile(self, p: float) -> float:
        """Estimated ``p``-th percentile in milliseconds (0 when empty)
        — delegates to the registry's shared
        :func:`~znicz_tpu.observe.registry.quantile_from_buckets`
        (ISSUE 6: one quantile estimator, not two private codes), with
        this histogram's long-standing overflow convention (interpolate
        toward ``max(last_edge, mean)``)."""
        if self.total == 0:
            return 0.0
        return quantile_from_buckets(
            self.edges, self.counts, p / 100.0,
            overflow_hi=max(self.edges[-1], self.sum_ms / self.total))

    def snapshot(self) -> dict:
        return {
            "count": self.total,
            "mean_ms": round(self.sum_ms / self.total, 3) if self.total
            else 0.0,
            "p50_ms": round(self.percentile(50), 3),
            "p95_ms": round(self.percentile(95), 3),
            "p99_ms": round(self.percentile(99), 3),
            "buckets_ms": {
                **{f"{edge:g}": self.counts[i]
                   for i, edge in enumerate(self.edges)},
                "+Inf": self.counts[-1],
            },
        }


class ServingMetrics:
    """Thread-safe aggregate of one serving plane's counters.

    One instance is shared by the batcher (admission, queue depth,
    request latency) and the HTTP front end; the engine keeps its own
    compile/run counters and the server merges both views in
    ``GET /metrics``.
    """

    #: sliding-window length for the recent-QPS figure
    WINDOW_S = 10.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.admitted = 0
        self.rejected = 0          # backpressure: queue-full fast failures
        self.timed_out = 0         # deadline expired before service
        self.completed = 0
        self.errors = 0            # model/engine raised during service
        self.queue_depth = 0       # live gauge, maintained by the batcher
        self.batch_sizes: dict[int, int] = {}   # coalesced batch -> count
        self.latency = LatencyHistogram()
        self._recent: deque = deque()           # completion stamps
        _M_QPS.set_function(self.qps)           # newest instance wins

    # -- event hooks (called by batcher / server) ---------------------------
    # registry mirrors honor the observe master switch like every other
    # probe (probe.set_enabled(False) => the instance counters keep
    # serving /status.json but the shared plane stops moving and the
    # per-request hot path drops the global-registry lock traffic)
    def on_admit(self, n_chunks: int = 1) -> None:
        with self._lock:
            self.admitted += 1
            self.queue_depth += n_chunks
            depth = self.queue_depth
        if _probe.enabled():
            _M_QUEUE.set(depth)
            _M_REQUESTS.labels(event="admitted").inc()

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1
        if _probe.enabled():
            _M_REQUESTS.labels(event="rejected").inc()

    def on_dequeue(self, n_chunks: int = 1) -> None:
        with self._lock:
            self.queue_depth = max(0, self.queue_depth - n_chunks)
            depth = self.queue_depth
        if _probe.enabled():
            _M_QUEUE.set(depth)

    def on_timeout(self) -> None:
        with self._lock:
            self.timed_out += 1
        if _probe.enabled():
            _M_REQUESTS.labels(event="timed_out").inc()

    def on_error(self) -> None:
        with self._lock:
            self.errors += 1
        if _probe.enabled():
            _M_REQUESTS.labels(event="error").inc()

    def on_batch(self, batch_rows: int) -> None:
        with self._lock:
            self.batch_sizes[batch_rows] = \
                self.batch_sizes.get(batch_rows, 0) + 1
        if _probe.enabled():
            _M_BATCHES.inc()
            _M_BATCH_ROWS.inc(batch_rows)

    def on_complete(self, latency_s: float) -> None:
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self.latency.record(latency_s)
            self._recent.append(now)
            cutoff = now - self.WINDOW_S
            while self._recent and self._recent[0] < cutoff:
                self._recent.popleft()
        if _probe.enabled():
            _M_REQUESTS.labels(event="completed").inc()
            _M_LATENCY.observe(latency_s)

    # -- export -------------------------------------------------------------
    def qps(self) -> float:
        """Completions per second over the sliding window (falls back to
        the since-start average while the window is still filling)."""
        with self._lock:
            return self._qps_locked(time.monotonic())

    def _qps_locked(self, now: float) -> float:
        elapsed = now - self.started_at
        if elapsed <= 0:
            return 0.0
        if elapsed < self.WINDOW_S:
            return self.completed / elapsed
        cutoff = now - self.WINDOW_S
        while self._recent and self._recent[0] < cutoff:
            self._recent.popleft()
        return len(self._recent) / self.WINDOW_S

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "uptime_s": round(now - self.started_at, 3),
                "qps": round(self._qps_locked(now), 3),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "timed_out": self.timed_out,
                "completed": self.completed,
                "errors": self.errors,
                "queue_depth": self.queue_depth,
                "batch_size_histogram": {
                    str(k): v for k, v in sorted(self.batch_sizes.items())},
                "latency": self.latency.snapshot(),
            }
