"""Serving telemetry — the observability half of the serving plane
(reference lineage: the veles web_status dashboard tracked *training*
progress; a traffic-serving runtime needs the request-side mirror).

Everything is stdlib + O(1) per event: fixed-bucket latency histogram
(p50/p95/p99 read off the cumulative bucket counts, no per-request
sample retention), an exact coalesced-batch-size histogram, admission /
rejection / timeout counters, a queue-depth gauge, and QPS both
since-start and over a short sliding window.  ``snapshot()`` returns a
plain JSON-able dict — the wire schema served by ``GET /metrics`` and
merged into web_status.py's ``/status.json``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

#: Fixed latency bucket upper bounds in milliseconds.  Spanning 0.5 ms
#: (in-process hits on a warm engine) to 8 s (drain under overload);
#: requests beyond the last edge land in the +Inf bucket.
LATENCY_BUCKETS_MS = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 4000, 8000)


class LatencyHistogram:
    """Fixed-bucket histogram with percentile estimation.

    Percentiles are linearly interpolated inside the winning bucket
    (Prometheus ``histogram_quantile`` convention), so accuracy is
    bounded by bucket width — the standard serving trade-off against
    unbounded sample storage.
    """

    def __init__(self, buckets_ms=LATENCY_BUCKETS_MS) -> None:
        self.edges = tuple(float(b) for b in buckets_ms)
        self.counts = [0] * (len(self.edges) + 1)   # +1: overflow bucket
        self.total = 0
        self.sum_ms = 0.0

    def record(self, latency_s: float) -> None:
        ms = latency_s * 1000.0
        i = 0
        for i, edge in enumerate(self.edges):       # noqa: B007
            if ms <= edge:
                break
        else:
            i = len(self.edges)
        self.counts[i] += 1
        self.total += 1
        self.sum_ms += ms

    def percentile(self, p: float) -> float:
        """Estimated ``p``-th percentile in milliseconds (0 when empty)."""
        if self.total == 0:
            return 0.0
        rank = p / 100.0 * self.total
        seen = 0
        for i, count in enumerate(self.counts):
            if count == 0:
                continue
            if seen + count >= rank:
                lo = self.edges[i - 1] if i > 0 else 0.0
                hi = self.edges[i] if i < len(self.edges) else \
                    max(self.edges[-1], self.sum_ms / self.total)
                frac = (rank - seen) / count
                return lo + (hi - lo) * frac
            seen += count
        return self.edges[-1]

    def snapshot(self) -> dict:
        return {
            "count": self.total,
            "mean_ms": round(self.sum_ms / self.total, 3) if self.total
            else 0.0,
            "p50_ms": round(self.percentile(50), 3),
            "p95_ms": round(self.percentile(95), 3),
            "p99_ms": round(self.percentile(99), 3),
            "buckets_ms": {
                **{f"{edge:g}": self.counts[i]
                   for i, edge in enumerate(self.edges)},
                "+Inf": self.counts[-1],
            },
        }


class ServingMetrics:
    """Thread-safe aggregate of one serving plane's counters.

    One instance is shared by the batcher (admission, queue depth,
    request latency) and the HTTP front end; the engine keeps its own
    compile/run counters and the server merges both views in
    ``GET /metrics``.
    """

    #: sliding-window length for the recent-QPS figure
    WINDOW_S = 10.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.admitted = 0
        self.rejected = 0          # backpressure: queue-full fast failures
        self.timed_out = 0         # deadline expired before service
        self.completed = 0
        self.errors = 0            # model/engine raised during service
        self.queue_depth = 0       # live gauge, maintained by the batcher
        self.batch_sizes: dict[int, int] = {}   # coalesced batch -> count
        self.latency = LatencyHistogram()
        self._recent: deque = deque()           # completion stamps

    # -- event hooks (called by batcher / server) ---------------------------
    def on_admit(self, n_chunks: int = 1) -> None:
        with self._lock:
            self.admitted += 1
            self.queue_depth += n_chunks

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def on_dequeue(self, n_chunks: int = 1) -> None:
        with self._lock:
            self.queue_depth = max(0, self.queue_depth - n_chunks)

    def on_timeout(self) -> None:
        with self._lock:
            self.timed_out += 1

    def on_error(self) -> None:
        with self._lock:
            self.errors += 1

    def on_batch(self, batch_rows: int) -> None:
        with self._lock:
            self.batch_sizes[batch_rows] = \
                self.batch_sizes.get(batch_rows, 0) + 1

    def on_complete(self, latency_s: float) -> None:
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self.latency.record(latency_s)
            self._recent.append(now)
            cutoff = now - self.WINDOW_S
            while self._recent and self._recent[0] < cutoff:
                self._recent.popleft()

    # -- export -------------------------------------------------------------
    def qps(self) -> float:
        """Completions per second over the sliding window (falls back to
        the since-start average while the window is still filling)."""
        with self._lock:
            return self._qps_locked(time.monotonic())

    def _qps_locked(self, now: float) -> float:
        elapsed = now - self.started_at
        if elapsed <= 0:
            return 0.0
        if elapsed < self.WINDOW_S:
            return self.completed / elapsed
        cutoff = now - self.WINDOW_S
        while self._recent and self._recent[0] < cutoff:
            self._recent.popleft()
        return len(self._recent) / self.WINDOW_S

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "uptime_s": round(now - self.started_at, 3),
                "qps": round(self._qps_locked(now), 3),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "timed_out": self.timed_out,
                "completed": self.completed,
                "errors": self.errors,
                "queue_depth": self.queue_depth,
                "batch_size_histogram": {
                    str(k): v for k, v in sorted(self.batch_sizes.items())},
                "latency": self.latency.snapshot(),
            }
