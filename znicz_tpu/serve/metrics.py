"""Serving telemetry — the observability half of the serving plane
(reference lineage: the veles web_status dashboard tracked *training*
progress; a traffic-serving runtime needs the request-side mirror).

Everything is stdlib + O(1) per event: fixed-bucket latency histogram
(p50/p95/p99 read off the cumulative bucket counts, no per-request
sample retention), an exact coalesced-batch-size histogram, admission /
rejection / timeout counters, a queue-depth gauge, and QPS both
since-start and over a short sliding window.  ``snapshot()`` returns a
plain JSON-able dict — the wire schema served by ``GET /metrics`` and
merged into web_status.py's ``/status.json``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from znicz_tpu.observe import probe as _probe
from znicz_tpu.observe import registry as _metrics
from znicz_tpu.observe.registry import quantile_from_buckets

#: Fixed latency bucket upper bounds in milliseconds.  Spanning 0.5 ms
#: (in-process hits on a warm engine) to 8 s (drain under overload);
#: requests beyond the last edge land in the +Inf bucket.
LATENCY_BUCKETS_MS = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 4000, 8000)

# shared-registry mirror (ISSUE 5): the per-instance snapshot() below
# stays the /status.json wire schema; these donate the same events into
# the process-global plane GET /metrics scrapes.  Counters aggregate
# across ServingMetrics instances (process-lifetime, Prometheus
# semantics); the QPS/queue-depth gauges follow the newest instance —
# one serving plane per process is the deployed shape.
_M_REQUESTS = _metrics.counter(
    "znicz_serve_requests_total", "serving requests by outcome",
    labelnames=("event",))
_M_LATENCY = _metrics.histogram(
    "znicz_serve_latency_seconds", "request latency (admit -> complete)",
    buckets=tuple(b / 1000.0 for b in LATENCY_BUCKETS_MS))
_M_BATCHES = _metrics.counter(
    "znicz_serve_batches_total", "coalesced engine batches dispatched")
_M_BATCH_ROWS = _metrics.counter(
    "znicz_serve_batch_rows_total", "rows across coalesced batches")
_M_QUEUE = _metrics.gauge("znicz_serve_queue_depth",
                          "admitted chunks awaiting service")
_M_QPS = _metrics.gauge("znicz_serve_qps",
                        "completions/sec over the sliding window "
                        "(newest serving plane)")
# ISSUE 10 small fix: `errors` counts failed BATCHES (one engine crash,
# however many requests rode it); this counts failed REQUESTS, so the
# admission ledger closes exactly: admitted == completed + failed
_M_REQ_FAILED = _metrics.counter(
    "znicz_serve_requests_failed_total",
    "requests terminally failed (engine error, deadline, shutdown)")

#: TTFT bucket upper bounds in milliseconds — generative serving's
#: time-to-first-token spans an in-process prefill (~ms) to a deep
#: admission queue under load
TTFT_BUCKETS_MS = (
    1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000)

# generative plane mirrors (ISSUE 10): same newest-instance-wins gauge
# convention as the serve mirrors above
_M_GEN_REQUESTS = _metrics.counter(
    "znicz_generate_requests_total", "generation requests by outcome",
    labelnames=("event",))
_M_GEN_TOKENS = _metrics.counter(
    "znicz_generate_tokens_total", "tokens streamed to clients")
_M_GEN_TTFT = _metrics.histogram(
    "znicz_generate_ttft_seconds",
    "time to first token (admit -> first sampled token)",
    buckets=tuple(b / 1000.0 for b in TTFT_BUCKETS_MS))
_M_GEN_SLOTS = _metrics.gauge(
    "znicz_generate_active_slots",
    "decode-batch slots generating right now (newest batcher)")
_M_GEN_TPS = _metrics.gauge(
    "znicz_generate_tokens_per_sec",
    "tokens/sec over the sliding window (newest batcher)")
_M_GEN_ABANDONED = _metrics.counter(
    "znicz_generate_abandoned_total",
    "requests abandoned by the client (cancel / disconnect)")
# ISSUE 11: the generative wait queue was only in the instance
# snapshot; the fleet aggregator's "total queue depth across N
# workers" autoscaler rule needs it in the scrapeable registry like
# znicz_serve_queue_depth
_M_GEN_QUEUE = _metrics.gauge(
    "znicz_generate_queue_depth",
    "admitted generations waiting for a decode slot (newest batcher)")
# ISSUE 12: paged-arena occupancy + speculation acceptance — the
# autoscaler/fleet-rule signals for the generative memory plane (the
# queue-depth precedent: scrapeable, not snapshot-only)
_M_GEN_PAGES_TOTAL = _metrics.gauge(
    "znicz_generate_cache_pages_total",
    "allocatable KV-arena pages (scratch page excluded; newest paged "
    "batcher)")
_M_GEN_PAGES_USED = _metrics.gauge(
    "znicz_generate_cache_pages_used",
    "KV-arena pages held by live generations (newest paged batcher)")
_M_GEN_SPEC = _metrics.counter(
    "znicz_generate_spec_tokens_total",
    "speculative draft tokens judged by the target verify pass",
    labelnames=("event",))


class LatencyHistogram:
    """Fixed-bucket histogram with percentile estimation.

    Percentiles are linearly interpolated inside the winning bucket
    (Prometheus ``histogram_quantile`` convention), so accuracy is
    bounded by bucket width — the standard serving trade-off against
    unbounded sample storage.
    """

    def __init__(self, buckets_ms=LATENCY_BUCKETS_MS) -> None:
        self.edges = tuple(float(b) for b in buckets_ms)
        self.counts = [0] * (len(self.edges) + 1)   # +1: overflow bucket
        self.total = 0
        self.sum_ms = 0.0

    def record(self, latency_s: float) -> None:
        ms = latency_s * 1000.0
        i = 0
        for i, edge in enumerate(self.edges):       # noqa: B007
            if ms <= edge:
                break
        else:
            i = len(self.edges)
        self.counts[i] += 1
        self.total += 1
        self.sum_ms += ms

    def percentile(self, p: float) -> float:
        """Estimated ``p``-th percentile in milliseconds (0 when empty)
        — delegates to the registry's shared
        :func:`~znicz_tpu.observe.registry.quantile_from_buckets`
        (ISSUE 6: one quantile estimator, not two private codes), with
        this histogram's long-standing overflow convention (interpolate
        toward ``max(last_edge, mean)``)."""
        if self.total == 0:
            return 0.0
        return quantile_from_buckets(
            self.edges, self.counts, p / 100.0,
            overflow_hi=max(self.edges[-1], self.sum_ms / self.total))

    def snapshot(self) -> dict:
        return {
            "count": self.total,
            "mean_ms": round(self.sum_ms / self.total, 3) if self.total
            else 0.0,
            "p50_ms": round(self.percentile(50), 3),
            "p95_ms": round(self.percentile(95), 3),
            "p99_ms": round(self.percentile(99), 3),
            "buckets_ms": {
                **{f"{edge:g}": self.counts[i]
                   for i, edge in enumerate(self.edges)},
                "+Inf": self.counts[-1],
            },
        }


class ServingMetrics:
    """Thread-safe aggregate of one serving plane's counters.

    One instance is shared by the batcher (admission, queue depth,
    request latency) and the HTTP front end; the engine keeps its own
    compile/run counters and the server merges both views in
    ``GET /metrics``.
    """

    #: sliding-window length for the recent-QPS figure
    WINDOW_S = 10.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.admitted = 0
        self.rejected = 0          # backpressure: queue-full fast failures
        self.timed_out = 0         # deadline expired before service
        self.completed = 0
        self.errors = 0            # model/engine raised during service
        self.failed = 0            # requests terminally failed (ledger:
        #                            admitted == completed + failed)
        self.queue_depth = 0       # live gauge, maintained by the batcher
        self.batch_sizes: dict[int, int] = {}   # coalesced batch -> count
        self.latency = LatencyHistogram()
        self._recent: deque = deque()           # completion stamps
        _M_QPS.set_function(self.qps)           # newest instance wins

    # -- event hooks (called by batcher / server) ---------------------------
    # registry mirrors honor the observe master switch like every other
    # probe (probe.set_enabled(False) => the instance counters keep
    # serving /status.json but the shared plane stops moving and the
    # per-request hot path drops the global-registry lock traffic)
    def on_admit(self, n_chunks: int = 1) -> None:
        with self._lock:
            self.admitted += 1
            self.queue_depth += n_chunks
            depth = self.queue_depth
        if _probe.enabled():
            _M_QUEUE.set(depth)
            _M_REQUESTS.labels(event="admitted").inc()

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1
        if _probe.enabled():
            _M_REQUESTS.labels(event="rejected").inc()

    def on_dequeue(self, n_chunks: int = 1) -> None:
        with self._lock:
            self.queue_depth = max(0, self.queue_depth - n_chunks)
            depth = self.queue_depth
        if _probe.enabled():
            _M_QUEUE.set(depth)

    def on_timeout(self) -> None:
        with self._lock:
            self.timed_out += 1
        if _probe.enabled():
            _M_REQUESTS.labels(event="timed_out").inc()

    def on_error(self) -> None:
        with self._lock:
            self.errors += 1
        if _probe.enabled():
            _M_REQUESTS.labels(event="error").inc()

    def on_request_failed(self) -> None:
        """One REQUEST got a terminal error (any cause: engine failure,
        deadline, non-drain shutdown) — the batcher calls this exactly
        once per request, from the one place requests fail, so
        ``admitted == completed + failed`` holds after a drain."""
        with self._lock:
            self.failed += 1
        if _probe.enabled():
            _M_REQ_FAILED.inc()

    def on_batch(self, batch_rows: int) -> None:
        with self._lock:
            self.batch_sizes[batch_rows] = \
                self.batch_sizes.get(batch_rows, 0) + 1
        if _probe.enabled():
            _M_BATCHES.inc()
            _M_BATCH_ROWS.inc(batch_rows)

    def on_complete(self, latency_s: float) -> None:
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self.latency.record(latency_s)
            self._recent.append(now)
            cutoff = now - self.WINDOW_S
            while self._recent and self._recent[0] < cutoff:
                self._recent.popleft()
        if _probe.enabled():
            _M_REQUESTS.labels(event="completed").inc()
            _M_LATENCY.observe(latency_s)

    # -- export -------------------------------------------------------------
    def qps(self) -> float:
        """Completions per second over the sliding window (falls back to
        the since-start average while the window is still filling)."""
        with self._lock:
            return self._qps_locked(time.monotonic())

    def _qps_locked(self, now: float) -> float:
        elapsed = now - self.started_at
        if elapsed <= 0:
            return 0.0
        if elapsed < self.WINDOW_S:
            return self.completed / elapsed
        cutoff = now - self.WINDOW_S
        while self._recent and self._recent[0] < cutoff:
            self._recent.popleft()
        return len(self._recent) / self.WINDOW_S

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "uptime_s": round(now - self.started_at, 3),
                "qps": round(self._qps_locked(now), 3),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "timed_out": self.timed_out,
                "completed": self.completed,
                "errors": self.errors,
                "failed": self.failed,
                "queue_depth": self.queue_depth,
                "batch_size_histogram": {
                    str(k): v for k, v in sorted(self.batch_sizes.items())},
                "latency": self.latency.snapshot(),
            }


class GenerateMetrics:
    """Thread-safe counters for one generative serving plane
    (continuous batcher + ``POST /generate``), mirrored into the shared
    registry as the ``znicz_generate_*`` family.

    The admission ledger is exact by construction — every admitted
    request reaches exactly one of ``completed`` / ``failed`` /
    ``abandoned`` (the continuous batcher's single terminal-event
    path), so chaos drills assert ``admitted == completed + failed +
    abandoned`` with ``==``, not ``>=``.
    """

    #: sliding-window length for the tokens/sec figure
    WINDOW_S = 10.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.admitted = 0
        self.rejected = 0          # backpressure: queue-full fast failures
        self.completed = 0         # streams that ended normally
        self.failed = 0            # terminal error sentinel (incl. deadline)
        self.abandoned = 0         # client cancelled / disconnected
        self.tokens = 0
        self.active_slots = 0
        self.queue_depth = 0       # admitted, waiting for a slot
        self.pages_used = 0        # paged arena only; 0 on contiguous
        self.pages_total = 0
        self.spec_accepted = 0     # draft tokens the target confirmed
        self.spec_rejected = 0     # draft tokens the target overrode
        self.ttft = LatencyHistogram(TTFT_BUCKETS_MS)
        self._recent: deque = deque()       # (stamp, n_tokens)
        _M_GEN_TPS.set_function(self.tokens_per_sec)  # newest wins

    # -- event hooks (called by the continuous batcher) ----------------------
    def on_admit(self) -> None:
        with self._lock:
            self.admitted += 1
            self.queue_depth += 1
            depth = self.queue_depth
        if _probe.enabled():
            _M_GEN_REQUESTS.labels(event="admitted").inc()
            _M_GEN_QUEUE.set(depth)

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1
        if _probe.enabled():
            _M_GEN_REQUESTS.labels(event="rejected").inc()

    def on_slots(self, active: int, queued: int) -> None:
        with self._lock:
            self.active_slots = active
            self.queue_depth = queued
        if _probe.enabled():
            _M_GEN_SLOTS.set(active)
            _M_GEN_QUEUE.set(queued)

    def on_first_token(self, ttft_s: float) -> None:
        with self._lock:
            self.ttft.record(ttft_s)
        if _probe.enabled():
            _M_GEN_TTFT.observe(ttft_s)

    def on_tokens(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self.tokens += n
            self._recent.append((now, n))
            cutoff = now - self.WINDOW_S
            while self._recent and self._recent[0][0] < cutoff:
                self._recent.popleft()
        if _probe.enabled():
            _M_GEN_TOKENS.inc(n)

    def on_complete(self) -> None:
        with self._lock:
            self.completed += 1
        if _probe.enabled():
            _M_GEN_REQUESTS.labels(event="completed").inc()

    def on_failed(self) -> None:
        with self._lock:
            self.failed += 1
        if _probe.enabled():
            _M_GEN_REQUESTS.labels(event="failed").inc()

    def on_abandoned(self) -> None:
        with self._lock:
            self.abandoned += 1
        if _probe.enabled():
            _M_GEN_ABANDONED.inc()
            _M_GEN_REQUESTS.labels(event="abandoned").inc()

    def on_pages(self, used: int, total: int) -> None:
        """Paged-arena occupancy (ISSUE 12): called by the continuous
        batcher whenever a page is allocated, appended or released."""
        with self._lock:
            self.pages_used = int(used)
            self.pages_total = int(total)
        if _probe.enabled():
            _M_GEN_PAGES_USED.set(used)
            _M_GEN_PAGES_TOTAL.set(total)

    def on_spec(self, accepted: int, rejected: int) -> None:
        """One slot's speculative round outcome: of the k draft
        proposals the target verified, ``accepted`` matched its greedy
        choice and ``rejected`` were overridden."""
        with self._lock:
            self.spec_accepted += int(accepted)
            self.spec_rejected += int(rejected)
        if _probe.enabled():
            # inc(0) still CREATES the labeled child: the batcher's
            # init-time on_spec(0, 0) pre-touch must materialize both
            # series so fleet delta rules see a 0 baseline (the PR 11
            # lesson), not a missing key
            _M_GEN_SPEC.labels(event="accepted").inc(accepted)
            _M_GEN_SPEC.labels(event="rejected").inc(rejected)

    # -- export -------------------------------------------------------------
    def tokens_per_sec(self) -> float:
        """Streamed tokens/sec over the sliding window (since-start
        average while the window is still filling)."""
        with self._lock:
            return self._tps_locked(time.monotonic())

    def _tps_locked(self, now: float) -> float:
        elapsed = now - self.started_at
        if elapsed <= 0:
            return 0.0
        if elapsed < self.WINDOW_S:
            return self.tokens / elapsed
        cutoff = now - self.WINDOW_S
        while self._recent and self._recent[0][0] < cutoff:
            self._recent.popleft()
        return sum(n for _, n in self._recent) / self.WINDOW_S

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "uptime_s": round(now - self.started_at, 3),
                "tokens_per_sec": round(self._tps_locked(now), 3),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "completed": self.completed,
                "failed": self.failed,
                "abandoned": self.abandoned,
                "tokens": self.tokens,
                "active_slots": self.active_slots,
                "queue_depth": self.queue_depth,
                "pages_used": self.pages_used,
                "pages_total": self.pages_total,
                "spec_accepted": self.spec_accepted,
                "spec_rejected": self.spec_rejected,
                "ttft": self.ttft.snapshot(),
            }
