"""Quantized-collective codec (EQuARX, arXiv:2506.17615): chunk-scaled
int8 (or bf16) payloads for the two hot collectives of the data-parallel
step — the explicit gradient psum and the ZeRO shard_params regather
(zero.gather_chain).

The psum is rebuilt as quantize -> all_gather -> dequantize -> local f32
sum: the quantized payload (1 byte/element for int8 plus one f32 scale
per chunk, 2 bytes/element for bf16) is what crosses the interconnect,
while the reduction itself happens locally in f32, so every replica
computes the SAME deterministic sum (the all-gather arrives in rank
order on every replica — no reduction-order nondeterminism on top of
the quantization error).

int8 chunks are BALANCED, not fixed: a flat payload of ``size`` elements
splits into ``ceil(size/chunk)`` chunks of ``ceil(size/n_chunks)``
elements, so padding never exceeds ``n_chunks - 1`` elements and the
wire overhead stays ~``0.25 x f32 + 4/chunk`` regardless of alignment
(a fixed chunk grid would pay up to ``chunk - 1`` padded bytes per
leaf — ruinous for bias-sized leaves).

Error feedback (the convergence preserver): the caller carries a
persistent residual tree r; each step quantizes ``h = g + r`` and the
new residual ``r' = h - dequantize(quantize(h))`` is returned to be
carried into the next step.  The residual is rank-local state — no
extra bytes on the wire.

``resolve`` turns the ``engine.quantized_collectives`` config mapping
(``{"mode": "off|bf16|int8", "chunk": N, "error_feedback": bool}``)
into a :class:`Codec` or ``None``; every entry point here treats
``codec=None`` as "exact" and emits the unquantized original ops, so
``mode=off`` is bit-identical to a build that never heard of this
module.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from znicz_tpu.core.config import root

#: config keys accepted by :func:`resolve` (anything else is a typo we
#: refuse loudly rather than silently running exact)
_CONFIG_KEYS = {"mode", "chunk", "error_feedback"}
MODES = ("off", "bf16", "int8")
DEFAULT_CHUNK = 1024


class Codec:
    """Resolved quantized-collective configuration (mode != off)."""

    __slots__ = ("mode", "chunk", "error_feedback")

    def __init__(self, mode: str, chunk: int = DEFAULT_CHUNK,
                 error_feedback: bool = True) -> None:
        self.mode = mode
        self.chunk = int(chunk)
        self.error_feedback = bool(error_feedback)

    def __repr__(self) -> str:  # config echo in errors/logs
        return (f"Codec(mode={self.mode!r}, chunk={self.chunk}, "
                f"error_feedback={self.error_feedback})")


def resolve(config=None) -> Optional[Codec]:
    """Config mapping -> :class:`Codec`, or ``None`` for the exact path.

    ``config=None`` falls back to ``root.common.engine
    .quantized_collectives`` (the process-global opt-in, the same ride
    ``shard_params`` flags took); an explicit mapping wins over the
    engine entry.  ``mode`` missing or ``"off"`` -> ``None``."""
    if config is None:
        config = root.common.engine.get("quantized_collectives", None)
    if config is None:
        return None
    if isinstance(config, Codec):
        return None if config.mode == "off" else config
    unknown = set(config) - _CONFIG_KEYS
    if unknown:
        raise ValueError(
            f"quantized_collectives: unknown key(s) {sorted(unknown)}; "
            f"accepted: {sorted(_CONFIG_KEYS)}")
    mode = config.get("mode", "off")
    if mode not in MODES:
        raise ValueError(f"quantized_collectives.mode={mode!r} — choose "
                         f"from {MODES}")
    if mode == "off":
        return None
    chunk = int(config.get("chunk", DEFAULT_CHUNK))
    if chunk <= 0:
        raise ValueError(f"quantized_collectives.chunk must be > 0, "
                         f"got {chunk}")
    return Codec(mode, chunk, bool(config.get("error_feedback", True)))


# -- chunk layout / byte math (static python ints) ---------------------------

def chunk_layout(size: int, chunk: int) -> tuple:
    """Balanced chunking of a flat ``size``-element payload:
    ``(n_chunks, chunk_len)`` with ``n_chunks * chunk_len >= size`` and
    at most ``n_chunks - 1`` padded elements."""
    size = max(int(size), 1)
    n_chunks = -(-size // chunk)
    chunk_len = -(-size // n_chunks)
    return n_chunks, chunk_len


def wire_nbytes(codec: Optional[Codec], size: int) -> int:
    """Bytes ONE participant ships for a collective over a flat f32
    payload of ``size`` elements: f32 when exact, 2B/element for bf16,
    1B/element (padded to the balanced chunk grid) + one f32 scale per
    chunk for int8."""
    if codec is None:
        return int(size) * 4
    if codec.mode == "bf16":
        return int(size) * 2
    n_chunks, chunk_len = chunk_layout(size, codec.chunk)
    return n_chunks * chunk_len + 4 * n_chunks


def exact_nbytes(size: int) -> int:
    """The f32 wire bytes the exact path ships for the same payload."""
    return int(size) * 4


# -- quantize / dequantize ---------------------------------------------------

def quantize_flat(x, codec: Codec, valid_size=None) -> tuple:
    """Flat array -> ``(payload, scales)``.

    int8: per-chunk absmax scaling over the BALANCED chunk grid
    (:func:`chunk_layout`); ``scales`` is f32 ``(n_chunks,)``.  bf16:
    an elementwise downcast, ``scales`` is ``None``.

    ``valid_size`` (static or traced scalar) masks a trailing pad out of
    BOTH the absmax and the payload: positions ``>= valid_size`` are
    zeroed before the scale computes, so tail content (zero.pad_slice
    zeros, or stale buffer bytes) can never leak into a chunk's scale
    and coarsen the valid elements' precision.  An all-pad chunk gets
    scale 1 (absmax 0), never a 0/NaN dequantize."""
    flat = x.reshape(-1).astype(jnp.float32)
    if valid_size is not None:
        keep = jnp.arange(flat.shape[0]) < valid_size
        flat = jnp.where(keep, flat, 0.0)
    if codec.mode == "bf16":
        return flat.astype(jnp.bfloat16), None
    n_chunks, chunk_len = chunk_layout(flat.shape[0], codec.chunk)
    pad = n_chunks * chunk_len - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(n_chunks, chunk_len)
    absmax = jnp.abs(chunks).max(axis=1)
    scales = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(chunks / scales[:, None]), -127.0, 127.0)
    return q.astype(jnp.int8), scales


def dequantize_flat(payload, scales, size: int):
    """Inverse of :func:`quantize_flat` back to flat f32 of ``size``
    elements (chunk padding stripped)."""
    if scales is None:                       # bf16
        return payload.astype(jnp.float32)[:size]
    n_chunks = scales.shape[0]
    deq = payload.reshape(n_chunks, -1).astype(jnp.float32) * \
        scales[:, None]
    return deq.reshape(-1)[:size]


# -- quantized psum ----------------------------------------------------------

def psum_leaf(g, axis_name, codec: Codec, residual=None) -> tuple:
    """Quantized replacement for ``lax.psum(g, axis_name)`` on one leaf:
    -> ``(summed, new_residual)``.

    Each participant quantizes its local contribution (plus the carried
    ``residual`` under error feedback), all-gathers the QUANTIZED
    payload (+ per-chunk scales for int8) over ``axis_name`` — the only
    bytes on the wire — then dequantizes every participant's payload
    and sums locally in f32.  ``new_residual`` is the local quantization
    error ``h - dequantize(own payload)`` (``None`` when ``residual``
    is), computed without any extra communication."""
    h = g if residual is None else g + residual
    size = h.size
    payload, scales = quantize_flat(h, codec)
    gathered = jax.lax.all_gather(payload, axis_name)
    if scales is None:                       # bf16: plain downcast
        total = gathered.astype(jnp.float32).sum(axis=0)[:size]
    else:
        g_scales = jax.lax.all_gather(scales, axis_name)
        deq = gathered.reshape(gathered.shape[0], scales.shape[0], -1) \
            .astype(jnp.float32) * g_scales[:, :, None]
        total = deq.reshape(gathered.shape[0], -1).sum(axis=0)[:size]
    summed = total.reshape(h.shape).astype(g.dtype)
    if residual is None:
        return summed, None
    own = dequantize_flat(payload, scales, size).reshape(h.shape)
    return summed, (h - own).astype(g.dtype)


def psum_tree(tree, axis_name, codec: Codec, residuals=None) -> tuple:
    """:func:`psum_leaf` over a pytree -> ``(summed_tree,
    new_residual_tree)``; ``residuals`` must share ``tree``'s structure
    (or be ``None`` for no error feedback)."""
    leaves, treedef = jax.tree.flatten(tree)
    res_leaves = [None] * len(leaves) if residuals is None \
        else jax.tree.flatten(residuals)[0]
    summed, new_res = [], []
    for g, r in zip(leaves, res_leaves):
        s, nr = psum_leaf(g, axis_name, codec, r)
        summed.append(s)
        new_res.append(nr)
    return (jax.tree.unflatten(treedef, summed),
            None if residuals is None
            else jax.tree.unflatten(treedef, new_res))


# -- quantized slice gather (the ZeRO shard_params regather) -----------------

def gather_slices(shard, rank, n: int, axis_name: str, like,
                  codec: Codec):
    """Quantized replacement for ``zero.all_gather_slices``: each rank
    quantizes its OWN flat slice (per-chunk scales local to the slice),
    the int8/bf16 payload + scales cross the wire, and every rank
    dequantizes the n slices on arrival back into ``like``'s shape.

    Only the bytes THIS rank actually owns enter its chunk scales:
    ``valid_size`` masks the zero.pad_slice alignment tail (present on
    the trailing rank(s) of a non-aligned leaf) out of the absmax, so
    the pad can never dilute a real chunk's scale — and an all-pad
    slice quantizes to zeros instead of NaNs."""
    shard_len = shard.shape[0]
    valid = jnp.clip(like.size - rank * shard_len, 0, shard_len)
    payload, scales = quantize_flat(shard, codec, valid_size=valid)
    gathered = jax.lax.all_gather(payload, axis_name)    # (n, padded)
    if scales is None:                                   # bf16
        slices = gathered.astype(jnp.float32)[:, :shard_len]
    else:
        g_scales = jax.lax.all_gather(scales, axis_name)
        deq = gathered.reshape(n, scales.shape[0], -1) \
            .astype(jnp.float32) * g_scales[:, :, None]
        slices = deq.reshape(n, -1)[:, :shard_len]
    full = slices.reshape(-1)[:like.size].reshape(like.shape)
    return full.astype(shard.dtype)
