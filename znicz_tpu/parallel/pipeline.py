"""Pipeline parallelism over the ``pipe`` mesh axis — GPipe-style
microbatch rotation expressed as one SPMD program (TPU-native extension;
SURVEY.md §3.4 PP row).

All devices run the same traced loop; device ``s`` applies stage ``s``'s
params (stacked stage weights sharded over the pipe axis, leading dim).
Each tick every device hands its activation to the next stage via one
``lax.ppermute`` (neighbor ICI traffic); stage 0 feeds microbatch ``t``,
stage ``S-1`` collects finished microbatch ``t - (S-1)``.  The bubble is
the standard ``S-1`` ticks.

Exactness pin: tests/test_parallel_axes.py::test_pipeline_matches_sequential.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params_local, xs, n_stages: int,
                   axis_name: str = "pipe"):
    """Run ``n_micro`` microbatches through the stage pipeline.

    - ``stage_fn(params, x) -> y``: one stage's compute; every stage must
      map shape ``(mb, d) -> (mb, d)`` (homogeneous-stage pipeline);
    - ``stage_params_local``: this device's stage params pytree (the
      caller shards a stage-stacked pytree over the pipe axis);
    - ``xs``: ``(n_micro, mb, d)`` microbatches (replicated);
    - ``n_stages``: static pipe-axis size (mesh.shape[axis_name]).
    Returns ``(n_micro, mb, d)``, replicated via the final psum.
    """
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(act, t):
        # stage 0 ingests microbatch t (clipped; ticks past the feed window
        # only drain the pipe)
        feed = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
        act = jnp.where(stage == 0, feed, act)
        y = stage_fn(stage_params_local, act)
        # the last stage emits the finished microbatch; others emit zeros
        done = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
        # rotate activations one stage forward (wraparound into stage 0 is
        # overwritten by the next feed)
        return lax.ppermute(y, axis_name, perm_fwd), done

    from znicz_tpu.parallel.mesh import varying
    # initial carry inherits xs's varying axes (e.g. data) and is cast
    # varying over the pipe axis the loop rotates on (scan vma rule)
    act0 = varying(xs[0] * 0.0, axis_name)
    _, emitted = lax.scan(tick, act0,
                          jnp.arange(n_micro + n_stages - 1))
    # microbatch t finishes at tick t + (S-1); gather in feed order, then
    # replicate off the last stage
    outs = emitted[jnp.arange(n_micro) + (n_stages - 1)]
    return lax.psum(outs, axis_name)
