"""Device-mesh construction.

Axis conventions (used across the framework; SURVEY.md §3.4 table):

- ``data``  — batch (DP): gradients psum over it;
- ``model`` — weight output-dim (TP): FC layers shard their (in, out)
  weights on out; collectives are all-gathers XLA inserts;
- ``seq``   — sequence/context (SP, ring attention extension).

Multi-host: on a pod slice ``jax.devices()`` already spans hosts after
``jax.distributed.initialize``; the same mesh code covers single-chip,
one-host-8-chip, and multi-host — XLA routes collectives over ICI/DCN from
the mesh topology.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh


def varying(x, axis_name):
    """Mark ``x`` as varying over ``axis_name`` (shard_map vma typing for
    scan carries); pcast on current jax, pvary fallback on older, and a
    no-op on pre-vma jax (no pcast/pvary): there shard_map has no
    varying-ness type system to satisfy — and the compat shim
    (parallel/compat.py) runs with the replication checker disabled, so
    no marking is needed or possible."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


def make_mesh(axis_sizes: dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the given ``{axis: size}`` (insertion-ordered).
    Total size must equal the device count used."""
    devs = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(list(axis_sizes.values())))
    if n > len(devs):
        raise ValueError(f"mesh wants {n} devices, have {len(devs)}")
    shape = tuple(axis_sizes.values())
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_sizes.keys()))


def data_parallel_mesh(n: Optional[int] = None,
                       devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-axis ("data",) mesh over ``n`` devices (default: all)."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n if n is not None else len(devs)
    return make_mesh({"data": n}, devs)


def make_hybrid_mesh(axis_sizes: dict[str, int],
                     dcn_axis_sizes: Optional[dict[str, int]] = None,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """DCN-aware mesh for multi-slice pods (SURVEY.md §6.8: "DCN-aware
    mesh axes for multi-slice").

    ``axis_sizes`` is the TOTAL per-axis size; ``dcn_axis_sizes`` says how
    much of each axis spans slices over the data-center network (default
    1 per axis = everything intra-slice).  Bandwidth rule: only axes whose
    collectives are one gradient psum per step (``data``, or ``pipe``'s
    point-to-point transfers) should span DCN; keep ``model``/``seq``
    (per-layer all-gathers) on ICI.

    On a runtime that reports slice topology (``device.slice_index``,
    real multi-slice pods) the assignment delegates to
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` so
    inner-axis neighbors are ICI neighbors; single-slice/CPU platforms
    degrade to the plain ordered mesh (same axis names and sizes, so the
    sharded program is identical — only the physical routing differs).
    """
    dcn = {k: 1 for k in axis_sizes}
    dcn.update(dcn_axis_sizes or {})
    unknown = set(dcn) - set(axis_sizes)
    if unknown:
        raise ValueError(f"dcn axes {sorted(unknown)} not in axis_sizes")
    for name, total in axis_sizes.items():
        if total % dcn[name]:
            raise ValueError(f"axis {name!r}: dcn size {dcn[name]} must "
                             f"divide total {total}")
    devs = list(devices) if devices is not None else jax.devices()
    n_slices = len({getattr(d, "slice_index", 0) for d in devs})
    n_dcn = int(np.prod(list(dcn.values())))
    if n_slices > 1 and n_dcn > 1:
        if n_dcn > n_slices:
            raise ValueError(f"dcn axes span {n_dcn} slices, runtime "
                             f"reports only {n_slices}")
        from jax.experimental import mesh_utils
        ici_shape = tuple(axis_sizes[k] // dcn[k] for k in axis_sizes)
        ici_n = int(np.prod(ici_shape))
        # surplus tolerance mirroring the single-slice make_mesh path:
        # the first n_dcn slices, and the first ici_n devices OF EACH
        # (create_hybrid_device_mesh demands exact per-granule counts)
        by_slice: dict[int, list] = {}
        for d in devs:
            by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
        trimmed = []
        for sid in sorted(by_slice)[:n_dcn]:
            if len(by_slice[sid]) < ici_n:
                raise ValueError(
                    f"slice {sid} has {len(by_slice[sid])} devices, mesh "
                    f"wants {ici_n} per slice")
            trimmed += by_slice[sid][:ici_n]
        arr = mesh_utils.create_hybrid_device_mesh(
            ici_shape, tuple(dcn[k] for k in axis_sizes), devices=trimmed)
        return Mesh(arr, tuple(axis_sizes.keys()))
    if n_slices > 1:
        # n_dcn == 1 means "everything intra-slice": honor it by building
        # from one slice when it holds enough devices (devs[:n] could
        # otherwise silently straddle the DCN boundary)
        total = int(np.prod(list(axis_sizes.values())))
        by_slice = {}
        for d in devs:
            by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
        for sid in sorted(by_slice):
            if len(by_slice[sid]) >= total:
                return make_mesh(axis_sizes, by_slice[sid])
        raise ValueError(
            f"no single slice holds the {total} devices this mesh wants "
            f"(largest has {max(len(v) for v in by_slice.values())}); "
            f"give the slice-spanning axis a dcn_axis_sizes entry")
    return make_mesh(axis_sizes, devs)
