"""Device-mesh construction.

Axis conventions (used across the framework; SURVEY.md §3.4 table):

- ``data``  — batch (DP): gradients psum over it;
- ``model`` — weight output-dim (TP): FC layers shard their (in, out)
  weights on out; collectives are all-gathers XLA inserts;
- ``seq``   — sequence/context (SP, ring attention extension).

Multi-host: on a pod slice ``jax.devices()`` already spans hosts after
``jax.distributed.initialize``; the same mesh code covers single-chip,
one-host-8-chip, and multi-host — XLA routes collectives over ICI/DCN from
the mesh topology.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh


def varying(x, axis_name):
    """Mark ``x`` as varying over ``axis_name`` (shard_map vma typing for
    scan carries); pcast on current jax, pvary fallback on older."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    return lax.pvary(x, axis_name)


def make_mesh(axis_sizes: dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the given ``{axis: size}`` (insertion-ordered).
    Total size must equal the device count used."""
    devs = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(list(axis_sizes.values())))
    if n > len(devs):
        raise ValueError(f"mesh wants {n} devices, have {len(devs)}")
    shape = tuple(axis_sizes.values())
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_sizes.keys()))


def data_parallel_mesh(n: Optional[int] = None,
                       devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-axis ("data",) mesh over ``n`` devices (default: all)."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n if n is not None else len(devs)
    return make_mesh({"data": n}, devs)
