"""Orbax-backed checkpointing for SPMD parameter pytrees (the
transformer / pipeline model family, whose params are user-managed
pytrees rather than workflow unit Arrays).

The workflow world keeps its own array-based snapshotter
(znicz_tpu/snapshotter.py: bit-exact resume, loader/PRNG/decision
state); this module covers the functional world with the TPU-ecosystem
standard (orbax), including restore onto a different mesh — the target
sharding is taken from the abstract target tree, so a checkpoint written
on one mesh loads sharded for another.
"""

from __future__ import annotations

import os

import jax

from znicz_tpu.resilience.retry import DEFAULT_IO_RETRY


def save_pytree(path: str, params, retry=DEFAULT_IO_RETRY) -> str:
    """Write ``params`` (any pytree of arrays) under ``path`` (a
    directory; created/overwritten atomically by orbax).  Transient
    filesystem failures retry under the shared I/O policy."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)

    def _save() -> None:
        with ocp.StandardCheckpointer() as ckpt:
            ckpt.save(path, params, force=True)

    if retry is None:
        _save()
    else:
        retry.call(_save)
    return path


def load_pytree(path: str, like=None):
    """Load a pytree checkpoint.  ``like`` (optional) is a template
    pytree — restored arrays adopt its shardings/dtypes, which is how a
    checkpoint written on one mesh restores onto another."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckpt:
        if like is None:
            return ckpt.restore(path)
        target = jax.tree.map(
            lambda x: ocp.utils.to_shape_dtype_struct(x), like)
        return ckpt.restore(path, target)
