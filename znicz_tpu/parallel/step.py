"""FusedTrainStep — the traced-segment compiler (SURVEY.md §8 design
stance).

Takes the accelerated segment of an NN workflow (forwards -> evaluator ->
gradient updates) and compiles it into ONE pure XLA program:

    (params, hyper, x, labels/targets, mask) -> (params', metrics)

``shard_map``-ped over a device mesh: the batch shards over the ``data``
axis, params are replicated, gradient sums ride ``lax.psum`` over ICI —
this is the rebuild of both (a) the reference's per-unit kernel-enqueue hot
loop and (b) its entire ZeroMQ master-slave protocol (§4.2), which
dissolves into the collective.

The backward pass is ``jax.value_and_grad`` of the composed forward +
evaluator loss: per-unit hand-written backward paths (units/gd.py) remain
the eager/tier-1 semantics; the equivalence of the two is pinned by
tests/test_units_fc.py::test_gd_matches_autograd and
tests/test_parallel.py (fused-vs-eager parity).

Per-layer hyperparameters (lr, weight decay, momentum) are traced scalars
read from the gradient units — LR schedule units mutate them without
triggering recompilation.  They live on device (``_hyper_device``) and are
re-uploaded only when a schedule actually changes a value; the per-step RNG
key likewise lives on device and is split inside the compiled step, so the
hot loop ships no host scalars at all.

Mixed precision: when the device reports a bfloat16 ``compute_dtype``
(TPUDevice on real TPU), activations and matmul/conv inputs run bf16 while
master params, gradient accumulation, loss and the SGD update stay f32 —
the standard MXU recipe.  On CPU (tests) compute stays f32, so tier-1/2
numerics are unchanged.

``train_steps`` scans K minibatches inside one compiled program — the
TPU-native answer to per-step dispatch latency: where the reference's hot
loop enqueues kernels per minibatch, ours compiles the whole minibatch
loop and dispatches once.

In the control graph, FusedTrainStep is one Unit replacing the whole
segment: Repeater -> Loader -> FusedTrainStep -> Decision -> Repeater;
Loader/Decision/Snapshotter stay host-side exactly like the reference.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from znicz_tpu.parallel.compat import quantized_psum, shard_map
# hoisted out of the program-build path (_apply_update used to import it
# per trace); the modules are jax-only, so the import is always safe here
from znicz_tpu.parallel import qcomm, zero

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.observe import probe as _probe
from znicz_tpu.ops import sgd
from znicz_tpu.resilience.faults import poison_hook
from znicz_tpu.units.all2all import All2AllSoftmax
from znicz_tpu.units.evaluator import EvaluatorMSE, EvaluatorSoftmax


def full_batch_arrays(loader, mse: bool):
    """The ONE place that decides whether a loader exposes a static
    full-batch dataset: returns ``(data_arr, labels_arr, None)`` or
    ``(None, None, reason)``.  Shared by the HBM dataset pinning
    (:meth:`FusedTrainStep._pin_dataset`) and the vmapped population
    evaluator (utils/genetics) so the loader contract lives in one
    function."""
    if loader is None:
        return None, None, "no loader"
    data_arr = getattr(loader, "original_data", None)
    if not data_arr:
        return None, None, "loader exposes no original_data"
    if getattr(loader, "augmenting", False):
        # augmenting loaders serve data-dependent minibatches
        # (mirror/crop per serve) — a static array stack would
        # silently skip the augmentation
        return None, None, "augmenting loader"
    labels_arr = getattr(
        loader, "original_targets" if mse else "original_labels", None)
    if not labels_arr:
        return None, None, "loader exposes no labels/targets array"
    return data_arr, labels_arr, None


class FusedTrainStep(Unit):
    """One-unit replacement for the accelerated segment of the graph."""

    #: optimizer registry: adamw state lives in extra leaf entries
    #: (sw/sb second moments, t step count) snapshotted via
    #: extra_state_arrays/load_extra_state
    OPTIMIZERS = ("sgd", "adam")
    ADAM_DEFAULTS = {"beta1": 0.9, "beta2": 0.999, "eps": 1e-8}

    def __init__(self, workflow=None, forwards=None, evaluator=None,
                 gds=None, loader=None, mesh: Optional[Mesh] = None,
                 donate: bool = True, defer_metrics: bool = True,
                 scan_epoch: Optional[bool] = None,
                 optimizer: str = "sgd",
                 optimizer_config: Optional[dict] = None,
                 shard_update: bool = False,
                 shard_params: bool = False,
                 clip_norm: Optional[float] = None,
                 accumulate_steps: int = 1,
                 ema_decay: Optional[float] = None,
                 quantized_collectives: Optional[dict] = None,
                 anatomy: Optional[bool] = None,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        #: step-anatomy split-dispatch mode (ISSUE 20): the train step
        #: runs as SEPARATE compiled programs per phase (zero_gather /
        #: grad / collective / update) with host stamps between them,
        #: feeding znicz_anatomy_* (observe/anatomy.py).  Numerics match
        #: the fused path (same loss_fn, same explicit grad psum, same
        #: apply); the cost is per-phase dispatch latency + a
        #: materialized full-weight output under shard_params — a
        #: diagnostic mode, never the perf path.  ``None`` defers to
        #: ``root.common.engine.step_anatomy`` (False).
        self.anatomy = anatomy
        #: quantized-collective codec config (ISSUE 18, EQuARX-style):
        #: ``{"mode": "off|bf16|int8", "chunk": N, "error_feedback":
        #: bool}`` — the gradient psum and (under shard_params) the
        #: regather chain ship int8/bf16 payloads; error feedback
        #: carries the quantization error into the next step's grads in
        #: persistent rw/rb residual leaves.  ``None`` defers to
        #: ``root.common.engine.quantized_collectives``; mode=off (or no
        #: config at all) compiles today's exact programs bit for bit.
        self.quantized_collectives = quantized_collectives
        if ema_decay is not None and not 0.0 < ema_decay < 1.0:
            raise ValueError(f"ema_decay must be in (0, 1), got "
                             f"{ema_decay}")
        #: exponential moving average of the params (Polyak averaging,
        #: beyond-reference): ew/eb leaves updated at every optimizer
        #: apply, read back via ema_params(), snapshotted with the
        #: optimizer state.  None = off.
        self.ema_decay = ema_decay
        if optimizer not in self.OPTIMIZERS:
            raise ValueError(f"unknown optimizer {optimizer!r}; "
                             f"registered: {self.OPTIMIZERS}")
        if accumulate_steps < 1:
            raise ValueError(f"accumulate_steps must be >= 1, got "
                             f"{accumulate_steps}")
        #: gradient accumulation: apply the optimizer every N train
        #: minibatches on the summed gradients — effective batch N x
        #: minibatch without the activation memory of a bigger batch.
        #: Per-minibatch metrics still publish every run; clipping (and
        #: the adam step count) applies per EFFECTIVE batch.
        self.accumulate_steps = int(accumulate_steps)
        #: ZeRO-grade persistent PARAMETER sharding (ISSUE 15): w/b live
        #: flat-sharded over ``data`` BETWEEN steps exactly like the
        #: optimizer state, full weights materialize on demand through a
        #: per-leaf all-gather chain (zero.gather_chain) for each
        #: forward/backward, and the post-update regather disappears —
        #: each replica keeps only its updated slice.  Per-chip
        #: persistent state (params + momenta + adam moments + EMA)
        #: scales 1/n with the dp mesh; numerics stay bit-identical to
        #: the replicated update (the gather is exact data movement and
        #: the shard update is elementwise on the same values).  Implies
        #: ``shard_update``.
        self.shard_params = bool(shard_params)
        #: ZeRO-style cross-replica sharding of the weight update (Xu et
        #: al. 2020, arXiv:2004.13336): gradients reduce-scatter over the
        #: ``data`` axis, each replica updates only its 1/n shard of the
        #: params with its 1/n shard of the OPTIMIZER STATE (momenta live
        #: sharded — the memory win), and updated params all-gather back.
        #: Numerically equivalent to the replicated update.
        self.shard_update = bool(shard_update) or self.shard_params
        #: global-norm gradient clipping (None = off): the batch-mean
        #: gradient across ALL layers is rescaled to at most this L2
        #: norm before the optimizer applies it (standard global clip)
        self.clip_norm = clip_norm
        #: "sgd" (reference semantics: momentum folded into the gd units'
        #: gradient buffers) or "adam" (AdamW, beyond-reference; lr and
        #: weight decay still come from the gd units' hyperparams, so LR
        #: schedule units keep working)
        self.optimizer = optimizer
        self.optimizer_config = {**self.ADAM_DEFAULTS,
                                 **(optimizer_config or {})}
        #: optional storage dtype for the SGD momentum buffers
        #: (``optimizer_config={"state_dtype": "bfloat16"}``): the update
        #: math stays f32 (cast in, cast out), only the persistent
        #: velocity lives narrow — at large batch the f32 w+v HBM traffic
        #: of the update rivals the matmul time, and halving the velocity
        #: bytes is the remaining lever (docs/TUNING.md).  Snapshots
        #: always store f32 (bf16->f32 is exact), so resume is bit-exact
        #: and portable across the flag.
        sd = self.optimizer_config.pop("state_dtype", None)
        self.state_dtype = jnp.dtype(sd) if sd is not None else None
        if self.state_dtype is not None and optimizer != "sgd":
            raise ValueError(
                "state_dtype applies to the SGD momentum buffers only "
                "(adam moments need f32 second-moment accumulation)")
        #: dispatch one compiled lax.scan per CLASS PASS instead of one
        #: program per minibatch (requires the pinned dataset; same
        #: "virtual minibatch" Decision accounting as defer_metrics).
        #: Hyperparams are read once per pass, so per-MINIBATCH LR
        #: schedules (LearningRateAdjust by_epoch=False) collapse to
        #: per-pass granularity in this mode; per-epoch schedules are
        #: unaffected.  None -> root.common.engine.scan_epoch (False)
        self.scan_epoch = scan_epoch
        self.forwards = list(forwards or [])
        self.evaluator = evaluator
        #: gradient units in FORWARD order (gds[i] pairs forwards[i]);
        #: suppliers of per-layer hyperparams + momentum buffers
        self.gds = list(gds or [])
        self.loader = loader
        self.mesh = mesh
        self.donate = donate
        #: keep per-minibatch metric sums ON DEVICE and sync to host once
        #: per class pass (at ``loader.last_minibatch``) — the hot loop
        #: then never blocks on host scalars between steps.  The Decision
        #: sees one aggregated "virtual minibatch" per class pass with
        #: identical epoch totals.  ``False`` restores per-minibatch sync.
        self.defer_metrics = defer_metrics
        #: forward/backward compute dtype (resolved from the device at
        #: initialize; bf16 on TPU, f32 elsewhere); params stay f32
        self.compute_dtype = None
        self._params = None
        self._key = None          # device-resident PRNG key, split per step
        self._train_fn = None
        self._eval_fn = None
        self._dataset_dev = None  # HBM-pinned (data, labels) full batch
        self._train_fn_idx = None
        self._eval_fn_idx = None
        self._scan_idx_fns = {}   # "train"/"eval" -> class-pass scan fn
        self._scan_in_flight = False  # current class pass was scan-dispatched
        self._scan_fn = None      # lazily-built K-step lax.scan variant
        self._grad_fn = None      # accumulation: grads-only half-step
        self._grad_fn_idx = None
        self._apply_fn = None     # accumulation: deferred optimizer apply
        self._grad_acc = None     # device-side summed grads
        self._bs_acc = None       # device-side summed sample count
        self._acc_count = 0       # minibatches since last apply
        self._hyper_cache = None  # (signature, device pytree)
        self._zero_gather_nbytes = 0   # bytes gathered per dispatch
        self._zero_gather_counter = None   # cached registry child
        self._gather_via_psum = False  # resolved from config at build
        self._codec = None        # resolved qcomm.Codec (None = exact)
        self._ef = False          # error-feedback residuals active?
        self._qcomm_grad_bytes = None    # (wire, exact) per train step
        self._qcomm_gather_bytes = None  # (wire, exact) per dispatch
        self._qcomm_grad_counters = None
        self._qcomm_gather_counters = None
        self._anatomy = None      # StepAnatomy accountant (anatomy mode)
        self._anat_gather_fn = None   # split programs (anatomy mode)
        self._anat_grad_fn = None
        self._anat_collective_fn = None
        self._anat_update_fn = None
        self._acc = None          # device-side metric sums (deferred mode)
        self._conf_seen = None    # confusion sums already folded this pass
        self._nt_valid = None     # nearest-target recovery proven valid?
        # metrics the Decision links to (mirrors the evaluator's attrs)
        self.n_err = 0
        self.mse = 0.0
        self.loss = 0.0
        #: host mirror of the summed sample count behind the current
        #: n_err/mse values; the Decision's ``minibatch_size`` link points
        #: here in fused workflows
        self.minibatch_size = 0

    # -- parameter pytree ---------------------------------------------------
    #: leaf keys holding optimizer state (sharded under shard_update)
    OPT_STATE_KEYS = ("vw", "vb", "sw", "sb")

    def _put(self, value, spec=P()):
        """THE device placement convention: ``value`` (array or pytree)
        onto this step's mesh under ``spec`` (a PartitionSpec or a
        matching pytree of them).  The input pipeline's stager
        (:meth:`make_stager`) shares this, so a pre-staged batch lands
        with exactly the layout the compiled step expects."""
        from jax.sharding import NamedSharding
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), spec,
            is_leaf=lambda s: isinstance(s, P))
        return jax.device_put(value, shardings)

    def _flat_shard_put(self, host_arr):
        """Flatten + pad an optimizer-state array and place it sharded
        over the ``data`` axis (ZeRO layout).  Dtype-preserving: callers
        own the storage dtype (f32 snapshots/adam moments; state_dtype
        momenta arrive pre-narrowed from put_state)."""
        n = self.mesh.shape["data"]
        flat = np.asarray(host_arr).reshape(-1)
        flat = np.pad(flat, (0, (-len(flat)) % n))
        return self._put(flat, P("data"))

    def _leaf_sharded(self, k: str) -> bool:
        """Does leaf key ``k`` live flat-sharded over ``data``?  THE one
        layout decision shared by gather_params/param_specs/
        extra_state_arrays/load_extra_state/sync_to_units."""
        if k in self.OPT_STATE_KEYS:
            return self.shard_update
        if k in ("w", "b", "ew", "eb"):
            return self.shard_params
        if k in ("rw", "rb"):
            # error-feedback residuals: rank-LOCAL (n, *param_shape)
            # slabs sharded on axis 0 — each replica carries only its
            # own quantization error (extra_state_arrays/load_extra_
            # state special-case these: the slab snapshots as-is, not
            # through the flat reassembly)
            return True
        return False            # t (scalar step count)

    def gather_params(self):
        """Build the params pytree from the unit Arrays: w/b replicated
        over the mesh (the sharding the step outputs, so the jit
        signature is stable from the first call); optimizer-state leaves
        flat-sharded over ``data`` when ``shard_update``; w/b (and the
        EMA mirrors) flat-sharded too when ``shard_params``."""
        put = lambda a: self._put(np.asarray(a))  # noqa: E731
        put_v = self._flat_shard_put if self.shard_update else put
        put_w = self._flat_shard_put if self.shard_params else put

        def put_state(a):
            # momentum buffers live in state_dtype (unit Arrays / snapshots
            # keep f32; the narrow copy exists only inside the step)
            if self.state_dtype is not None:
                a = np.asarray(a).astype(self.state_dtype)
            return put_v(a)

        params = []
        for fwd, gd in zip(self.forwards, self.gds):
            leaf = {k: put_w(arr.map_read())
                    for k, arr in fwd.param_arrays().items()}
            if "w" in leaf:
                leaf["vw"] = put_state(
                    np.zeros_like(fwd.weights.map_read())
                    if not gd.gradient_weights
                    else gd.gradient_weights.map_read())
            if "b" in leaf:
                leaf["vb"] = put_state(
                    np.zeros_like(fwd.bias.map_read())
                    if not gd.gradient_bias
                    else gd.gradient_bias.map_read())
            if self.optimizer == "adam":
                # vw/vb double as first moments; second moments + step
                # count are step-level state (restored from snapshots via
                # load_extra_state AFTER this rebuild)
                if "w" in leaf:
                    leaf["sw"] = put_v(
                        np.zeros_like(fwd.weights.map_read()))
                if "b" in leaf:
                    leaf["sb"] = put_v(np.zeros_like(fwd.bias.map_read()))
                leaf["t"] = put(np.float32(0.0))
            if self.ema_decay is not None:
                # EMA mirrors share the layout of the params they track
                # (flat-sharded under shard_params)
                if "w" in leaf:
                    leaf["ew"] = put_w(fwd.weights.map_read())
                if "b" in leaf:
                    leaf["eb"] = put_w(fwd.bias.map_read())
            if self._ef:
                # error-feedback residuals (one param-shaped slab per
                # replica, zero at build): quantization error of step t
                # rides into step t+1's gradient — persistent optimizer-
                # adjacent state, snapshotted via extra_state_arrays
                n = self.mesh.shape["data"]
                for k in ("w", "b"):
                    if k in leaf:
                        leaf["r" + k] = self._put(
                            np.zeros((n,) + self._param_shape(
                                len(params), k), np.float32), P("data"))
            params.append(leaf)
        return params

    def ema_params(self):
        """Host copies of the Polyak-averaged weights: a list of
        {"w": ..., "b": ...} dicts in unit order (export/eval view),
        fetched in ONE batched ``jax.device_get`` and reassembled from
        flat shards when ``shard_params``."""
        if self.ema_decay is None:
            raise RuntimeError("ema_decay is not enabled on this step")
        dev = {f"{i}.{k}": leaf[k]
               for i, leaf in enumerate(self._params)
               for k in ("ew", "eb") if k in leaf}
        host = jax.device_get(dev) if dev else {}
        out = [{} for _ in self._params]
        for key, val in host.items():
            i, k = key.split(".", 1)
            i = int(i)
            if self._leaf_sharded(k):
                val = self._unshard_host(val, self._param_shape(i, k))
            out[i]["w" if k == "ew" else "b"] = np.asarray(val)
        return out

    def param_specs(self):
        """Per-leaf PartitionSpecs matching gather_params' placement."""
        return [{k: (P("data") if self._leaf_sharded(k) else P())
                 for k in leaf} for leaf in self._params]

    def _res_specs(self):
        """out_specs for ``_local_grads``' residual-update return: the
        rw/rb slab layout under error feedback, ``None`` (an empty
        pytree — zero extra outputs) otherwise."""
        if not self._ef:
            return None
        return [{k: P("data") for k in ("rw", "rb") if k in leaf}
                for leaf in self._params]

    def _unshard_host(self, flat_host, like_shape):
        """Flat zero-padded HOST array (the device_get of a sharded
        leaf) -> host array of the original parameter shape.  Callers
        own the D2H transfer — the snapshot path batches the whole tree
        into one ``jax.device_get`` before reassembling."""
        size = int(np.prod(like_shape))
        return np.asarray(flat_host).reshape(-1)[:size].reshape(like_shape)

    def hyper_params(self):
        """Per-layer hyperparams as host floats (traced scalars)."""
        return [
            {"lr": float(gd.learning_rate), "wd": float(gd.weights_decay),
             "l1": float(gd.l1_vs_l2), "mom": float(gd.gradient_moment),
             "lr_b": float(gd.learning_rate_bias),
             "wd_b": float(gd.weights_decay_bias),
             "mom_b": float(gd.gradient_moment_bias)}
            for gd in self.gds
        ]

    def _hyper_device(self):
        """Device-resident hyperparam pytree, re-uploaded only when an LR
        schedule actually changed a value — the per-step rebuild shipped
        ~20 host scalars per minibatch (VERDICT r2 weak #1)."""
        host = self.hyper_params()
        sig = tuple(tuple(sorted(h.items())) for h in host)
        if self._hyper_cache is None or self._hyper_cache[0] != sig:
            dev = self._put(jax.tree.map(np.float32, host))
            self._hyper_cache = (sig, dev)
        return self._hyper_cache[1]

    def _param_shape(self, i: int, key: str):
        fwd = self.forwards[i]
        return (fwd.weights if key.endswith("w") else fwd.bias).shape

    def _account_zero_memory(self) -> None:
        """Per-chip persistent-state byte accounting into the
        ``znicz_zero_*`` registry families: params (w/b) vs
        optimizer/EMA state, sharded leaves counted at their 1/n slice
        (padding included — the flat arrays are padded to a multiple of
        n, so the per-chip figure carries the real padding epsilon).
        Also fixes the static per-dispatch gathered-bytes figure for the
        shard_params chain and caches its counter child."""
        n = self.mesh.shape["data"]
        param_b = opt_b = gather_b = 0
        for leaf in self._params:
            for k, v in leaf.items():
                nb = int(np.prod(v.shape)) * v.dtype.itemsize
                per_chip = nb // n if self._leaf_sharded(k) else nb
                if k in ("w", "b"):
                    param_b += per_chip
                    if self.shard_params:
                        gather_b += nb
                else:
                    opt_b += per_chip
        self._zero_gather_nbytes = gather_b
        _probe.zero_memory(self.name, param_b, opt_b)
        self._zero_gather_counter = _probe.zero_gather_counter(self.name)
        self._account_qcomm()

    def _account_qcomm(self) -> None:
        """Static per-dispatch wire/exact byte figures for the quantized
        collectives (same build-time convention as
        ``_zero_gather_nbytes``), plus the compression-ratio gauges and
        cached counter children.  Exact bytes follow each collective's
        native accounting: full f32 grads per train step for the psum,
        the padded-flat f32 leaf (= ``znicz_zero_gathered_bytes_total``'s
        figure) per dispatch for the shard_params regather."""
        if self._codec is None:
            return
        n = self.mesh.shape["data"]
        grad_wire = grad_exact = zg_wire = zg_exact = 0
        for i, leaf in enumerate(self._params):
            for k in ("w", "b"):
                if k not in leaf:
                    continue
                size = int(np.prod(self._param_shape(i, k)))
                grad_wire += qcomm.wire_nbytes(self._codec, size)
                grad_exact += qcomm.exact_nbytes(size)
                if self.shard_params:
                    padded = size + (-size) % n
                    zg_wire += n * qcomm.wire_nbytes(self._codec,
                                                     padded // n)
                    zg_exact += qcomm.exact_nbytes(padded)
        self._qcomm_grad_bytes = (grad_wire, grad_exact)
        self._qcomm_grad_counters = _probe.qcomm_counters(
            self.name, "grad_psum")
        _probe.qcomm_ratio(self.name, "grad_psum", grad_wire, grad_exact)
        if self.shard_params:
            self._qcomm_gather_bytes = (zg_wire, zg_exact)
            self._qcomm_gather_counters = _probe.qcomm_counters(
                self.name, "zero_gather")
            _probe.qcomm_ratio(self.name, "zero_gather", zg_wire,
                               zg_exact)

    def _note_gathered(self, n_steps: int = 1) -> None:
        """Count ``n_steps`` dispatches' worth of on-demand all-gather
        traffic (every dispatch under shard_params — train, eval, or
        each scanned minibatch — regathers the full w/b set once)."""
        if not _probe.enabled():
            return
        if self._zero_gather_nbytes:
            self._zero_gather_counter.inc(
                float(self._zero_gather_nbytes) * n_steps)
        if self._qcomm_gather_bytes:
            wire, exact = self._qcomm_gather_bytes
            c_wire, c_exact = self._qcomm_gather_counters
            c_wire.inc(float(wire) * n_steps)
            c_exact.inc(float(exact) * n_steps)

    def _note_qcomm_grads(self, n_steps: int = 1) -> None:
        """Count ``n_steps`` TRAIN dispatches' worth of quantized
        gradient-psum traffic (eval dispatches compute no grads, so the
        caller — not ``_finish_run`` — gates on the minibatch class)."""
        if self._qcomm_grad_bytes and _probe.enabled():
            wire, exact = self._qcomm_grad_bytes
            c_wire, c_exact = self._qcomm_grad_counters
            c_wire.inc(float(wire) * n_steps)
            c_exact.inc(float(exact) * n_steps)

    def _publish_residual_norm(self) -> None:
        """Global L2 norm of the error-feedback residual tree into the
        ``znicz_qcomm_residual_norm`` gauge (class-pass cadence — one
        small device reduction + scalar fetch, never per minibatch)."""
        if not self._ef or not _probe.enabled():
            return
        total = jnp.zeros((), jnp.float32)
        for leaf in self._params:
            for k in ("rw", "rb"):
                if k in leaf:
                    r = leaf[k].astype(jnp.float32)
                    total = total + jnp.vdot(r, r)
        _probe.qcomm_residual_norm(self.name,
                                   float(jnp.sqrt(total)))

    def extra_state_arrays(self) -> dict:
        """Optimizer state that has no unit Array home (adam second
        moments + step count, EMA mirrors) -> host arrays for the
        snapshotter, always in the PARAM shape (snapshots stay
        layout-independent: a sharded run restores into a replicated one
        and vice versa).  The whole tree comes down in ONE
        ``jax.device_get`` call — one blocking transfer per snapshot,
        not one per optimizer-state leaf (snapshot stalls must not scale
        with layer count)."""
        out = {}
        if self._params is None:
            return out
        keys = []
        if self.optimizer == "adam":
            keys += ["sw", "sb", "t"]
        if self.ema_decay is not None:
            keys += ["ew", "eb"]
        if self._ef:
            keys += ["rw", "rb"]
        dev = {f"{i}.{k}": leaf[k]
               for i, leaf in enumerate(self._params)
               for k in keys if k in leaf}
        host = jax.device_get(dev) if dev else {}
        for key, val in host.items():
            i, k = key.split(".", 1)
            if k in ("rw", "rb"):
                # error-feedback residuals are genuinely per-rank state:
                # the (n, *param_shape) slab snapshots AS-IS (same mesh
                # resumes bit-exact; load_extra_state folds the rank sum
                # — the only quantity the EF correction depends on —
                # when the world size changed)
                out[key] = np.asarray(val)
                continue
            if self._leaf_sharded(k):
                val = self._unshard_host(val, self._param_shape(int(i), k))
            out[key] = np.asarray(val)
        return out

    def load_extra_state(self, arrays: dict) -> None:
        """Restore extra_state_arrays output into the (already rebuilt)
        device params — call after gather_params on resume.  Arrays
        arrive in the PARAM shape and land in whatever layout THIS step
        uses (the cross-layout resume contract)."""
        for key, val in arrays.items():
            i, k = key.split(".", 1)
            if k in ("rw", "rb"):
                if not self._ef:
                    # quantized -> exact cross-layout restore: the
                    # residual has no home (and no effect) here — drop
                    # it rather than corrupt the leaf layout
                    continue
                n = self.mesh.shape["data"]
                val = np.asarray(val, np.float32)
                if val.shape[0] != n:
                    # cross-world restore: only the rank SUM of the
                    # residuals is meaningful (Σr is the total deferred
                    # quantization error) — fold it onto rank 0
                    folded = np.zeros((n,) + val.shape[1:], np.float32)
                    folded[0] = val.sum(axis=0)
                    val = folded
                self._params[int(i)][k] = self._put(val, P("data"))
            elif self._leaf_sharded(k):
                self._params[int(i)][k] = self._flat_shard_put(val)
            else:
                self._params[int(i)][k] = self._put(np.asarray(val))

    def sync_to_units(self) -> None:
        """Write the device params back into the unit Arrays (snapshot /
        inspection path; the hot loop never does this).  Replicated
        leaves hand their device buffer over zero-copy (set_devmem);
        flat-sharded leaves come down in ONE batched ``jax.device_get``
        and reassemble to the param shape host-side."""
        fetch = {f"{i}.{k}": leaf[k]
                 for i, leaf in enumerate(self._params)
                 for k in ("w", "b", "vw", "vb")
                 if k in leaf and self._leaf_sharded(k)}
        host = jax.device_get(fetch) if fetch else {}

        def put_host(arr, flat, shape):
            arr.map_invalidate()
            arr.mem = np.asarray(self._unshard_host(flat, shape),
                                 dtype=np.float32)

        for i, (fwd, gd, leaf) in enumerate(
                zip(self.forwards, self.gds, self._params)):
            if "w" in leaf:
                if self.shard_params:
                    put_host(fwd.weights, host[f"{i}.w"],
                             fwd.weights.shape)
                else:
                    fwd.weights.set_devmem(leaf["w"])
            if "b" in leaf:
                if self.shard_params:
                    put_host(fwd.bias, host[f"{i}.b"], fwd.bias.shape)
                else:
                    fwd.bias.set_devmem(leaf["b"])
            if not self.shard_update:
                # unit buffers are f32 (astype is a no-op without
                # state_dtype; exact widening with it)
                if "w" in leaf:
                    gd.gradient_weights.set_devmem(
                        leaf["vw"].astype(jnp.float32))
                if "b" in leaf:
                    gd.gradient_bias.set_devmem(
                        leaf["vb"].astype(jnp.float32))
                continue
            # sharded momenta: reassemble to the param shape host-side
            # (the batched fetch above; f32 widening is exact)
            if "w" in leaf:
                put_host(gd.gradient_weights, host[f"{i}.vw"],
                         fwd.weights.shape)
            if "b" in leaf:
                put_host(gd.gradient_bias, host[f"{i}.vb"],
                         fwd.bias.shape)

    # -- forward / loss composition -----------------------------------------
    def _forward_chain(self, params, x, train: bool, rng=None):
        """Compose the forwards; returns pre-softmax logits when the last
        layer is All2AllSoftmax (loss uses log_softmax directly).

        ``rng`` is a per-step key; each NEEDS_RNG unit (dropout, stochastic
        pooling) gets a per-unit fold so masks are independent across units
        and steps.

        Activations and param inputs are cast to ``compute_dtype`` (bf16 on
        TPU) — AD then casts cotangents back, so gradients accumulate into
        the f32 master params."""
        cdt = self.compute_dtype or jnp.float32
        x = x.astype(cdt)
        last = len(self.forwards) - 1
        logits_tail = isinstance(self.forwards[last], All2AllSoftmax) and \
            isinstance(self.evaluator, EvaluatorSoftmax)
        for i, (fwd, p) in enumerate(zip(self.forwards, params)):
            pc = {k: (v.astype(cdt) if k in ("w", "b") else v)
                  for k, v in p.items()}
            unit_rng = None
            if getattr(fwd, "NEEDS_RNG", False) and rng is not None:
                unit_rng = jax.random.fold_in(rng, i)
            if i == last and logits_tail:
                x = fwd.xla_apply_linear(pc, x)
            else:
                x = fwd.xla_apply(pc, x, rng=unit_rng, train=train)
        return x, logits_tail

    def _nt_recovery_valid(self) -> bool:
        """Fused nearest-target n_err is emitted only when the label-
        recovery assumption is PROVEN at trace time: every stored target
        must be the exact prototype row of its label (noisy targets
        would silently recover wrong labels — the eager evaluator, which
        has real label plumbing, stays correct for those).  Cached after
        the first check."""
        if self._nt_valid is not None:
            return self._nt_valid
        self._nt_valid = False
        ev = self.evaluator
        loader = self.loader
        if isinstance(ev, EvaluatorMSE) and ev._classifies and \
                loader is not None:
            targets = getattr(loader, "original_targets", None)
            labels = getattr(loader, "original_labels", None)
            if targets and labels:
                protos = ev.class_targets.map_read()
                lab = np.asarray(labels.mem)
                self._nt_valid = bool(
                    np.array_equal(np.asarray(targets.mem),
                                   protos[lab]))
        return self._nt_valid

    def _loss_and_metrics(self, out, logits_tail, labels, mask):
        """Masked loss-sum + metric sums over the local shard (f32
        regardless of the forward's compute dtype)."""
        out = out.astype(jnp.float32)
        fmask = mask.astype(out.dtype)
        if isinstance(self.evaluator, EvaluatorSoftmax):
            if logits_tail:
                logp = jax.nn.log_softmax(out, axis=1)
            else:
                logp = jnp.log(jnp.clip(out, 1e-30, None))
            n = out.shape[0]
            picked = logp[jnp.arange(n), labels]
            # per-class weights (evaluator contract): the CE term of each
            # sample is scaled by its TRUE class's weight, so AD yields
            # err_output rows scaled exactly like the eager evaluator's
            cw = getattr(self.evaluator, "class_weights", None)
            wrow = fmask if cw is None else \
                fmask * jnp.asarray(cw, out.dtype)[labels]
            loss = -(picked * wrow).sum()
            pred = out.argmax(axis=1)
            n_err = ((pred != labels) & mask).sum()
            metrics = {"loss": loss, "n_err": n_err}
            if getattr(self.evaluator, "compute_confusion_matrix", False):
                # (pred, label) count matrix as f32 sums — exact up to
                # 2^24 samples per class pass, far above any epoch here;
                # orientation matches the eager evaluator's
                # np.add.at(confusion, (max_idx, labels), 1)
                c = out.shape[1]
                pred_oh = jax.nn.one_hot(pred, c, dtype=jnp.float32) * \
                    fmask[:, None]
                lab_oh = jax.nn.one_hot(labels, c, dtype=jnp.float32)
                metrics["confusion"] = pred_oh.T @ lab_oh
            return loss, metrics
        if isinstance(self.evaluator, EvaluatorMSE):
            n = out.shape[0]
            diff = (out.reshape(n, -1) -
                    labels.reshape(n, -1)) * fmask[:, None]
            loss = 0.5 * (diff * diff).sum()
            mse_sum = (diff * diff).mean(axis=1).sum()
            metrics = {"loss": loss, "mse_sum": mse_sum}
            if self._nt_recovery_valid():
                # nearest-target classification without label plumbing:
                # the init-time check proved targets are exact prototype
                # rows, so the integer label is recoverable as the
                # nearest prototype of the TARGET; n_err then counts
                # outputs nearest a different prototype (the eager
                # evaluator's count).  Prototypes are a small static
                # table baked in at trace time.
                protos = jnp.asarray(
                    self.evaluator.class_targets.map_read(), out.dtype)
                pred = EvaluatorMSE.nearest_prototype(jnp, out, protos)
                lab = EvaluatorMSE.nearest_prototype(
                    jnp, labels.reshape(n, -1).astype(out.dtype), protos)
                metrics["n_err"] = ((pred != lab) & mask).sum()
            return loss, metrics
        raise TypeError(f"unsupported evaluator {type(self.evaluator)}")

    # -- compiled step bodies ------------------------------------------------
    def _local_train(self, params, key, hyper, x, labels, mask):
        """One step: ``(params, key, ...) -> (params', key', metrics)``.
        The key is split ON DEVICE — the host never mints per-step keys.
        Gradient computation is shared with the accumulation half-step
        (_local_grads); the optimizer application with the deferred apply
        (_apply_update)."""
        key, grads, metrics, new_res = self._local_grads(params, key, x,
                                                         labels, mask)
        if new_res is not None:
            # fold the stepped error-feedback residuals into the params
            # carry BEFORE the apply (_apply_update's dict(leaf) copy
            # passes them through to the output pytree)
            params = [{**leaf, **nr}
                      for leaf, nr in zip(params, new_res)]
        new_params = self._apply_update(params, grads, hyper,
                                        metrics["bs"])
        return new_params, key, metrics

    def _apply_update(self, params, grads, hyper, bs):
        """Apply one optimizer step for summed gradients ``grads`` over
        ``bs`` total samples — shared by the per-minibatch step and the
        gradient-accumulation apply."""
        if self.clip_norm is not None:
            # clip the batch-mean gradient's GLOBAL norm across layers;
            # scaling grad_sum by the same factor is equivalent and keeps
            # the downstream /bs convention untouched
            sq = sum(jnp.sum(jnp.square(g / bs))
                     for leaf in grads for g in leaf.values())
            gnorm = jnp.sqrt(sq)
            scale = jnp.minimum(1.0, self.clip_norm /
                                jnp.maximum(gnorm, 1e-12))
            grads = [{k: v * scale for k, v in leaf.items()}
                     for leaf in grads]
        # SGD backend: XLA-fused by default; the Pallas single-HBM-pass
        # kernel when root.common.engine.pallas is set (SURVEY.md §3.2
        # "fused SGD-update" kernel parity deliverable)
        use_pallas = bool(root.common.engine.get("pallas", False))
        interp = bool(root.common.engine.get("pallas_interpret", False))
        cfg = self.optimizer_config
        if use_pallas:
            from znicz_tpu.ops.pallas import (fused_adam_update,
                                              fused_sgd_update)

            def upd(w, g, v, lr, wd, l1, mom, bsz):
                return fused_sgd_update(w, g, v, lr, wd, l1, mom,
                                        bsz.astype(jnp.float32),
                                        interpret=interp)

            def adam_upd(w, g, m, s, t_new, lr, wd, bsz):
                return fused_adam_update(
                    w, g, m, s, t_new, lr, wd, cfg["beta1"],
                    cfg["beta2"], cfg["eps"], bsz.astype(jnp.float32),
                    interpret=interp)
        else:
            from znicz_tpu.ops import adam

            def upd(w, g, v, lr, wd, l1, mom, bsz):
                return sgd.update(jnp, w, g, v, lr, wd, l1, mom, bsz)

            def adam_upd(w, g, m, s, t_new, lr, wd, bsz):
                return adam.update(jnp, w, g, m, s, t_new, lr, wd,
                                   cfg["beta1"], cfg["beta2"],
                                   cfg["eps"], bsz)

        # narrow momenta (state_dtype) need no handling here: both
        # backends preserve the velocity's storage dtype themselves —
        # ops.sgd.update widens for the math and returns vel narrow; the
        # Pallas kernel casts in-tile (single HBM pass preserved)

        if self.shard_update:
            n_data = self.mesh.shape["data"]   # static: pad math below
            rank = jax.lax.axis_index("data")
            sp = self.shard_params

            def my_slice(w):
                return zero.pad_slice(w, rank, n_data)

            def regather(w_shard, like):
                return zero.psum_regather(w_shard, rank, n_data, "data",
                                          like)

            def apply(leaf, grad, h, wk, vk, sk, lr_k, wd_k, new, t_new):
                # the grads arrive ALREADY globally summed: the vma
                # system requires cotangents of unvaried (replicated)
                # primals to be unvaried, so AD inserts the cross-replica
                # psum itself.  Each replica therefore just slices its
                # shard — the sharding win is the ZeRO-1 one (optimizer
                # state + update compute at 1/n), not grad bandwidth
                g = my_slice(grad[wk])
                # under shard_params the leaf already IS the flat shard
                w_sh = leaf[wk] if sp else my_slice(leaf[wk])
                if self.optimizer == "adam":
                    w_sh, new[vk], new[sk] = adam_upd(
                        w_sh, g, leaf[vk], leaf[sk], t_new, h[lr_k],
                        h[wd_k], bs)
                else:
                    mom_k = "mom" if wk == "w" else "mom_b"
                    w_sh, new[vk] = upd(w_sh, g, leaf[vk], h[lr_k],
                                        h[wd_k], h["l1"], h[mom_k], bs)
                # shard_params: the updated slice IS the persistent
                # layout — the post-update regather disappears entirely
                # (the next forward regathers on demand instead)
                new[wk] = w_sh if sp else regather(w_sh, leaf[wk])
        else:
            apply = None

        new_params = []
        for leaf, grad, h in zip(params, grads, hyper):
            new = dict(leaf)
            t_new = leaf["t"] + 1.0 if self.optimizer == "adam" else None
            if apply is not None:
                if "w" in leaf:
                    apply(leaf, grad, h, "w", "vw", "sw", "lr", "wd",
                          new, t_new)
                if "b" in leaf:
                    apply(leaf, grad, h, "b", "vb", "sb", "lr_b", "wd_b",
                          new, t_new)
                if t_new is not None:
                    new["t"] = t_new
            elif self.optimizer == "adam":
                if "w" in leaf:
                    new["w"], new["vw"], new["sw"] = adam_upd(
                        leaf["w"], grad["w"], leaf["vw"], leaf["sw"],
                        t_new, h["lr"], h["wd"], bs)
                if "b" in leaf:
                    new["b"], new["vb"], new["sb"] = adam_upd(
                        leaf["b"], grad["b"], leaf["vb"], leaf["sb"],
                        t_new, h["lr_b"], h["wd_b"], bs)
                new["t"] = t_new
            else:
                if "w" in leaf:
                    new["w"], new["vw"] = upd(
                        leaf["w"], grad["w"], leaf["vw"], h["lr"], h["wd"],
                        h["l1"], h["mom"], bs)
                if "b" in leaf:
                    new["b"], new["vb"] = upd(
                        leaf["b"], grad["b"], leaf["vb"], h["lr_b"],
                        h["wd_b"], h["l1"], h["mom_b"], bs)
            if self.ema_decay is not None:
                d = jnp.float32(self.ema_decay)
                if "ew" in leaf:
                    new["ew"] = d * leaf["ew"] + (1.0 - d) * new["w"]
                if "eb" in leaf:
                    new["eb"] = d * leaf["eb"] + (1.0 - d) * new["b"]
            new_params.append(new)
        return new_params

    def _gather_full(self, leaves):
        """``shard_params`` materialization: full w/b arrays from the
        flat shards via the per-leaf all-gather chain
        (:func:`zero.gather_chain`), dispatched in consumption order
        ahead of the forward so XLA's async collectives overlap leaf
        i+1's gather with leaf i's compute.  Non-w/b keys pass through;
        a no-op without ``shard_params``."""
        if not self.shard_params:
            return leaves
        n = self.mesh.shape["data"]
        rank = jax.lax.axis_index("data")
        shards, likes, sites = [], [], []
        for i, leaf in enumerate(leaves):
            for k in ("w", "b"):
                if k in leaf:
                    shards.append(leaf[k])
                    likes.append(jax.ShapeDtypeStruct(
                        self._param_shape(i, k), leaf[k].dtype))
                    sites.append((i, k))
        full = zero.gather_chain(shards, likes, rank, n, "data",
                                 via_psum=self._gather_via_psum,
                                 codec=self._codec)
        out = [dict(leaf) for leaf in leaves]
        for (i, k), v in zip(sites, full):
            out[i][k] = v
        return out

    def _local_grads(self, params, key, x, labels, mask):
        """Gradient-accumulation half-step: summed grads + metrics, NO
        update (the apply happens every ``accumulate_steps`` runs)."""
        key, sub = jax.random.split(key)
        rng = jax.random.fold_in(sub, jax.lax.axis_index("data"))
        trainable = [{k: v for k, v in leaf.items() if k in ("w", "b")}
                     for leaf in params]
        # shard_params: materialize full weights OUTSIDE the
        # differentiated function — grads land param-shaped and reduce
        # through the SAME explicit psum as every other mode (AD through
        # the gather would transpose to a reduce-scatter, changing the
        # reduction path and with it the bit-exact parity with the
        # replicated/shard_update paths); the update slices them
        trainable = self._gather_full(trainable)

        def loss_fn(ps):
            out, logits_tail = self._forward_chain(ps, x, train=True,
                                                   rng=rng)
            loss, metrics = self._loss_and_metrics(
                out, logits_tail, labels, mask)
            metrics = jax.lax.psum(metrics, "data")
            # LOCAL loss on purpose: the cross-device reduction happens
            # on the GRADS below.  Differentiating through a psum'd loss
            # depends on the psum transpose convention (it flips with
            # the replication checker, see parallel/compat.py) and never
            # yields replicated params on >1 device — the explicit grad
            # psum is correct under either convention.
            return loss, metrics

        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(trainable)
        # the grad reduction rides the quantized-psum seam: exact
        # lax.psum when no codec (bit-identical program), int8/bf16
        # payload + error-feedback residuals otherwise.  metrics/bs
        # psums above/below stay exact always — telemetry and the
        # Decision's sample accounting must never quantize.
        residuals = None
        if self._ef:
            # local residual view: the (1, *shape) slab's single row
            residuals = [{k: params[i]["r" + k][0] for k in g}
                         for i, g in enumerate(grads)]
        grads, res_out = quantized_psum(grads, "data", self._codec,
                                        residuals)
        new_res = None if res_out is None else \
            [{"r" + k: v[None] for k, v in leaf.items()}
             for leaf in res_out]
        metrics["bs"] = jax.lax.psum(mask.sum(), "data")
        return key, grads, metrics, new_res

    def _local_grads_idx(self, params, key, data, labels, idx, mask):
        return self._local_grads(params, key, data[idx], labels[idx], mask)

    def _local_apply(self, params, hyper, grads, bs):
        return self._apply_update(params, grads, hyper, bs)

    def _local_eval(self, params, x, labels, mask):
        params = self._gather_full(params)
        out, logits_tail = self._forward_chain(params, x, train=False)
        _, metrics = self._loss_and_metrics(out, logits_tail, labels, mask)
        metrics = jax.lax.psum(metrics, "data")
        metrics["bs"] = jax.lax.psum(mask.sum(), "data")
        return metrics

    # index-fed variants: the dataset lives on HBM (see initialize); the
    # host ships ~4 bytes/sample of indices per step instead of the
    # minibatch itself (reference: FullBatchLoader's ``on_device`` option)
    def _local_train_idx(self, params, key, hyper, data, labels, idx, mask):
        return self._local_train(params, key, hyper, data[idx],
                                 labels[idx], mask)

    def _local_eval_idx(self, params, data, labels, idx, mask):
        return self._local_eval(params, data[idx], labels[idx], mask)

    # -- step anatomy (ISSUE 20): split-dispatch phase programs --------------
    def _trainable_specs(self, spec):
        """Specs pytree matching the trainable (w/b-only) subtree."""
        return [{k: spec for k in ("w", "b") if k in leaf}
                for leaf in self._params]

    def _build_anatomy(self) -> None:
        """Compile the per-phase programs the anatomy mode dispatches
        sequentially: the SAME bodies as ``_local_train`` — gather, then
        ``loss_fn``+grad, then the explicit (possibly quantized) psum,
        then ``_apply_update`` — cut at the phase seams.  The grad
        program returns per-rank UNREDUCED grads as a stacked
        ``(n, *shape)`` array via the ``g[None]`` / out_specs
        ``P("data")`` trick (each rank's slice stays on its device: no
        data movement at the cut), and the collective program takes the
        stack back per-rank and runs the identical ``quantized_psum``
        seam — grads, error-feedback residuals and the update follow
        exactly the fused program's math (parity to float tolerance:
        XLA may fuse/reassociate differently across the program cuts,
        which test_anatomy pins)."""
        from znicz_tpu.observe.anatomy import StepAnatomy, TRAIN_PHASES

        rep, sh = P(), P("data")
        pspecs = self.param_specs()
        t_rep = self._trainable_specs(rep)
        t_stacked = self._trainable_specs(sh)

        def local_gather(params):
            trainable = [{k: v for k, v in leaf.items()
                          if k in ("w", "b")} for leaf in params]
            return self._gather_full(trainable)

        def local_grad(trainable, key, x, labels, mask):
            key, sub = jax.random.split(key)
            rng = jax.random.fold_in(sub, jax.lax.axis_index("data"))

            def loss_fn(ps):
                out, logits_tail = self._forward_chain(ps, x, train=True,
                                                       rng=rng)
                loss, metrics = self._loss_and_metrics(
                    out, logits_tail, labels, mask)
                metrics = jax.lax.psum(metrics, "data")
                return loss, metrics

            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(trainable)
            stacked = [{k: v[None] for k, v in leaf.items()}
                       for leaf in grads]
            metrics["bs"] = jax.lax.psum(mask.sum(), "data")
            return key, stacked, metrics

        def local_collective(params, stacked):
            grads = [{k: v[0] for k, v in leaf.items()}
                     for leaf in stacked]
            residuals = None
            if self._ef:
                residuals = [{k: params[i]["r" + k][0] for k in g}
                             for i, g in enumerate(grads)]
            grads, res_out = quantized_psum(grads, "data", self._codec,
                                            residuals)
            new_res = None if res_out is None else \
                [{"r" + k: v[None] for k, v in leaf.items()}
                 for leaf in res_out]
            return grads, new_res

        if self.shard_params:
            gatherf = shard_map(local_gather, mesh=self.mesh,
                                in_specs=(pspecs,), out_specs=t_rep)
            self._anat_gather_fn = jax.jit(gatherf)
        gradf = shard_map(local_grad, mesh=self.mesh,
                          in_specs=(t_rep, rep, sh, sh, sh),
                          out_specs=(rep, t_stacked, rep))
        self._anat_grad_fn = jax.jit(gradf)
        collf = shard_map(local_collective, mesh=self.mesh,
                          in_specs=(pspecs, t_stacked),
                          out_specs=(t_rep, self._res_specs()))
        self._anat_collective_fn = jax.jit(collf)
        if self._ef:
            def local_update(params, hyper, grads, bs, new_res):
                params = [{**leaf, **nr}
                          for leaf, nr in zip(params, new_res)]
                return self._apply_update(params, grads, hyper, bs)
            updf = shard_map(local_update, mesh=self.mesh,
                             in_specs=(pspecs, rep, t_rep, rep,
                                       self._res_specs()),
                             out_specs=pspecs)
        else:
            updf = shard_map(self._local_apply, mesh=self.mesh,
                             in_specs=(pspecs, rep, t_rep, rep),
                             out_specs=pspecs)
        self._anat_update_fn = jax.jit(updf)
        self._anatomy = StepAnatomy("fused", TRAIN_PHASES)
        if self.loader is not None:
            from znicz_tpu.utils import flops as _flops
            self._anatomy.set_flops(_flops.train_step_flops(
                self.forwards, int(self.loader.max_minibatch_size)))

    def _run_anatomy_step(self, x, labels, mask):
        """Anatomy-mode train dispatch: one program per phase, host
        stamps at the ``block_until_ready`` boundaries.  Returns the
        metrics pytree the fused program would have returned."""
        anat = self._anatomy
        anat.begin()
        if self.shard_params:
            trainable = jax.block_until_ready(
                self._anat_gather_fn(self._params))
            anat.stamp("zero_gather")
        else:
            trainable = [{k: leaf[k] for k in ("w", "b") if k in leaf}
                         for leaf in self._params]
        key, stacked, metrics = jax.block_until_ready(
            self._anat_grad_fn(trainable, self._key, x, labels, mask))
        anat.stamp("grad")
        grads, new_res = jax.block_until_ready(
            self._anat_collective_fn(self._params, stacked))
        anat.stamp("collective")
        hyper = self._hyper_device()
        if self._ef:
            params = self._anat_update_fn(self._params, hyper, grads,
                                          metrics["bs"], new_res)
        else:
            params = self._anat_update_fn(self._params, hyper, grads,
                                          metrics["bs"])
        jax.block_until_ready(params)
        anat.stamp("update")
        self._params, self._key = params, key
        anat.finish()
        return metrics

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, device=None, **kwargs) -> None:
        # the step subsumes the segment units: they are not in the control
        # graph, so initialize them here (weights allocated + filled) before
        # gathering the params pytree
        for unit in (*self.forwards, self.evaluator, *self.gds):
            if unit is not None and not unit.initialized:
                unit.initialize(device=device, **kwargs)
                unit.initialized = True
        # compile-latency plane (ISSUE 7): the program builds below are
        # the training path's cold compiles — route them through the
        # persistent cache so a restarted process (or a second host on
        # a shared cache dir) pays trace cost only
        from znicz_tpu import compilecache
        compilecache.ensure()
        if self.optimizer == "adam":
            # the adam branch reads lr/wd only; a configured L1 mix would
            # be silently dropped — refuse like the fused=False guard
            bad = [gd.name for gd in self.gds
                   if float(getattr(gd, "l1_vs_l2", 0.0)) != 0.0]
            if bad:
                raise ValueError(
                    f"l1_vs_l2 is SGD-only (adam applies decoupled L2 "
                    f"weight decay); set it to 0 on: {bad}")
        if self.mesh is None:
            # local_devices: under a jax.distributed join, devices()[0]
            # belongs to process 0 — a default mesh must be addressable
            # from THIS rank (the elastic fleet's standalone-SPMD path)
            self.mesh = Mesh(np.array(jax.local_devices()[:1]), ("data",))
        n_data = self.mesh.shape["data"]
        if self.loader is not None and \
                self.loader.max_minibatch_size % n_data != 0:
            raise ValueError(
                f"minibatch {self.loader.max_minibatch_size} not divisible "
                f"by data-mesh size {n_data}")
        if self.compute_dtype is None:
            self.compute_dtype = getattr(device, "compute_dtype", None) or \
                jnp.float32
        # shard_params regather flavor: payload-proportional all_gather
        # by default; engine.zero_gather_via_psum opts into the
        # provably-replicating psum fallback (parallel/compat.py shim
        # notes — the checker cannot infer replication through the
        # all_gather, so a caller re-enabling check_vma needs this)
        self._gather_via_psum = bool(root.common.engine.get(
            "zero_gather_via_psum", False))
        # quantized collectives (ISSUE 18): resolve BEFORE gather_params
        # — the error-feedback residual leaves must exist in the pytree
        # the specs and programs are built from
        self._codec = qcomm.resolve(self.quantized_collectives)
        self._ef = self._codec is not None and self._codec.error_feedback
        self._params = self.gather_params()
        self._account_zero_memory()
        self._key = self._put(prng.get().key())
        rep, sh = P(), P("data")
        pspecs = self.param_specs()
        train = shard_map(self._local_train, mesh=self.mesh,
                          in_specs=(pspecs, rep, rep, sh, sh, sh),
                          out_specs=(pspecs, rep, rep))
        evalf = shard_map(self._local_eval, mesh=self.mesh,
                          in_specs=(pspecs, sh, sh, sh),
                          out_specs=rep)
        donate = (0, 1) if self.donate else ()
        self._train_fn = jax.jit(train, donate_argnums=donate)
        self._eval_fn = jax.jit(evalf)
        if self.accumulate_steps > 1:
            gradf = shard_map(self._local_grads, mesh=self.mesh,
                              in_specs=(pspecs, rep, sh, sh, sh),
                              out_specs=(rep, rep, rep,
                                         self._res_specs()))
            applyf = shard_map(self._local_apply, mesh=self.mesh,
                               in_specs=(pspecs, rep, rep, rep),
                               out_specs=pspecs)
            self._grad_fn = jax.jit(gradf)
            self._apply_fn = jax.jit(
                applyf, donate_argnums=(0,) if self.donate else ())
        self.anatomy = bool(
            self.anatomy if self.anatomy is not None
            else root.common.engine.get("step_anatomy", False))
        if self.anatomy:
            # split-dispatch diagnostics are a per-minibatch mode: the
            # accumulate/scan paths batch many steps into one dispatch,
            # which a host-stamped split cannot attribute — refuse
            # instead of silently accounting garbage
            if self.accumulate_steps > 1:
                raise ValueError("anatomy (split-dispatch step "
                                 "accounting) requires "
                                 "accumulate_steps == 1")
            self._build_anatomy()
        self._pin_dataset()
        if self._scan_idx_fns:
            # VERDICT r5 item 6: in epoch-scan mode hyperparams are read
            # once per class pass, so a per-MINIBATCH LR schedule would
            # silently coarsen to per-pass granularity — refuse instead
            # of changing training dynamics quietly
            from znicz_tpu.units.lr_adjust import LearningRateAdjust
            gd_ids = {id(gd) for gd in self.gds}
            offenders = [
                u.name for u in (self.workflow.units if self.workflow
                                 else [])
                if isinstance(u, LearningRateAdjust) and not u.by_epoch
                and any(id(gd) in gd_ids for gd, _, _ in u._gd_units)]
            if offenders:
                raise ValueError(
                    f"scan_epoch compiles a whole class pass into one "
                    f"dispatch reading hyperparams once, so the "
                    f"per-minibatch (by_epoch=False) LearningRateAdjust "
                    f"unit(s) {offenders} would silently coarsen to "
                    f"per-pass schedules; use by_epoch=True or disable "
                    f"scan_epoch")
        # telemetry plane: wrap every compiled program so its FIRST call
        # (the trace+compile+run cold path) lands in the
        # znicz_compile_seconds histogram with a compile.cold span —
        # the ROADMAP compile-latency item's baseline — then donate the
        # wrappers to the recompile probe, which polls the REAL
        # compile-cache sizes through them, so an unexpected mid-run
        # recompile lands as a counter increment plus an instant event
        # on the step timeline.  Keyed per INSTANCE (two live steps keep
        # separate watches; the probe holds weakrefs, so a dropped step
        # reaps its own entry) while the metric label stays the class
        # name.
        label = type(self).__name__
        for attr in ("_train_fn", "_eval_fn", "_grad_fn", "_apply_fn",
                     "_train_fn_idx", "_eval_fn_idx", "_grad_fn_idx",
                     "_scan_fn", "_anat_gather_fn", "_anat_grad_fn",
                     "_anat_collective_fn", "_anat_update_fn"):
            fn = getattr(self, attr, None)
            if fn is not None:
                setattr(self, attr, _probe.time_compiles(label, fn))
        self._scan_idx_fns = {k: _probe.time_compiles(label, fn)
                              for k, fn in self._scan_idx_fns.items()}
        fns = [getattr(self, n, None) for n in
               ("_train_fn", "_eval_fn", "_grad_fn", "_apply_fn",
                "_train_fn_idx", "_eval_fn_idx", "_grad_fn_idx",
                "_scan_fn", "_anat_gather_fn", "_anat_grad_fn",
                "_anat_collective_fn", "_anat_update_fn")] + \
            list(self._scan_idx_fns.values())
        _probe.watch_compiles(f"{type(self).__name__}-{id(self):x}",
                              *(f for f in fns if f is not None),
                              label=label)
        self.initialized = True

    def _pin_dataset(self) -> None:
        """Place a full-batch dataset on HBM so the hot loop ships only
        minibatch INDICES — per-step host->device data transfer (the
        dominant cost for image workflows) disappears.  Gated on size
        (``root.common.engine.dataset_on_device_max_bytes``, default 1
        GiB) and on the loader exposing ``original_data``."""
        self._dataset_dev = None
        self._train_fn_idx = self._eval_fn_idx = None
        if self.anatomy:
            # the index-fed/scan fast paths batch work the split cannot
            # attribute; anatomy keeps the standard per-minibatch path
            return
        loader = self.loader
        data_arr, labels_arr, _why = full_batch_arrays(
            loader, mse=isinstance(self.evaluator, EvaluatorMSE))
        if data_arr is None:
            return
        limit = int(root.common.engine.get(
            "dataset_on_device_max_bytes", 1 << 30))
        data = np.asarray(data_arr.mem, np.float32)
        if data.nbytes > limit:
            return
        self._dataset_dev = (
            self._put(data),
            self._put(np.asarray(labels_arr.mem)))
        rep, sh = P(), P("data")
        pspecs = self.param_specs()
        train = shard_map(self._local_train_idx, mesh=self.mesh,
                          in_specs=(pspecs, rep, rep, rep, rep, sh, sh),
                          out_specs=(pspecs, rep, rep))
        evalf = shard_map(self._local_eval_idx, mesh=self.mesh,
                          in_specs=(pspecs, rep, rep, sh, sh),
                          out_specs=rep)
        donate = (0, 1) if self.donate else ()
        self._train_fn_idx = jax.jit(train, donate_argnums=donate)
        self._eval_fn_idx = jax.jit(evalf)
        if self.accumulate_steps > 1:
            gradf = shard_map(self._local_grads_idx, mesh=self.mesh,
                              in_specs=(pspecs, rep, rep, rep, sh, sh),
                              out_specs=(rep, rep, rep,
                                         self._res_specs()))
            self._grad_fn_idx = jax.jit(gradf)
        # the loader now only needs to serve indices — its per-step host
        # gather + device upload of the minibatch would be dead work
        loader.serve_indices_only = True
        if self.scan_epoch is None:
            self.scan_epoch = bool(root.common.engine.get("scan_epoch",
                                                          False))
        if self.scan_epoch and self.accumulate_steps > 1:
            raise ValueError("accumulate_steps > 1 is a per-minibatch "
                             "mode; disable scan_epoch to use it")
        if self.scan_epoch:
            self._build_scan_idx_fns()

    def _build_scan_idx_fns(self) -> None:
        """Class-pass scan programs over the index plan: ONE dispatch per
        class pass (train or eval) — per-minibatch host dispatch latency
        leaves the hot loop entirely."""
        def local_train_many(params, key, hyper, data, labels, idxs, ms):
            def body(carry, inp):
                p, k = carry
                idx, m = inp
                p, k, metrics = self._local_train(p, k, hyper, data[idx],
                                                  labels[idx], m)
                return (p, k), metrics
            (params, key), mets = jax.lax.scan(
                body, (params, key), (idxs, ms))
            return params, key, jax.tree.map(lambda a: a.sum(0), mets)

        def local_eval_many(params, data, labels, idxs, ms):
            def body(_, inp):
                idx, m = inp
                return None, self._local_eval(params, data[idx],
                                              labels[idx], m)
            _, mets = jax.lax.scan(body, None, (idxs, ms))
            return jax.tree.map(lambda a: a.sum(0), mets)

        rep = P()
        shs = P(None, "data")
        pspecs = self.param_specs()
        donate = (0, 1) if self.donate else ()
        self._scan_idx_fns["train"] = jax.jit(shard_map(
            local_train_many, mesh=self.mesh,
            in_specs=(pspecs, rep, rep, rep, rep, shs, shs),
            out_specs=(pspecs, rep, rep)), donate_argnums=donate)
        self._scan_idx_fns["eval"] = jax.jit(shard_map(
            local_eval_many, mesh=self.mesh,
            in_specs=(pspecs, rep, rep, shs, shs),
            out_specs=rep))
        # plan capture costs an int64 matrix per class pass — only pay it
        # when this mode actually consumes it
        self.loader.capture_class_plan = True

    def _build_scan_fn(self):
        """K-step variant: ``lax.scan`` over stacked minibatches inside the
        same shard_map'd program — one dispatch per K steps."""
        def local_many(params, key, hyper, xs, ys, ms):
            def body(carry, inp):
                p, k = carry
                p, k, metrics = self._local_train(p, k, hyper, *inp)
                return (p, k), metrics
            (params, key), mets = jax.lax.scan(
                body, (params, key), (xs, ys, ms))
            return params, key, jax.tree.map(lambda a: a.sum(0), mets)

        rep = P()
        sh = P(None, "data")  # (step, batch, ...): batch axis sharded
        pspecs = self.param_specs()
        fn = shard_map(local_many, mesh=self.mesh,
                       in_specs=(pspecs, rep, rep, sh, sh, sh),
                       out_specs=(pspecs, rep, rep))
        donate = (0, 1) if self.donate else ()
        self._scan_fn = _probe.time_compiles(
            type(self).__name__, jax.jit(fn, donate_argnums=donate))

    def train_steps(self, xs, ys, masks):
        """Run ``xs.shape[0]`` training minibatches in ONE dispatch and
        return the summed metric pytree (device-resident).  ``xs/ys/masks``
        carry a leading step axis over per-step minibatches — the input
        pipeline stages them on device, the compiled program loops.  This
        is the hot path for ms-scale steps, where per-step host dispatch
        latency would otherwise dominate."""
        if self.accumulate_steps > 1:
            raise ValueError("train_steps (K-step scan) applies the "
                             "optimizer per minibatch; accumulate_steps "
                             "> 1 requires the per-minibatch run() path")
        if self._scan_fn is None:
            self._build_scan_fn()
        self._params, self._key, metrics = self._scan_fn(
            self._params, self._key, self._hyper_device(), xs, ys, masks)
        self._note_gathered(int(xs.shape[0]))
        self._note_qcomm_grads(int(xs.shape[0]))
        return metrics

    # -- input-pipeline staging ---------------------------------------------
    def make_stager(self):
        """Producer-side staging callable for the input pipeline
        (znicz_tpu.pipeline): issues the NEXT batch's ``device_put`` with
        this step's input shardings while the current step is still
        executing, so the H2D transfer hides under device compute.
        Signature: ``stage(record, arrays) -> (staged_dict, nbytes)``.

        Ring-slot safety: ``arrays`` come from the loader's rotating
        fill_batch buffers, handed off through
        :func:`~znicz_tpu.pipeline.prefetcher.ring_safe_stager` (copy on
        the aliasing CPU backend, H2D fence on accelerators)."""
        from znicz_tpu.pipeline.prefetcher import ring_safe_stager

        sh = P("data")
        # ONE tuple put: batch, labels/targets and mask ride a single
        # staging call
        safe_put = ring_safe_stager(
            lambda x, y, m: self._put((x, y, m), (sh, sh, sh)))

        def stage(rec, arrays):
            if self._scan_idx_fns:
                # epoch-scan feeding dispatches whole class passes from
                # the captured plan — per-minibatch staging would be
                # dead device buffers (the pipeline still overlaps the
                # shuffle/plan work)
                return None, 0
            mask = rec["indices"] >= 0
            if self._dataset_dev is not None:
                # index-fed mode: only the indices + mask ride H2D (both
                # freshly built per record — no ring slot to protect)
                idx = np.maximum(rec["indices"], 0).astype(np.int32)
                idx_d, mask_d = self._put((idx, mask), (sh, sh))
                return ({"idx": idx_d, "mask": mask_d},
                        idx.nbytes + mask.nbytes)
            x = arrays["data"]
            y = arrays["targets" if isinstance(self.evaluator, EvaluatorMSE)
                       else "labels"]
            x_d, y_d, mask_d = safe_put(x, y, mask)
            return ({"x": x_d, "y": y_d, "mask": mask_d},
                    x.nbytes + y.nbytes + mask.nbytes)

        return stage

    # -- per-minibatch control callback -------------------------------------
    def run(self) -> None:
        loader = self.loader
        # pipelined feeding: the batch (or its indices) was device_put by
        # the prefetch worker with this step's shardings — consume the
        # staged arrays instead of re-shipping the host copies
        staged = loader.take_staged() \
            if getattr(loader, "pipeline", None) is not None else None
        if self._dataset_dev is not None and self._scan_idx_fns and \
                (int(loader.minibatch_offset) == 0 or
                 self._scan_in_flight):
            self._run_scanned_class(loader)
            return
        # (a class pass entered MID-WAY — restored loader state — falls
        # through to the per-minibatch path for the remainder; _acc is
        # NOT a valid in-flight marker because that path sets it too)
        mask = staged["mask"] if staged is not None else \
            loader.minibatch_indices.mem >= 0
        accumulate = self.accumulate_steps > 1
        if self._dataset_dev is not None:
            # index-fed hot path: dataset already on HBM
            idx = staged["idx"] if staged is not None else \
                np.maximum(loader.minibatch_indices.mem, 0).astype(
                    np.int32)
            data, labels_all = self._dataset_dev
            if int(loader.minibatch_class) != TRAIN:
                metrics = self._eval_fn_idx(self._params, data, labels_all,
                                            idx, mask)
            elif accumulate:
                self._key, grads, metrics, new_res = self._grad_fn_idx(
                    self._params, self._key, data, labels_all, idx, mask)
                self._fold_residuals(new_res)
                self._accumulate(grads, metrics, loader)
                self._note_qcomm_grads()
            else:
                self._params, self._key, metrics = self._train_fn_idx(
                    self._params, self._key, self._hyper_device(),
                    data, labels_all, idx, mask)
                self._note_qcomm_grads()
            self._finish_run(loader, metrics)
            return
        if staged is not None:
            x, labels = staged["x"], staged["y"]
        elif isinstance(self.evaluator, EvaluatorMSE):
            x = loader.minibatch_data.mem
            labels = loader.minibatch_targets.mem
        else:
            x = loader.minibatch_data.mem
            labels = loader.minibatch_labels.mem
        if int(loader.minibatch_class) != TRAIN:
            metrics = self._eval_fn(self._params, x, labels, mask)
        elif accumulate:
            self._key, grads, metrics, new_res = self._grad_fn(
                self._params, self._key, x, labels, mask)
            self._fold_residuals(new_res)
            self._accumulate(grads, metrics, loader)
            self._note_qcomm_grads()
        elif self._anatomy is not None:
            metrics = self._run_anatomy_step(x, labels, mask)
            self._note_qcomm_grads()
        else:
            self._params, self._key, metrics = self._train_fn(
                self._params, self._key, self._hyper_device(),
                x, labels, mask)
            self._note_qcomm_grads()
        self._finish_run(loader, metrics)

    def _fold_residuals(self, new_res) -> None:
        """Persist the residual updates returned by a ``_grad_fn``
        half-step into the params pytree (the full-step path folds them
        inside the compiled program; the accumulation path returns them
        because the apply is deferred)."""
        if new_res is not None:
            for leaf, nr in zip(self._params, new_res):
                leaf.update(nr)

    def _accumulate(self, grads, metrics, loader) -> None:
        """Fold one half-step's summed grads into the device accumulator;
        apply the optimizer every ``accumulate_steps`` train minibatches
        and at the END of a train pass (a ragged tail must not leak into
        the next epoch's first effective batch)."""
        bs = metrics["bs"]
        if self._grad_acc is None:
            self._grad_acc = grads
            self._bs_acc = bs
        else:
            self._grad_acc = jax.tree.map(jnp.add, self._grad_acc, grads)
            self._bs_acc = self._bs_acc + bs
        self._acc_count += 1
        if self._acc_count >= self.accumulate_steps or \
                loader.last_minibatch:
            self._params = self._apply_fn(
                self._params, self._hyper_device(), self._grad_acc,
                self._bs_acc)
            self._grad_acc = None
            self._bs_acc = None
            self._acc_count = 0

    def _run_scanned_class(self, loader) -> None:
        """Epoch-scan mode: the FIRST minibatch of a class pass dispatches
        the whole pass as one scanned program; the control loop keeps
        iterating (the loader serves indices cheaply) and the summed
        metrics land at the last minibatch — the same "virtual minibatch"
        the Decision already sees in deferred mode."""
        if int(loader.minibatch_offset) == 0:
            from znicz_tpu.loader.base import plan_device_arrays
            idxs, ms = plan_device_arrays(loader.class_plan())
            data, labels = self._dataset_dev
            if int(loader.minibatch_class) == TRAIN:
                self._params, self._key, metrics = \
                    self._scan_idx_fns["train"](
                        self._params, self._key, self._hyper_device(),
                        data, labels, idxs, ms)
                self._note_qcomm_grads(int(idxs.shape[0]))
            else:
                metrics = self._scan_idx_fns["eval"](
                    self._params, data, labels, idxs, ms)
            self._note_gathered(int(idxs.shape[0]))
            self._acc = metrics
            self._scan_in_flight = True
        if loader.last_minibatch:
            self._publish(jax.device_get(self._acc), cumulative=True)
            self._acc = None
            self._conf_seen = None
            self._scan_in_flight = False
            self._publish_residual_norm()
        else:
            self.n_err = 0
            self.mse = 0.0
            self.loss = 0.0
            self.minibatch_size = 0

    def _finish_run(self, loader, metrics) -> None:
        # one dispatch (train, grads half-step, or eval) = one on-demand
        # full-weight regather under shard_params
        self._note_gathered()
        if loader.last_minibatch:
            self._publish_residual_norm()
        # chaos hook (site "step.params"): NaN-poisons the param pytree —
        # the observable effect of NaN gradients — so health-guard and
        # rollback paths are exercised against the real fused step
        self._params = poison_hook("step.params", self._params)
        if not self.defer_metrics:
            self._publish(jax.device_get(metrics))
            return
        # deferred mode: fold into the device-side accumulator (async tiny
        # adds, no host sync) and only fetch at the end of the class pass
        self._acc = metrics if self._acc is None else \
            jax.tree.map(jnp.add, self._acc, metrics)
        if loader.last_minibatch:
            self._publish(jax.device_get(self._acc), cumulative=True)
            self._acc = None
            self._conf_seen = None
        else:
            # non-final minibatches contribute zero to the Decision's
            # accumulators; the class-pass totals land in one shot above
            self.n_err = 0
            self.mse = 0.0
            self.loss = 0.0
            self.minibatch_size = 0

    def _publish(self, sums, cumulative: bool = False) -> None:
        """Write (host) metric sums into the attrs the Decision reads.

        ``cumulative=True`` marks sums that cover the class pass SO FAR
        (the deferred/scan accumulator) rather than one minibatch — the
        confusion matrix folds only the delta since the last publish, so
        a mid-pass ``flush_metrics`` never double-counts."""
        bs = float(sums["bs"])
        self.minibatch_size = int(bs)
        # chaos hook (site "step.loss"): NaN into the published loss
        self.loss = poison_hook("step.loss", float(sums["loss"]))
        if "n_err" in sums:
            self.n_err = int(sums["n_err"])
        if "mse_sum" in sums:
            self.mse = float(sums["mse_sum"]) / max(bs, 1.0)
        if "confusion" in sums and \
                getattr(self.evaluator, "confusion_matrix", None) is not None:
            # accumulate like the eager evaluator; the Decision copies and
            # zeroes the matrix at each class-pass end (finalize_class)
            conf = np.rint(np.asarray(sums["confusion"])).astype(np.int64)
            if cumulative:
                delta = conf if self._conf_seen is None else \
                    conf - self._conf_seen
                self._conf_seen = conf
            else:
                delta = conf
            self.evaluator.confusion_matrix += delta

    def flush_metrics(self) -> None:
        """Sync pending deferred sums into the host mirrors (probe/debug
        path; the training loop flushes itself per class).  ``_acc`` is NOT
        reset — the class pass keeps accumulating, so a mid-pass flush never
        truncates the Decision's epoch accounting."""
        if self._acc is not None:
            self._publish(jax.device_get(self._acc), cumulative=True)

    def stop(self) -> None:
        if self._params is not None:
            self.sync_to_units()
