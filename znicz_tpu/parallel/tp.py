"""Tensor-parallel linear layers over the ``model`` mesh axis
(SURVEY.md §3.4 "natural extension via jax.sharding on weight dims" —
Megatron column/row pattern expressed for shard_map).

- ``column_parallel``: W sharded on the output dim; each device computes
  its slice of the features.  No communication (the activation stays
  feature-sharded).
- ``row_parallel``: W sharded on the input dim, activation feature-sharded
  from the previous column layer; partial products are ``psum``ed back to
  replicated.  One ICI all-reduce per layer pair — the Megatron MLP shape.
"""

from __future__ import annotations

from jax import lax


def column_parallel(x, w_local, b_local=None):
    """x replicated ``(..., d_in)``; w_local ``(d_in, d_out/tp)`` ->
    feature-sharded ``(..., d_out/tp)``."""
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel(x_local, w_local, b=None, axis_name: str = "model"):
    """x_local feature-sharded ``(..., d_in/tp)``; w_local
    ``(d_in/tp, d_out)`` -> replicated ``(..., d_out)`` via one psum.
    ``b`` must be replicated (added once, after the reduce)."""
    y = lax.psum(x_local @ w_local, axis_name)
    if b is not None:
        y = y + b
    return y


def mlp(x, w1_local, b1_local, w2_local, b2, act, axis_name: str = "model"):
    """Megatron MLP: column-parallel + activation + row-parallel."""
    h = act(column_parallel(x, w1_local, b1_local))
    return row_parallel(h, w2_local, b2, axis_name)
