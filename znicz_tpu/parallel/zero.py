"""ZeRO-style sharding primitives (Xu et al. 2020, arXiv:2004.13336) —
THE one copy of the pad/slice/regather logic shared by the fused
workflow step (parallel/step.py) and the sharded transformer step
(parallel/transformer.py).

Two regather flavors exist because of the shard_map vma type system:

- ``psum_regather`` reassembles disjoint per-replica slices through a
  psum over a zero buffer.  psum PROVABLY yields a replicated value
  under the replication checker, so P() out_specs type-check — but it
  moves (and adds) n× the bytes of the payload.
- ``all_gather_slices`` concatenates the aligned disjoint slices with
  ONE ``lax.all_gather(tiled=True)`` — the bytes-on-wire-proportional
  path used by the persistent-parameter mode (``shard_params``), where
  full weights materialize on demand per leaf.  The replication checker
  cannot infer replication through it on the container's jax versions;
  the compat shim (parallel/compat.py) runs with the checker disabled,
  and ``via_psum=True`` keeps the provably-replicating fallback one
  keyword away for callers that re-enable it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pad_slice(x, rank, n: int):
    """This replica's 1/n slice of ``x`` flattened and zero-padded to a
    multiple of ``n``.  ``rank`` may be traced (lax.axis_index).  The
    pad is skipped entirely when ``x.size`` already divides by ``n`` —
    the common aligned case must not pay a copy for a no-op."""
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = flat.shape[0] // n
    return jax.lax.dynamic_slice(flat, (rank * shard,), (shard,))


def psum_regather(shard, rank, n: int, axis_name: str, like):
    """Disjoint per-replica slices -> the full array of ``like``'s shape,
    replicated (each replica writes its slice into a zero buffer at its
    offset; the psum sums the disjoint contributions).  ``like`` only
    needs ``.size``/``.shape`` (an array or a ShapeDtypeStruct)."""
    size = shard.shape[0]
    buf = jnp.zeros((size * n,), shard.dtype)
    buf = jax.lax.dynamic_update_slice(buf, shard, (rank * size,))
    full = jax.lax.psum(buf, axis_name)
    return full[:like.size].reshape(like.shape)


def all_gather_slices(shard, rank, n: int, axis_name: str, like,
                      via_psum: bool = False, codec=None):
    """Disjoint per-replica flat slices -> the full array of ``like``'s
    shape, replicated, via one concatenating ``lax.all_gather`` —
    payload-proportional bytes on the wire, no zero buffer and no adds.
    Slices must be the aligned ``pad_slice`` layout (rank-ordered, equal
    length, zero-padded tail).  ``via_psum=True`` routes through
    :func:`psum_regather` instead — the vma-safe fallback for callers
    running with the replication checker enabled (parallel/compat.py
    disables it by default, which is what lets the all_gather path
    type-check).  ``codec`` (a qcomm.Codec) ships each slice quantized
    (int8/bf16 + chunk scales) and dequantizes on arrival — it implies
    the all_gather wire format, so it overrides ``via_psum``; ``None``
    keeps this exact path untouched."""
    if codec is not None:
        from znicz_tpu.parallel import qcomm
        return qcomm.gather_slices(shard, rank, n, axis_name, like,
                                   codec)
    if via_psum:
        return psum_regather(shard, rank, n, axis_name, like)
    full = jax.lax.all_gather(shard, axis_name, tiled=True)
    return full[:like.size].reshape(like.shape)


def gather_chain(shards, likes, rank, n: int, axis_name: str,
                 via_psum: bool = False, codec=None):
    """Materialize a list of full arrays from their per-replica slices —
    the ``shard_params`` on-demand regather chain.  Each leaf gets its
    OWN collective, dispatched in consumption order ahead of the forward
    that consumes it: the gathers carry no data dependency on the
    downstream compute, so XLA's async-collective scheduling overlaps
    leaf i+1's gather with leaf i's compute (the ring_attention
    overlap effect — K/V blocks in flight while the current block's
    scores compute — applied to the parameter gather chain; one fused
    whole-tree gather would serialize instead).  ``codec`` quantizes
    every slice on the wire (per-leaf collectives keep their no-data-
    dependency shape, so the dispatch-ahead overlap is preserved)."""
    return [all_gather_slices(s, rank, n, axis_name, like,
                              via_psum=via_psum, codec=codec)
            for s, like in zip(shards, likes)]
