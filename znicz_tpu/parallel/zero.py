"""ZeRO-style update-sharding primitives (Xu et al. 2020,
arXiv:2004.13336) — THE one copy of the pad/slice/psum-reassembly logic
shared by the fused workflow step (parallel/step.py) and the sharded
transformer step (parallel/transformer.py).

``psum_regather`` reassembles disjoint per-replica slices through a psum
rather than an all_gather because psum PROVABLY yields a replicated
value under shard_map's vma type system, so P() out_specs type-check.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pad_slice(x, rank, n: int):
    """This replica's 1/n slice of ``x`` flattened and zero-padded to a
    multiple of ``n``.  ``rank`` may be traced (lax.axis_index)."""
    flat = x.reshape(-1)
    flat = jnp.pad(flat, (0, (-flat.shape[0]) % n))
    shard = flat.shape[0] // n
    return jax.lax.dynamic_slice(flat, (rank * shard,), (shard,))


def psum_regather(shard, rank, n: int, axis_name: str, like):
    """Disjoint per-replica slices -> the full array of ``like``'s shape,
    replicated (each replica writes its slice into a zero buffer at its
    offset; the psum sums the disjoint contributions)."""
    size = shard.shape[0]
    buf = jnp.zeros((size * n,), shard.dtype)
    buf = jax.lax.dynamic_update_slice(buf, shard, (rank * size,))
    full = jax.lax.psum(buf, axis_name)
    return full[:like.size].reshape(like.shape)
