"""Expert-parallel mixture-of-experts FFN over the ``expert`` mesh axis
(TPU-native extension; the reference has no MoE — SURVEY.md §3.4 EP row).

Two regimes (docs/TUNING.md "MoE"):

- :func:`moe_ffn` — tokens REPLICATED over the expert axis: dense
  masked compute (each device runs its local experts over all tokens,
  one psum combines), exact for top-1 switch routing and GShard
  renormalized top-k, at E_local× arithmetic per token.
- :func:`moe_ffn_dispatch` — tokens SHARDED over the expert axis: the
  all_to_all token-dispatch path (each token computes once, on its
  expert's device; capacity overflow drops, switch semantics).

:func:`load_balance_aux` is the shared switch load-balance regularizer.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(x, gate_w, w1_local, b1_local, w2_local, b2_local,
            act, axis_name: str = "expert", top_k: int = 1):
    """x ``(tokens, d)`` replicated over the expert axis; ``gate_w``
    ``(d, n_experts_total)`` replicated; ``w1_local`` ``(e_local, d, ff)``,
    ``w2_local`` ``(e_local, ff, d)`` expert-sharded.  Returns replicated
    ``(tokens, d)`` plus the (replicated) gate distribution for load-
    balancing diagnostics.

    ``top_k=1`` is switch routing (winner scaled by its raw softmax
    prob); ``top_k≥2`` is GShard-style: the k winners' probs are
    RENORMALIZED to sum to 1 and their expert outputs combine
    weighted."""
    my_idx = lax.axis_index(axis_name)
    e_local = w1_local.shape[0]
    scores = x @ gate_w                          # (tokens, E)
    gate_probs = jax.nn.softmax(scores, axis=-1)
    _, choice_k = lax.top_k(scores, top_k)       # (tokens, k)
    gate_k = jnp.take_along_axis(gate_probs, choice_k, axis=1)  # (t, k)
    if top_k > 1:
        gate_k = gate_k / gate_k.sum(axis=-1, keepdims=True)
    # local expert ids: my_idx*e_local .. +e_local
    local_ids = my_idx * e_local + jnp.arange(e_local)
    # (e_local, tokens): this local expert's combined gate weight per
    # token (0 when the token routed elsewhere)
    sel = (choice_k[None, :, :] ==
           local_ids[:, None, None])             # (e_local, t, k)
    wgt = (sel.astype(x.dtype) * gate_k[None, :, :]).sum(-1)
    h = act(jnp.einsum("td,edf->etf", x, w1_local) + b1_local[:, None, :])
    y_e = jnp.einsum("etf,efd->etd", h, w2_local) + b2_local[:, None, :]
    y_local = (y_e * wgt[:, :, None]).sum(axis=0)
    return lax.psum(y_local, axis_name), gate_probs


def router_z_loss(scores):
    """ST-MoE router z-loss (arXiv:2202.08906 eq. 5) over the LOCAL
    tokens: mean of ``logsumexp(scores)²`` — penalizes large router
    logits, whose drift destabilizes bf16 MoE training long before the
    balance aux notices.  f32 regardless of compute dtype."""
    z = jax.nn.logsumexp(scores.astype(jnp.float32), axis=-1)
    return (z * z).mean()


def load_balance_aux(gate_probs):
    """Switch-transformer load-balance auxiliary (arXiv:2101.03961
    eq. 4) over the LOCAL tokens: ``E · Σ_e f_e·P_e`` with ``f`` the
    top-1 routed fraction (argmax-derived — gradients flow through the
    mean gate prob ``P`` only) — minimized (=1) at uniform routing.
    f32 regardless of the compute dtype."""
    n_exp = gate_probs.shape[-1]
    pf = gate_probs.astype(jnp.float32)
    f = jnp.mean(jax.nn.one_hot(pf.argmax(-1), n_exp,
                                dtype=jnp.float32), axis=0)
    return n_exp * (f * pf.mean(axis=0)).sum()


def moe_ffn_dispatch(x, gate_w, w1_local, b1_local, w2_local, b2_local,
                     act, axis_name: str = "expert",
                     capacity_factor: float = 2.0, top_k: int = 1):
    """Token-dispatch MoE FFN for the TOKEN-SHARDED regime (the
    all_to_all optimization :func:`moe_ffn`'s docstring plans): ``x``
    ``(tokens_local, d)`` is sharded over ``axis_name`` (each device
    holds its own tokens AND ``e_local`` experts).  Routed tokens
    travel to their expert's device and back with two ``lax.all_to_all``
    exchanges — each token is computed ONCE, by one expert, instead of
    the dense-masked path's E_local× arithmetic.

    Mesh-TensorFlow dispatch formulation (einsum with a
    ``(tokens, E, capacity)`` one-hot — MXU-friendly, no scatters):
    per-expert buckets have ``capacity = ceil(capacity_factor ·
    tokens_local · top_k / E)`` slots per SOURCE device; a
    (token, choice) pair past its expert's capacity is DROPPED
    (contributes zero output — the standard switch-transformer overflow
    semantics; size ``capacity_factor`` for the expected imbalance, or
    set it ≥ E/top_k for provably lossless routing).  Gradients flow
    through both all_to_alls back to x, the gate, and the owning
    expert's weights.

    ``top_k≥2`` routes each token to its k best experts with
    GShard-renormalized combine weights (same semantics as
    :func:`moe_ffn`); the token then occupies up to k bucket slots and
    ``capacity`` scales by k.  Returns ``(y_local (tokens_local, d),
    gate_probs)`` — both sharded like ``x``."""
    n_dev = lax.psum(1, axis_name)
    tokens, d = x.shape
    e_local = w1_local.shape[0]
    n_experts = n_dev * e_local
    scores = x @ gate_w                          # (t, E)
    gate_probs = jax.nn.softmax(scores, axis=-1)
    _, choice_k = lax.top_k(scores, top_k)       # (t, k)
    gate_k = jnp.take_along_axis(gate_probs, choice_k, axis=1)  # (t, k)
    if top_k > 1:
        gate_k = gate_k / gate_k.sum(axis=-1, keepdims=True)
    capacity = int(np.ceil(capacity_factor * tokens * top_k /
                           n_experts))
    # bucket positions over ALL (token, choice) pairs, token-major with
    # the k choices inner — each pair claims its own slot
    cf = choice_k.reshape(-1)                    # (t·k,)
    onehot = jax.nn.one_hot(cf, n_experts, dtype=jnp.int32)  # (t·k, E)
    pos = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - onehot,
                              cf[:, None], axis=1)[:, 0]   # (t·k,) int
    keep = (pos < capacity).astype(x.dtype)
    # (t·k, E, C) slot one-hots -> (t, k, E, C)
    mask_k = (onehot.astype(x.dtype)[:, :, None] *
              jax.nn.one_hot(pos, capacity, dtype=x.dtype)[:, None, :] *
              keep[:, None, None]).reshape(tokens, top_k, n_experts,
                                           capacity)
    # slots are distinct across k, so the binary send mask is the sum
    mask = mask_k.sum(axis=1)                    # (t, E, C) dispatch
    comb = (mask_k * gate_k[:, :, None, None]).sum(axis=1)  # combine
    disp = jnp.einsum("tec,td->ecd", mask, x)    # (E, C, d)
    # -> (n_dev, e_local, C, d); all_to_all swaps the leading device dim
    # so each device receives its OWN experts' buckets from every source
    disp = disp.reshape(n_dev, e_local, capacity, d)
    recv = lax.all_to_all(disp, axis_name, split_axis=0, concat_axis=0)
    # expert compute over (n_src * C) tokens per local expert
    xin = recv.transpose(1, 0, 2, 3).reshape(e_local,
                                             n_dev * capacity, d)
    h = act(jnp.einsum("etd,edf->etf", xin, w1_local) +
            b1_local[:, None, :])
    y = jnp.einsum("etf,efd->etd", h, w2_local) + b2_local[:, None, :]
    y = y.reshape(e_local, n_dev, capacity, d).transpose(1, 0, 2, 3)
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0)
    res = back.reshape(n_experts, capacity, d)   # MY tokens' results
    out = jnp.einsum("tec,ecd->td", comb, res)
    return out, gate_probs
