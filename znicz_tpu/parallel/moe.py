"""Expert-parallel mixture-of-experts FFN over the ``expert`` mesh axis
(TPU-native extension; the reference has no MoE — SURVEY.md §3.4 EP row).

v1 semantics: top-1 gating with dense masked compute — each device runs
its *local* experts over all tokens, masks by the gate's one-hot choice,
and a single ``psum`` over the expert axis combines the winners.  This is
exact top-1 MoE (identical to dispatch-based routing) at the cost of
E_local x compute per token; an all_to_all token-dispatch path is the
planned optimization and slots behind the same function signature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(x, gate_w, w1_local, b1_local, w2_local, b2_local,
            act, axis_name: str = "expert"):
    """x ``(tokens, d)`` replicated over the expert axis; ``gate_w``
    ``(d, n_experts_total)`` replicated; ``w1_local`` ``(e_local, d, ff)``,
    ``w2_local`` ``(e_local, ff, d)`` expert-sharded.  Returns replicated
    ``(tokens, d)`` plus the (replicated) gate distribution for load-
    balancing diagnostics."""
    my_idx = lax.axis_index(axis_name)
    e_local = w1_local.shape[0]
    scores = x @ gate_w                          # (tokens, E)
    gate_probs = jax.nn.softmax(scores, axis=-1)
    choice = scores.argmax(axis=-1)              # (tokens,)
    # local expert ids: my_idx*e_local .. +e_local
    local_ids = my_idx * e_local + jnp.arange(e_local)
    # (e_local, tokens) one-hot of "token routed to this local expert"
    sel = (choice[None, :] == local_ids[:, None]).astype(x.dtype)
    gate_val = jnp.take_along_axis(gate_probs, choice[:, None],
                                   axis=1)[:, 0]  # (tokens,)
    h = act(jnp.einsum("td,edf->etf", x, w1_local) + b1_local[:, None, :])
    y_e = jnp.einsum("etf,efd->etd", h, w2_local) + b2_local[:, None, :]
    y_local = (y_e * sel[:, :, None]).sum(axis=0) * gate_val[:, None]
    return lax.psum(y_local, axis_name), gate_probs
