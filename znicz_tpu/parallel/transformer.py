"""Long-context sharded transformer — the flagship multi-axis SPMD model
(TPU-native extension; the task treats long-context + distributed as
first-class even though the reference predates transformers, SURVEY.md
§6.7).

One ``shard_map``-ped training step over a ``(data, seq, model)`` mesh:

- batch sharded over ``data`` (DP) — gradients reduce via the loss psum;
- sequence sharded over ``seq`` (SP) — exact ring attention rotates K/V
  blocks over ICI (znicz_tpu.parallel.ring_attention);
- attention heads + MLP hidden sharded over ``model`` (TP) — Megatron
  column/row pattern, one psum per block half (znicz_tpu.parallel.tp).

``make_pipeline_step`` provides the complementary ``(data, pipe, expert)``
configuration: GPipe microbatching over ``pipe`` with expert-parallel MoE
blocks over ``expert`` (znicz_tpu.parallel.{pipeline,moe}).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from znicz_tpu.parallel.compat import quantized_psum, shard_map

from znicz_tpu.parallel import qcomm
from znicz_tpu.parallel.moe import (load_balance_aux, moe_ffn,
                                    router_z_loss)
from znicz_tpu.parallel.pipeline import pipeline_apply
from znicz_tpu.parallel.ring_attention import (ring_attention,
                                               ring_flash_attention)
from znicz_tpu.parallel import tp, zero


def _layer_norm(x, g, b, eps=1e-5):
    # stats in f32 regardless of the compute dtype (bf16 mean/var loses
    # ~3 decimal digits); the normalized result returns to x.dtype so the
    # surrounding matmuls stay on the MXU's bf16 path
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = ((xf - mu) / jnp.sqrt(var + eps)).astype(x.dtype)
    return y * g + b


def _flash_eligible(mesh: Mesh, interpret: bool) -> bool:
    """Use the Pallas flash kernel when the seq axis is unsharded (the
    ring handles sharded time) on a TPU-family backend (the sandbox chip
    reports platform ``axon``); per-shape limits are checked at trace
    time by ops.pallas.attention.supported.
    ``root.common.engine.flash_attention`` (default True) turns it off;
    ``interpret`` (the pallas_interpret flag, captured once at step-build
    time) forces it ON for the Pallas interpreter — but only on a
    SINGLETON mesh, because interpret mode needs ``check_vma=False``
    whose altered psum transposition is only harmless at axis size 1."""
    from znicz_tpu.core.config import root
    if not bool(root.common.engine.get("flash_attention", True)):
        return False
    if mesh.shape.get("seq", 1) != 1:
        return False
    if interpret:
        return all(s == 1 for s in mesh.shape.values())
    return jax.default_backend() in ("tpu", "axon")


def _ring_flash_eligible(mesh: Mesh, interpret: bool) -> bool:
    """Flash-in-ring (ring_flash_attention) for a SHARDED seq axis: the
    kernel runs per ring step on (t_loc × t_loc) blocks and results
    merge by lse weight.  Same ``flash_attention`` flag; compiled TPU
    backends only — interpret mode must be opted into explicitly
    (``engine.ring_flash_interpret``, used by the parity tests; the
    vma checker those runs would trip is disabled by the
    parallel/compat.py shard_map shim)."""
    from znicz_tpu.core.config import root
    if not bool(root.common.engine.get("flash_attention", True)):
        return False
    if mesh.shape.get("seq", 1) == 1:
        return False
    if interpret:
        return bool(root.common.engine.get("ring_flash_interpret", False))
    return jax.default_backend() in ("tpu", "axon")


def _default_compute_dtype(compute_dtype=None):
    """Explicit dtype wins; None defers to the framework-wide precision
    policy (core.backends.resolve_compute_dtype) for this process's
    default backend.  (Named differently from the backends policy on
    purpose — its first argument is a dtype, not a platform string.)"""
    if compute_dtype is not None:
        return compute_dtype
    from znicz_tpu.core.backends import resolve_compute_dtype as policy
    return policy(jax.default_backend())


# -- dp x sp x tp flagship --------------------------------------------------
def init_params(gen, n_layers: int, d: int, heads: int, ff: int,
                vocab: int, n_experts: int | None = None):
    """Global (unsharded) parameter pytree from the framework PRNG.
    ``n_experts`` swaps each block's dense FFN for a top-1 MoE FFN
    (gate + per-expert w1/b1/w2/b2 stacks, expert-sharded over the
    ``model`` axis at placement time)."""
    def w(shape, scale=None):
        scale = scale or 1.0 / np.sqrt(shape[-2] if len(shape) > 1
                                       else shape[0])
        return gen.normal(0.0, scale, shape).astype(np.float32)

    blocks = []
    for _ in range(n_layers):
        blk = {
            "ln1_g": np.ones(d, np.float32), "ln1_b": np.zeros(d, np.float32),
            "wq": w((d, d)), "wk": w((d, d)), "wv": w((d, d)), "wo": w((d, d)),
            "ln2_g": np.ones(d, np.float32), "ln2_b": np.zeros(d, np.float32),
        }
        if n_experts:
            blk.update({
                "gate": w((d, n_experts)),
                "ew1": w((n_experts, d, ff)),
                "eb1": np.zeros((n_experts, ff), np.float32),
                "ew2": w((n_experts, ff, d)),
                "eb2": np.zeros((n_experts, d), np.float32),
            })
        else:
            blk.update({
                "w1": w((d, ff)), "b1": np.zeros(ff, np.float32),
                "w2": w((ff, d)), "b2": np.zeros(d, np.float32),
            })
        blocks.append(blk)
    return {"emb": w((vocab, d), 0.02), "head": w((d, vocab)),
            "blocks": blocks}


def param_specs(n_layers: int, head_sharded: bool = False,
                moe: bool = False):
    """PartitionSpecs matching init_params: attention qkv column-sharded,
    wo row-sharded, MLP Megatron-sharded over ``model``; the rest
    replicated.  ``head_sharded`` vocab-shards the LM head over
    ``model`` (Megatron parallel cross-entropy — pair with
    ``make_train_step(head_sharded=True)``).  ``moe`` selects the
    expert-parallel FFN layout: expert stacks sharded over ``model`` on
    the expert dim, gate replicated."""
    blk = {
        "ln1_g": P(), "ln1_b": P(),
        "wq": P(None, "model"), "wk": P(None, "model"),
        "wv": P(None, "model"), "wo": P("model", None),
        "ln2_g": P(), "ln2_b": P(),
    }
    if moe:
        blk.update({
            "gate": P(),
            "ew1": P("model", None, None), "eb1": P("model", None),
            "ew2": P("model", None, None), "eb2": P("model", None),
        })
    else:
        blk.update({
            "w1": P(None, "model"), "b1": P("model"),
            "w2": P("model", None), "b2": P(),
        })
    head = P(None, "model") if head_sharded else P()
    return {"emb": P(), "head": head, "blocks": [dict(blk)] * n_layers}


def param_shapes(n_layers: int, d: int, ff: int, vocab: int,
                 n_experts: int | None = None):
    """Shape pytree mirroring :func:`init_params` — the static ``like``
    information the shard_params gather chain needs (a flat-sharded
    leaf has lost its original shape)."""
    blk = {
        "ln1_g": (d,), "ln1_b": (d,),
        "wq": (d, d), "wk": (d, d), "wv": (d, d), "wo": (d, d),
        "ln2_g": (d,), "ln2_b": (d,),
    }
    if n_experts:
        blk.update({
            "gate": (d, n_experts),
            "ew1": (n_experts, d, ff), "eb1": (n_experts, ff),
            "ew2": (n_experts, ff, d), "eb2": (n_experts, d),
        })
    else:
        blk.update({"w1": (d, ff), "b1": (ff,),
                    "w2": (ff, d), "b2": (d,)})
    return {"emb": (vocab, d), "head": (d, vocab),
            "blocks": [dict(blk)] * n_layers}


def _spec_leaves(specs):
    # PartitionSpec is a tuple subclass (a pytree container), so spec
    # trees flatten with an is_leaf guard (same trick as local_step)
    return jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))


def _shape_leaves(shapes):
    return jax.tree.leaves(shapes,
                           is_leaf=lambda x: isinstance(x, tuple))


def shard_params_specs(specs):
    """Layout of a ``shard_params`` step's params: every REPLICATED
    (``P()``) leaf becomes a flat array sharded ``P("data")``;
    tensor-sharded leaves keep their specs (they already live
    partitioned)."""
    return jax.tree.map(lambda s: P("data") if s == P() else s, specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params_host(params, specs, n: int):
    """Host-side conversion INTO the shard_params layout: replicated
    leaves flatten and zero-pad to a multiple of ``n`` (place them with
    :func:`shard_params_specs`); tensor-sharded leaves pass through.
    ``specs`` is the REPLICATED-layout tree (:func:`param_specs`)."""
    flat_w, treedef = jax.tree.flatten(params)
    out = []
    for w, s in zip(flat_w, _spec_leaves(specs)):
        if s == P():
            f = np.asarray(w).reshape(-1)
            pad = (-f.size) % n
            if pad:
                f = np.pad(f, (0, pad))
            out.append(f)
        else:
            out.append(w)
    return jax.tree.unflatten(treedef, out)


def unshard_params_host(params, specs, shapes):
    """Inverse of :func:`shard_params_host` on host arrays (the caller
    ``jax.device_get``s first): flat-padded leaves slice back to their
    original shapes from the :func:`param_shapes` tree."""
    flat_w, treedef = jax.tree.flatten(params)
    out = []
    for w, s, shp in zip(flat_w, _spec_leaves(specs),
                         _shape_leaves(shapes)):
        if s == P():
            size = int(np.prod(shp))
            out.append(np.asarray(w).reshape(-1)[:size].reshape(shp))
        else:
            out.append(np.asarray(w))
    return jax.tree.unflatten(treedef, out)


def _block(x, p, heads_local: int, causal: bool, use_flash: bool = False,
           interpret: bool = False, use_ring_flash: bool = False,
           moe_top_k: int = 1, moe_aux_weight: float = 0.0,
           moe_zloss_weight: float = 0.0):
    """One transformer block on local shards: ring attention (seq axis)
    with tp-sharded heads, then Megatron MLP (model axis).  With the seq
    axis unsharded, ``use_flash`` swaps the attention core for the Pallas
    flash kernel (ops/pallas/attention.py) — same math, no (t, t) score
    matrix in HBM.  ``interpret`` is captured at step-build time along
    with ``use_flash`` so one config snapshot governs all three
    flash-related decisions (kernel choice, interpreter, vma mode)."""
    h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
    b, t_loc, _ = h.shape

    def heads_of(w):
        y = h @ w                                    # (b, t_loc, d_local)
        return y.reshape(b, t_loc, heads_local, -1)

    q, k, v = heads_of(p["wq"]), heads_of(p["wk"]), heads_of(p["wv"])
    from znicz_tpu.ops.pallas import attention as pattn
    if use_flash and pattn.supported(t_loc, q.shape[-1]):
        o = pattn.flash_attention(q, k, v, causal=causal,
                                  interpret=interpret)
    elif use_ring_flash and pattn.supported(t_loc, q.shape[-1]):
        o = ring_flash_attention(q, k, v, "seq", causal=causal,
                                 interpret=interpret)
    else:
        o = ring_attention(q, k, v, "seq", causal=causal)
    o = o.reshape(b, t_loc, -1)                      # (b, t_loc, d_local)
    x = x + tp.row_parallel(o, p["wo"], None, "model")
    m = _layer_norm(x, p["ln2_g"], p["ln2_b"])
    if "ew1" in p:
        # expert-parallel MoE FFN over the model axis (the block's FFN
        # capacity scales with experts instead of Megatron-splitting ff)
        d = m.shape[-1]
        m2d = m.reshape(-1, d)
        y2d, probs = moe_ffn(m2d, p["gate"], p["ew1"],
                             p["eb1"], p["ew2"], p["eb2"],
                             jax.nn.gelu, axis_name="model",
                             top_k=moe_top_k)
        x = x + y2d.reshape(m.shape)
        # regularizers pre-weighted here (weights are static floats), so
        # the accumulator upstream stays a single scalar.  The z-loss's
        # scores GEMM is identical to moe_ffn's internal one — XLA CSEs
        # them under jit
        aux = moe_aux_weight * load_balance_aux(probs)
        if moe_zloss_weight:
            aux = aux + moe_zloss_weight * router_z_loss(m2d @ p["gate"])
        return x, aux
    x = x + tp.mlp(m, p["w1"], p["b1"], p["w2"], p["b2"],
                   jax.nn.gelu, "model")
    return x, jnp.zeros((), jnp.float32)


def _check_tp(mesh: Mesh, heads: int, d: int, ff: int,
              vocab_sharded: int | None = None,
              n_experts: int | None = None) -> int:
    tp_size = mesh.shape["model"]
    if heads % tp_size or d % tp_size:
        raise ValueError(f"tp={tp_size} must divide heads={heads} "
                         f"and d={d}")
    # the MoE FFN shards the EXPERT dim, never ff; the dense FFN
    # Megatron-splits ff
    if n_experts:
        if n_experts % tp_size:
            raise ValueError(f"n_experts={n_experts} must divide by "
                             f"tp={tp_size}")
    elif ff % tp_size:
        raise ValueError(f"tp={tp_size} must divide ff={ff}")
    if vocab_sharded is not None and vocab_sharded % tp_size:
        raise ValueError(f"head_sharded needs vocab={vocab_sharded} "
                         f"divisible by tp={tp_size}")
    return heads // tp_size


def _dense_chunk_nll(head):
    """-> chunk fn: Σ w·(-log p[label]) from replicated-head logits."""
    @jax.checkpoint
    def chunk_nll(xc, lc, wc):
        logits = (xc @ head).astype(jnp.float32)     # (chunk, vocab)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, lc[:, None], axis=-1)[:, 0]
        return (-picked * wc).sum()
    return chunk_nll


def _vshard_chunk_nll(head_local, axis_name: str = "model"):
    """-> chunk fn for a VOCAB-SHARDED head (Megatron parallel cross
    entropy, arXiv:1909.08053 §3): each model shard computes its
    ``(chunk, vocab/n)`` logit columns; the stable-softmax max and the
    sum-exp reduce with one pmax + one psum, and the label's logit
    comes from its owning shard via a masked psum — the full-vocab
    logits row never exists on any device."""
    @jax.checkpoint
    def chunk_nll(xc, lc, wc):
        logits = (xc @ head_local).astype(jnp.float32)  # (chunk, v_loc)
        v_loc = logits.shape[-1]
        start = lax.axis_index(axis_name) * v_loc
        # the max shift is gradient-neutral (the lse gradient is the
        # softmax either way).  stop_gradient goes on pmax's INPUT: the
        # zero tangent keeps AD from needing pmax's (missing) JVP rule,
        # and pmax — unlike all_gather — types as model-INVARIANT under
        # the shard_map vma checker, which the P() loss out_spec needs
        m = lax.pmax(lax.stop_gradient(logits.max(-1)), axis_name)
        se = lax.psum(jnp.exp(logits - m[:, None]).sum(-1), axis_name)
        lse = m + jnp.log(se)
        lc_loc = jnp.clip(lc - start, 0, v_loc - 1)
        mine = (lc >= start) & (lc < start + v_loc)
        picked_loc = jnp.take_along_axis(logits, lc_loc[:, None],
                                         axis=-1)[:, 0]
        picked = lax.psum(jnp.where(mine, picked_loc, 0.0), axis_name)
        return (-(picked - lse) * wc).sum()
    return chunk_nll


def _ce_token_nll_sum(x, labels, chunk_nll, n_chunks, weights):
    """Σ weights·(-log p[label]) over the local tokens, computed
    ``n_chunks`` tokens-chunks at a time with the chunk rematerialized:
    the full ``(tokens, vocab)`` f32 logits tensor — ~2 GB at the bench
    shape, and the dominant HBM stream of a small-d model — never
    exists; only one chunk of logits is live (forward AND backward,
    ``jax.checkpoint`` recomputes it in the transpose).  Per-token
    numerics are identical to the dense path (row-wise log_softmax);
    only the cross-token summation order differs."""
    b, t, d = x.shape
    n_tok = b * t
    xf = x.reshape(n_tok, d)
    lf = labels.reshape(n_tok)
    wf = jnp.broadcast_to(weights, (b, t)).reshape(n_tok) \
        if weights is not None else None
    chunk = -(-n_tok // n_chunks)
    pad = chunk * n_chunks - n_tok
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
        lf = jnp.pad(lf, (0, pad))
        # padded rows weigh 0 so they contribute nothing either way
        wf = jnp.pad(jnp.ones((n_tok,), jnp.float32) if wf is None
                     else wf, (0, pad))
    elif wf is None:
        wf = jnp.ones((n_tok,), jnp.float32)

    # lax.map (carry-free scan): a scan carry would need its varying-axes
    # type pinned to whatever mesh axes the enclosing shard_map uses,
    # which this helper cannot know
    totals = lax.map(
        lambda inp: chunk_nll(*inp),
        (xf.reshape(n_chunks, chunk, d), lf.reshape(n_chunks, chunk),
         wf.reshape(n_chunks, chunk)))
    return totals.sum()


#: named selective-remat policies for ``jax.checkpoint`` around each
#: block: "dots" saves matmul outputs and recomputes the cheap
#: elementwise chain (the usual sweet spot); "dots_no_batch" saves only
#: non-batch dots (layernorm stats etc. recompute); "nothing" is full
#: recompute — the maximum-memory-savings end of the dial
_REMAT_POLICIES = {
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch":
        jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "nothing": jax.checkpoint_policies.nothing_saveable,
}


def _forward_hidden(ps, tokens, heads_local, causal, use_flash,
                    interp, cdt, remat: bool = False,
                    use_ring_flash: bool = False,
                    moe_aux_weight: float = 0.0,
                    moe_top_k: int = 1,
                    remat_policy: str | None = None,
                    moe_zloss_weight: float = 0.0):
    """Embedding + block stack — the ONE pre-head forward body, shared
    by the CE loss (:func:`_forward_ce`) and the full-pass logits oracle
    (:func:`make_logits_fn`, the generative serving plane's correctness
    anchor).  Returns ``(x, aux_term, ps_cast)`` — the hidden states,
    the summed MoE regularizer term, and the compute-dtype-cast params
    (so the caller's head matmul uses the same precision policy)."""
    ps = jax.tree.map(lambda w: w.astype(cdt), ps)
    x = ps["emb"][tokens]                         # (b_l, t_l, d)
    blk = _block
    if remat or remat_policy:
        pol = _REMAT_POLICIES[remat_policy] if remat_policy else None
        blk = jax.checkpoint(
            _block, policy=pol,
            static_argnums=(2, 3, 4, 5, 6, 7,
                            8, 9))  # type: ignore[assignment]
    # regularizer weights apply inside _block (per-block pre-weighted)
    aux_term = jnp.zeros((), jnp.float32)
    for p in ps["blocks"]:
        x, aux = blk(x, p, heads_local, causal, use_flash, interp,
                     use_ring_flash, moe_top_k, moe_aux_weight,
                     moe_zloss_weight)
        aux_term = aux_term + aux
    return x, aux_term, ps


def _forward_ce(ps, tokens, labels, mask, heads_local, causal, use_flash,
                interp, cdt, remat: bool = False,
                loss_chunks: int | None = None,
                use_ring_flash: bool = False,
                head_sharded: bool = False,
                moe_aux_weight: float = 0.0,
                moe_top_k: int = 1,
                remat_policy: str | None = None,
                moe_zloss_weight: float = 0.0,
                reduce: bool = True):
    """The ONE forward + CE-loss body (shared by the train step's loss_fn
    and the eval pass, so their numerics can never drift).  ``mask`` is a
    per-row validity mask or None; masked rows (the loader's padded tail)
    contribute neither loss nor — through AD — gradients, the framework's
    padding contract (loader/base.py).  ``moe_aux_weight`` scales the
    MoE blocks' summed load-balance aux into the loss (local-mean
    convention, same psum as the CE term; PADDED rows do count toward
    the routing statistics — the aux is a regularizer, not a metric).

    ``reduce=False`` returns the LOCAL loss term whose exact
    ``psum(..., ("data", "seq"))`` equals the ``reduce=True`` value
    (the replicated normalizers — shard counts, the masked token total —
    still reduce exactly inside).  The quantized-collective train step
    uses it to differentiate a local loss and route the gradient
    reduction through the explicit quantized psum instead of AD's
    psum transpose."""
    x, aux_term, ps = _forward_hidden(
        ps, tokens, heads_local, causal, use_flash, interp, cdt,
        remat=remat, use_ring_flash=use_ring_flash,
        moe_aux_weight=moe_aux_weight, moe_top_k=moe_top_k,
        remat_policy=remat_policy, moe_zloss_weight=moe_zloss_weight)
    b_l, t_l = labels.shape
    mvec = mask[:, None].astype(jnp.float32) if mask is not None else None
    # either path yields the LOCAL weighted nll sum; normalization below
    # is shared so dense and chunked conventions can never drift.  A
    # vocab-sharded head always routes through the chunk helper (its CE
    # needs the collective-reduced softmax; n_chunks=1 when unchunked).
    if head_sharded or (loss_chunks and loss_chunks > 1):
        fn = _vshard_chunk_nll(ps["head"]) if head_sharded else \
            _dense_chunk_nll(ps["head"])
        n_chunks = loss_chunks if (loss_chunks and loss_chunks > 1) else 1
        nll = _ce_token_nll_sum(x, labels, fn, n_chunks, mvec)
    else:
        logits = (x @ ps["head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, labels[..., None],
                                     axis=-1)[..., 0]
        nll = -picked.sum() if mvec is None else \
            -(picked * jnp.broadcast_to(mvec, picked.shape)).sum()
    if mask is None:
        local = nll / (b_l * t_l) + aux_term
        if not reduce:
            return local
        # psum-of-local-means; it makes AD emit globally-reduced grads
        # for replicated params; model-sharded params get their local
        # shard's grad
        return lax.psum(local, ("data", "seq"))
    # masked variant, SAME n_shards-scaled convention as the unmasked
    # psum-of-local-means (the caller divides loss and grads by n_shards)
    n_seq = lax.psum(1, "seq")
    n_shards = lax.psum(1, "data") * n_seq
    # the mask is seq-INVARIANT (each seq shard sees the same rows), so
    # its token count reduces over "data" and multiplies by n_seq — a
    # joint psum would mix varying and invarying axis states
    total = lax.psum(mask.astype(jnp.float32).sum() * t_l, "data") * n_seq
    if not reduce:
        # n_shards/total are replicated, so the psum of this local term
        # distributes back to exactly the reduce=True expression
        return n_shards * nll / jnp.maximum(total, 1.0) + aux_term
    return n_shards * lax.psum(nll, ("data", "seq")) / \
        jnp.maximum(total, 1.0) + lax.psum(aux_term, ("data", "seq"))


def make_train_step(mesh: Mesh, n_layers: int, d: int, heads: int, ff: int,
                    vocab: int, lr: float = 0.1, causal: bool = True,
                    compute_dtype=None, shard_update: bool = False,
                    shard_params: bool = False,
                    masked: bool = False, donate: bool = False,
                    remat: bool = False, loss_chunks: int | None = None,
                    head_sharded: bool = False,
                    n_experts: int | None = None,
                    moe_aux_weight: float = 0.0,
                    moe_top_k: int = 1,
                    remat_policy: str | None = None,
                    moe_zloss_weight: float = 0.0,
                    quantized_collectives: dict | None = None,
                    anatomy: bool = False):
    """-> jitted ``step(params, tokens, labels) -> (params, loss)``
    (``masked=True``: ``step(params, tokens, labels, mask)`` with a
    per-row bool mask — padded loader rows train nothing).

    ``donate=True`` donates the params buffers to the step (the training
    loop's natural contract — the caller rebinds; the old pytree is dead
    after the call), halving parameter HBM traffic.  ``remat=True``
    wraps each block in ``jax.checkpoint``: backward recomputes block
    activations instead of saving them — the standard long-context
    trade (HBM for FLOPs) once t grows past what activations fit;
    ``remat_policy`` ("dots" | "dots_no_batch" | "nothing") selects a
    SELECTIVE checkpoint policy instead of the all-or-nothing default
    (implies remat when set).
    ``loss_chunks=k`` computes the CE loss k token-chunks at a time
    (:func:`_ce_token_nll_sum`) so the ``(tokens, vocab)`` f32 logits
    never materialize — the dominant HBM stream when vocab ≫ d.  Loss
    differs from the dense path only in summation order (~1 ulp); the
    dense default keeps historical pins bit-stable.
    ``head_sharded=True`` vocab-shards the LM head over ``model`` and
    computes the CE with Megatron parallel cross-entropy
    (:func:`_vshard_chunk_nll`): head memory, the head GEMM, and its
    gradient all divide by tp, at the cost of one pmax + two psums per
    chunk; composes with ``loss_chunks``.  Requires ``vocab % tp == 0``.
    ``n_experts=E`` swaps every block's dense FFN for a top-1
    expert-parallel MoE FFN with the E experts sharded over ``model``
    (parallel/moe.py; requires ``E % tp == 0``; pass matching
    ``init_params(..., n_experts=E)`` params).  ``moe_aux_weight``
    adds the switch-transformer load-balance aux (arXiv:2101.03961
    eq. 4, summed over blocks) to the TRAINING loss — without it top-1
    routing tends to collapse onto few experts; eval losses stay pure
    CE.  ``moe_top_k=k`` routes each token to its k best experts with
    GShard-renormalized gate weights (k=1 is switch routing).
    ``moe_zloss_weight`` adds the ST-MoE router z-loss
    (arXiv:2202.08906 eq. 5) — penalizes router-logit drift, the bf16
    MoE instability the balance aux does not catch; training loss
    only, like the balance aux.

    ``tokens``/``labels``: int32 ``(batch, time)``, batch sharded over
    ``data`` and time over ``seq``; per-position class targets (CE loss).

    Mixed precision follows the FusedTrainStep recipe: master params and
    the SGD update stay f32; the forward casts params + activations to
    ``compute_dtype`` (bf16 on accelerators, see
    :func:`_default_compute_dtype`), and the loss/log-softmax runs f32.
    AD transposes the casts, so gradients land f32 on the masters.

    ``shard_update`` applies the ZeRO-style cross-replica update split
    (arXiv:2004.13336) to the REPLICATED leaves (embeddings, head,
    layernorms): each data-axis replica updates a 1/n slice and the
    slices reassemble through a psum.  NOTE the honest scope: this step
    is stateless SGD, so there is no optimizer-state memory to shard —
    the split divides the update COMPUTE and pins the numerics the
    fused step's stateful shard_update (parallel/step.py, where the
    ZeRO-1 memory win is real) must match.  Tensor-sharded leaves
    already live partitioned and update locally.

    ``shard_params`` (ISSUE 15) goes further: the replicated leaves
    PERSIST flat-sharded over ``data`` between steps — per-chip
    parameter memory for those leaves is 1/n — and full weights
    materialize on demand through the per-leaf all-gather chain
    (zero.gather_chain) ahead of each forward; the update applies on
    the local slice and the post-update regather disappears.  Params
    must arrive in the :func:`shard_params_host` layout and the
    returned specs are :func:`shard_params_specs`; read results back
    with :func:`unshard_params_host`.  Subsumes (and refuses to compose
    with) ``shard_update``.

    ``quantized_collectives`` (ISSUE 18; ``None`` defers to the
    ``engine.quantized_collectives`` config) ships the gradient
    reduction and the shard_params regather chain quantized
    (parallel/qcomm.py): the loss differentiates LOCALLY and ALL grads
    reduce through one explicit quantized psum over ``("data", "seq")``,
    while the reported loss scalar still reduces exactly.  NOTE the
    reduction semantics: the exact path's grads come from AD's
    psum-transpose of the reduced loss, which applies each batch
    shard's OWN gradient to its replica; the quantized path's explicit
    psum applies the true batch-mean gradient instead — on a
    ``model=1`` mesh its trajectory matches a single-device full-batch
    run to within codec noise (pinned in the flag fuzz), where the
    exact path's does not.  The two paths therefore track each other
    within a band, not bitwise.  No error feedback here: the step is
    stateless (pure ``(params, batch) -> params``), so there is no
    residual carry; prefer bf16 mode or the fused step for EF-grade
    convergence.  mode=off builds today's program bit for bit.

    ``anatomy=True`` (ISSUE 20) returns a split-dispatch DRIVER instead
    of one jitted program: separate compiled phases (zero_gather / grad
    / collective / update) with host stamps between them feeding
    ``znicz_anatomy_*{plane="transformer"}``.  The reduction follows
    the quantized-collectives semantics (local loss + one explicit
    psum — the true batch-mean gradient) even with no codec, because
    the exact path's AD-transposed grads are per-rank PARTIAL values
    that cannot cross a program cut; trajectories therefore track the
    exact path within the band documented above, not bitwise.  A
    diagnostic mode — per-phase dispatch latency is the price;
    ``donate`` is ignored (params feed two programs per step).
    """
    if shard_params and shard_update:
        raise ValueError(
            "shard_params subsumes shard_update (replicated leaves "
            "persist sharded and update in place — there is no "
            "regather left to split); pass only one")
    heads_local = _check_tp(mesh, heads, d, ff,
                            vocab if head_sharded else None, n_experts)
    if remat_policy is not None and remat_policy not in _REMAT_POLICIES:
        raise ValueError(f"remat_policy={remat_policy!r} — choose from "
                         f"{sorted(_REMAT_POLICIES)}")
    specs = param_specs(n_layers, head_sharded, moe=bool(n_experts))
    cdt = _default_compute_dtype(compute_dtype)
    from znicz_tpu.core.config import root as root_cfg
    interp = bool(root_cfg.common.engine.get("pallas_interpret", False))
    use_flash = _flash_eligible(mesh, interp)
    use_ring_flash = _ring_flash_eligible(mesh, interp)
    if use_ring_flash and interp:
        # eval-only mode: interpret-Pallas needs check_vma=False at
        # seq>1, which corrupts replicated-param gradient reduction
        # (docs/TUNING.md "Ring×flash" §3) — refuse to build a silently
        # wrong TRAINING step
        raise ValueError(
            "engine.ring_flash_interpret is eval-only (forward parity "
            "tests): a train step under the relaxed vma checker gets "
            "corrupted replicated-param gradients at seq>1. Train with "
            "engine.flash_attention=False (dense ring) in interpret "
            "mode, or run compiled on TPU.")
    n_data = mesh.shape["data"]
    shapes = param_shapes(n_layers, d, ff, vocab, n_experts=n_experts)
    step_specs = shard_params_specs(specs) if shard_params else specs
    via_psum = bool(root_cfg.common.engine.get("zero_gather_via_psum",
                                               False))
    codec = qcomm.resolve(quantized_collectives)

    def _sharded_sgd(w, g, scale):
        """w - lr*g/scale computed on this replica's 1/n slice only,
        reassembled via a (provably replicating) psum."""
        rank = lax.axis_index("data")
        new_sh = zero.pad_slice(w, rank, n_data) - \
            lr * zero.pad_slice(g, rank, n_data) / scale
        return zero.psum_regather(new_sh, rank, n_data, "data", w)

    def local_step(params, tokens, labels, mask=None):
        if shard_params:
            # materialize full replicated leaves from the flat shards —
            # the on-demand regather chain, OUTSIDE the differentiated
            # function so grads reduce through the same AD-inserted
            # psum as the replicated path (bit-parity; AD through the
            # gather would transpose to a reduce-scatter instead)
            rank = lax.axis_index("data")
            flat_p, treedef = jax.tree.flatten(params)
            flat_s = _spec_leaves(specs)
            flat_shapes = _shape_leaves(shapes)
            idx = [i for i, s in enumerate(flat_s) if s == P()]
            gathered = zero.gather_chain(
                [flat_p[i] for i in idx],
                [jax.ShapeDtypeStruct(flat_shapes[i], flat_p[i].dtype)
                 for i in idx],
                rank, n_data, "data", via_psum=via_psum, codec=codec)
            flat_full = list(flat_p)
            for i, g in zip(idx, gathered):
                flat_full[i] = g
            full_params = jax.tree.unflatten(treedef, flat_full)
        else:
            full_params = params

        def loss_fn(ps):
            return _forward_ce(ps, tokens, labels, mask, heads_local,
                               causal, use_flash, interp, cdt,
                               remat=remat, loss_chunks=loss_chunks,
                               use_ring_flash=use_ring_flash,
                               head_sharded=head_sharded,
                               moe_aux_weight=moe_aux_weight,
                               moe_top_k=moe_top_k,
                               remat_policy=remat_policy,
                               moe_zloss_weight=moe_zloss_weight,
                               reduce=codec is None)

        loss, grads = jax.value_and_grad(loss_fn)(full_params)
        if codec is not None:
            # quantized mode differentiates the LOCAL loss and reduces
            # every grad leaf (replicated AND tensor-sharded — both need
            # the data x seq sum) through the quantized-psum seam; the
            # reported loss scalar reduces exactly (telemetry never
            # quantizes)
            grads, _ = quantized_psum(grads, ("data", "seq"), codec)
            loss = lax.psum(loss, ("data", "seq"))
        n_shards = lax.psum(1, "data") * lax.psum(1, "seq")
        if shard_params:
            # each replica updates ONLY its slice (grad sliced to match)
            # and keeps it — no regather; tensor-sharded leaves update
            # locally as before
            flat_g = jax.tree.leaves(grads)
            new_leaves = [
                flat_p[i] -
                lr * zero.pad_slice(flat_g[i], rank, n_data) / n_shards
                if flat_s[i] == P()
                else flat_full[i] - lr * flat_g[i] / n_shards
                for i in range(len(flat_p))]
            new_params = jax.tree.unflatten(treedef, new_leaves)
        elif shard_update:
            # PartitionSpec is a tuple subclass (a pytree container), so
            # align specs to params by flattening with an is_leaf guard
            flat_w, treedef = jax.tree.flatten(params)
            flat_g = jax.tree.leaves(grads)
            flat_s = _spec_leaves(specs)
            new_leaves = [
                _sharded_sgd(w, g, n_shards) if s == P()
                else w - lr * g / n_shards
                for w, g, s in zip(flat_w, flat_g, flat_s)]
            new_params = jax.tree.unflatten(treedef, new_leaves)
        else:
            new_params = jax.tree.map(
                lambda w, g: w - lr * g / n_shards, params, grads)
        return new_params, loss / n_shards

    # replication checking is disabled wholesale by the compat shim
    # (parallel/compat.py) — it false-positives on these psum-composed
    # updates (and cannot infer replication through the shard_params
    # all_gather); _flash_eligible still only allows interpret-flash on
    # a SINGLETON mesh, where the relaxed psum transposition is exact.
    batch_spec = P("data", "seq")
    in_specs = (step_specs, batch_spec, batch_spec) + \
        ((P("data"),) if masked else ())
    if not anatomy:
        step = shard_map(
            local_step, mesh=mesh, in_specs=in_specs,
            out_specs=(step_specs, P()))
        return jax.jit(step, donate_argnums=(0,) if donate else ()), \
            step_specs
    return _make_anatomy_step(
        mesh, specs, step_specs, shapes, batch_spec, masked, lr,
        shard_params, shard_update, n_data, via_psum, codec,
        _sharded_sgd,
        dict(heads_local=heads_local, causal=causal, use_flash=use_flash,
             interp=interp, cdt=cdt, remat=remat,
             loss_chunks=loss_chunks, use_ring_flash=use_ring_flash,
             head_sharded=head_sharded, moe_aux_weight=moe_aux_weight,
             moe_top_k=moe_top_k, remat_policy=remat_policy,
             moe_zloss_weight=moe_zloss_weight)), step_specs


def _make_anatomy_step(mesh, specs, step_specs, shapes, batch_spec,
                       masked, lr, shard_params, shard_update, n_data,
                       via_psum, codec, sharded_sgd, fwd_kw):
    """Split-dispatch phase programs + host-stamping driver for
    ``make_train_step(anatomy=True)`` — the same gather / loss_fn /
    psum / update bodies as ``local_step``, cut at the phase seams.
    The grad program returns per-rank UNREDUCED grads stacked over the
    combined ``(data, seq)`` ranks via the ``g[None]`` / out_specs
    ``P(("data","seq"), ...)`` trick (no data movement at the cut);
    the collective program takes the stack back per-rank and runs the
    explicit (possibly quantized) psum."""
    from znicz_tpu.observe.anatomy import StepAnatomy, TRAIN_PHASES

    is_spec = lambda s: isinstance(s, P)  # noqa: E731
    stacked_specs = jax.tree.map(lambda s: P(("data", "seq"), *s),
                                 specs, is_leaf=is_spec)

    def local_gather(params):
        rank = lax.axis_index("data")
        flat_p, treedef = jax.tree.flatten(params)
        flat_s = _spec_leaves(specs)
        flat_shapes = _shape_leaves(shapes)
        idx = [i for i, s in enumerate(flat_s) if s == P()]
        gathered = zero.gather_chain(
            [flat_p[i] for i in idx],
            [jax.ShapeDtypeStruct(flat_shapes[i], flat_p[i].dtype)
             for i in idx],
            rank, n_data, "data", via_psum=via_psum, codec=codec)
        flat_full = list(flat_p)
        for i, g in zip(idx, gathered):
            flat_full[i] = g
        return jax.tree.unflatten(treedef, flat_full)

    def local_grad(full_params, tokens, labels, mask=None):
        def loss_fn(ps):
            return _forward_ce(ps, tokens, labels, mask,
                               reduce=False, **fwd_kw)

        loss, grads = jax.value_and_grad(loss_fn)(full_params)
        n_shards = lax.psum(1, "data") * lax.psum(1, "seq")
        loss = lax.psum(loss, ("data", "seq")) / n_shards
        return jax.tree.map(lambda g: g[None], grads), loss

    def local_collective(stacked):
        grads = jax.tree.map(lambda g: g[0], stacked)
        grads, _ = quantized_psum(grads, ("data", "seq"), codec)
        return grads

    def local_update(params, grads):
        n_shards = lax.psum(1, "data") * lax.psum(1, "seq")
        if shard_params:
            rank = lax.axis_index("data")
            flat_p, treedef = jax.tree.flatten(params)
            flat_g = jax.tree.leaves(grads)
            flat_s = _spec_leaves(specs)
            new_leaves = [
                flat_p[i] - lr * zero.pad_slice(flat_g[i], rank,
                                                n_data) / n_shards
                if flat_s[i] == P()
                else flat_p[i] - lr * flat_g[i] / n_shards
                for i in range(len(flat_p))]
            return jax.tree.unflatten(treedef, new_leaves)
        if shard_update:
            flat_w, treedef = jax.tree.flatten(params)
            flat_g = jax.tree.leaves(grads)
            flat_s = _spec_leaves(specs)
            new_leaves = [
                sharded_sgd(w, g, n_shards) if s == P()
                else w - lr * g / n_shards
                for w, g, s in zip(flat_w, flat_g, flat_s)]
            return jax.tree.unflatten(treedef, new_leaves)
        return jax.tree.map(lambda w, g: w - lr * g / n_shards,
                            params, grads)

    gather_fn = None
    if shard_params:
        gather_fn = jax.jit(shard_map(
            local_gather, mesh=mesh, in_specs=(step_specs,),
            out_specs=specs))
    grad_in = (specs, batch_spec, batch_spec) + \
        ((P("data"),) if masked else ())
    grad_fn = jax.jit(shard_map(
        local_grad, mesh=mesh, in_specs=grad_in,
        out_specs=(stacked_specs, P())))
    coll_fn = jax.jit(shard_map(
        local_collective, mesh=mesh, in_specs=(stacked_specs,),
        out_specs=specs))
    upd_fn = jax.jit(shard_map(
        local_update, mesh=mesh, in_specs=(step_specs, specs),
        out_specs=step_specs))

    anat = StepAnatomy("transformer", TRAIN_PHASES)
    # analytic MFU numerator: ~6 FLOPs per matmul weight per token for
    # one train step (2 fwd + 4 bwd), embedding lookup excluded — the
    # standard transformer approximation; tokens.size (the GLOBAL
    # batch x time) is known at the first call
    flat_shapes = _shape_leaves(shapes)
    matmul_params = sum(int(np.prod(s)) for s in flat_shapes
                        if len(s) >= 2)
    matmul_params -= int(np.prod(shapes["emb"]))
    state = {"flops_set": False}

    def step(params, tokens, labels, mask=None):
        if not state["flops_set"]:
            anat.set_flops(6.0 * matmul_params * int(tokens.size))
            state["flops_set"] = True
        anat.begin()
        if gather_fn is not None:
            full = jax.block_until_ready(gather_fn(params))
            anat.stamp("zero_gather")
        else:
            full = params
        args = (tokens, labels) + ((mask,) if masked else ())
        stacked, loss = jax.block_until_ready(grad_fn(full, *args))
        anat.stamp("grad")
        grads = jax.block_until_ready(coll_fn(stacked))
        anat.stamp("collective")
        new_params = jax.block_until_ready(upd_fn(params, grads))
        anat.stamp("update")
        anat.finish()
        return new_params, loss

    return step


def make_eval_loss(mesh: Mesh, n_layers: int, d: int, heads: int, ff: int,
                   vocab: int, causal: bool = True, compute_dtype=None,
                   masked: bool = False, loss_chunks: int | None = None,
                   head_sharded: bool = False,
                   n_experts: int | None = None,
                   moe_top_k: int = 1):
    """-> jitted ``eval_loss(params, tokens, labels[, mask]) -> loss`` —
    the train step's forward + CE loss (the SHARED ``_forward_ce`` body,
    so the numerics cannot drift) with no update: validation/test
    passes."""
    heads_local = _check_tp(mesh, heads, d, ff,
                            vocab if head_sharded else None, n_experts)
    specs = param_specs(n_layers, head_sharded, moe=bool(n_experts))
    cdt = _default_compute_dtype(compute_dtype)
    from znicz_tpu.core.config import root as root_cfg
    interp = bool(root_cfg.common.engine.get("pallas_interpret", False))
    use_flash = _flash_eligible(mesh, interp)
    use_ring_flash = _ring_flash_eligible(mesh, interp)

    def local_eval(params, tokens, labels, mask=None):
        n_shards = lax.psum(1, "data") * lax.psum(1, "seq")
        return _forward_ce(params, tokens, labels, mask, heads_local,
                           causal, use_flash, interp, cdt,
                           loss_chunks=loss_chunks,
                           use_ring_flash=use_ring_flash,
                           head_sharded=head_sharded,
                           moe_top_k=moe_top_k) / n_shards

    batch_spec = P("data", "seq")
    in_specs = (specs, batch_spec, batch_spec) + \
        ((P("data"),) if masked else ())
    fn = shard_map(local_eval, mesh=mesh, in_specs=in_specs,
                   out_specs=P())
    return jax.jit(fn)


def make_logits_fn(mesh: Mesh, n_layers: int, d: int, heads: int, ff: int,
                   vocab: int, causal: bool = True, compute_dtype=None,
                   n_experts: int | None = None, moe_top_k: int = 1):
    """-> jitted ``logits(params, tokens) -> (b, t, vocab)`` f32 — the
    full forward pass through the SAME ``_forward_hidden`` body the
    train/eval steps use, with the LM head applied per position instead
    of the CE reduction.  This is the generative serving plane's
    correctness oracle: ``serve/kvcache.py`` pins greedy KV-cache
    incremental decode against exactly this function (ISSUE 10), so any
    drift between training numerics and the decode path fails a test
    instead of degrading generations silently.

    The head must be replicated (``head_sharded`` has no logits form —
    the vocab-sharded CE never materializes full-vocab rows by design);
    callers wanting Megatron CE keep using :func:`make_eval_loss`."""
    heads_local = _check_tp(mesh, heads, d, ff, None, n_experts)
    cdt = _default_compute_dtype(compute_dtype)
    from znicz_tpu.core.config import root as root_cfg
    interp = bool(root_cfg.common.engine.get("pallas_interpret", False))
    use_flash = _flash_eligible(mesh, interp)
    use_ring_flash = _ring_flash_eligible(mesh, interp)

    def local_logits(params, tokens):
        x, _aux, ps = _forward_hidden(
            params, tokens, heads_local, causal, use_flash, interp, cdt,
            use_ring_flash=use_ring_flash, moe_top_k=moe_top_k)
        return (x @ ps["head"]).astype(jnp.float32)

    specs = param_specs(n_layers, False, moe=bool(n_experts))
    batch_spec = P("data", "seq")
    fn = shard_map(local_logits, mesh=mesh,
                   in_specs=(specs, batch_spec),
                   out_specs=batch_spec)
    return jax.jit(fn)


# -- dp x pipe x expert configuration ---------------------------------------
def init_moe_pipeline_params(gen, n_stages: int, d: int, ff: int,
                             n_experts: int):
    """Stage-stacked MoE-block params (leading dim = pipe stage)."""
    def w(shape, scale=None):
        scale = scale or 1.0 / np.sqrt(shape[-2])
        return gen.normal(0.0, scale, shape).astype(np.float32)

    return {
        "gate": w((n_stages, d, n_experts)),
        "w1": w((n_stages, n_experts, d, ff)),
        "b1": np.zeros((n_stages, n_experts, ff), np.float32),
        "w2": w((n_stages, n_experts, ff, d)),
        "b2": np.zeros((n_stages, n_experts, d), np.float32),
    }


def moe_pipeline_specs():
    return {k: P("pipe", "expert") if k != "gate" else P("pipe")
            for k in ("gate", "w1", "b1", "w2", "b2")}


def make_pipeline_step(mesh: Mesh, n_experts: int, lr: float = 0.05,
                       compute_dtype=None):
    """-> jitted ``step(params, xs, ys) -> (params, loss)`` on a
    ``(data, pipe, expert)`` mesh: each pipe stage is an expert-parallel
    MoE residual block; xs ``(n_micro, mb, d)`` microbatches (data-sharded
    on mb), ys same shape (regression targets — keeps the demo loss
    self-contained).  Feature/ff sizes flow from the params pytree.
    Mixed precision follows the same recipe as make_train_step: bf16
    compute on accelerators, f32 masters/updates, f32 loss."""
    n_stages = mesh.shape["pipe"]
    ep = mesh.shape["expert"]
    if n_experts % ep:
        raise ValueError(f"expert-axis size {ep} must divide "
                         f"n_experts={n_experts}")
    specs = moe_pipeline_specs()
    cdt = _default_compute_dtype(compute_dtype)

    def stage_fn(p, x):
        y, _ = moe_ffn(x, p["gate"][0], p["w1"][0], p["b1"][0],
                       p["w2"][0], p["b2"][0], jax.nn.gelu, "expert")
        return x + y

    def local_step(params, xs, ys):
        def loss_fn(ps):
            ps = jax.tree.map(lambda w: w.astype(cdt), ps)
            out = pipeline_apply(
                lambda _unused, x: stage_fn(ps, x), None,
                xs.astype(cdt), n_stages, "pipe")
            diff = out.astype(jnp.float32) - ys
            return lax.psum((diff * diff).mean(), "data")

        loss, grads = jax.value_and_grad(loss_fn)(params)
        n_data = lax.psum(1, "data")
        new_params = jax.tree.map(
            lambda w, g: w - lr * g / n_data, params, grads)
        return new_params, loss / n_data

    step = shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, P(None, "data"), P(None, "data")),
        out_specs=(specs, P()))
    return jax.jit(step), specs
