"""shard_map compatibility shim — one import site for every user.

Two portability problems are solved here:

- the symbol moved (``jax.experimental.shard_map.shard_map`` ->
  ``jax.shard_map``);
- the static replication checker (``check_rep``, renamed ``check_vma``)
  cannot infer replication through this codebase's psum-composed
  update functions on the jax versions in the container image, and
  rejects out_specs that are in fact correct (the documented escape
  hatch in the error message itself is to disable the check).  The
  real correctness guard is the test suite's numeric parity coverage:
  sharded-vs-replicated equality, mesh-size invariance, and the
  snapshot/resume bit-exactness pins all fail loudly if a P() output
  ever stops being replicated.

Callers may still pass ``check_rep=``/``check_vma=`` explicitly; an
explicit keyword overrides the relaxed default.
"""

from __future__ import annotations

import functools
import inspect

try:                               # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:                # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_params = inspect.signature(_shard_map).parameters
if "check_vma" in _params:
    shard_map = functools.partial(_shard_map, check_vma=False)
elif "check_rep" in _params:
    shard_map = functools.partial(_shard_map, check_rep=False)
else:                              # no checker flag on this version
    shard_map = _shard_map

__all__ = ["shard_map"]
