"""shard_map compatibility shim — one import site for every user.

Two portability problems are solved here:

- the symbol moved (``jax.experimental.shard_map.shard_map`` ->
  ``jax.shard_map``);
- the static replication checker (``check_rep``, renamed ``check_vma``)
  cannot infer replication through this codebase's psum-composed
  update functions on the jax versions in the container image, and
  rejects out_specs that are in fact correct (the documented escape
  hatch in the error message itself is to disable the check).  The
  real correctness guard is the test suite's numeric parity coverage:
  sharded-vs-replicated equality, mesh-size invariance, and the
  snapshot/resume bit-exactness pins all fail loudly if a P() output
  ever stops being replicated.

Callers may still pass ``check_rep=``/``check_vma=`` explicitly; an
explicit keyword overrides the relaxed default.

This module also hosts :func:`quantized_psum` — the ONE opt-in seam
through which the explicit gradient psums (parallel/step.py's fused
step, parallel/transformer.py's sharded step) pick up the quantized
collective codec (parallel/qcomm.py): ``codec=None`` emits a verbatim
``jax.lax.psum``, so the exact path's program is bit-identical to a
build that never imported the codec.
"""

from __future__ import annotations

import functools
import inspect

import jax

try:                               # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:                # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_params = inspect.signature(_shard_map).parameters
if "check_vma" in _params:
    shard_map = functools.partial(_shard_map, check_vma=False)
elif "check_rep" in _params:
    shard_map = functools.partial(_shard_map, check_rep=False)
else:                              # no checker flag on this version
    shard_map = _shard_map


def quantized_psum(tree, axis_name, codec=None, residuals=None):
    """``lax.psum(tree, axis_name)`` with an opt-in quantized wire
    format: -> ``(summed_tree, new_residual_tree)``.

    ``codec=None`` (mode=off) is the EXACT path — one verbatim
    ``jax.lax.psum`` over the tree, ``residuals`` handed back untouched
    — so flipping the flag off reproduces today's program bit for bit.
    With a :class:`~znicz_tpu.parallel.qcomm.Codec`, the tree reduces
    through qcomm.psum_tree (int8/bf16 payload on the wire, f32 local
    sum) and ``residuals`` carries the error-feedback state: pass the
    previous step's residual tree (same structure as ``tree``) and
    persist the returned one."""
    if codec is None:
        return jax.lax.psum(tree, axis_name), residuals
    from znicz_tpu.parallel import qcomm
    return qcomm.psum_tree(tree, axis_name, codec, residuals)


__all__ = ["shard_map", "quantized_psum"]
