"""Ring attention — sequence-parallel exact attention over the ``seq``
mesh axis (long-context support; Liu et al. 2023 blockwise ring attention
pattern, re-derived for shard_map + lax.ppermute).

Each device holds a sequence block of Q/K/V ``(b, t_local, h, dh)``.  K/V
blocks rotate around the ring (one ``lax.ppermute`` per step — ICI
neighbor traffic only) while a numerically-stable online softmax
accumulates the local Q block's output:

    m' = max(m, rowmax(s));  l' = l*e^(m-m') + rowsum(e^(s-m'))
    o' = o*e^(m-m') + e^(s-m') @ V_blk

After ``seq`` steps every Q block has attended to the full sequence and
``o / l`` equals dense attention exactly (pinned by
tests/test_parallel_axes.py::test_ring_attention_matches_dense).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Sequence-sharded exact attention; call inside shard_map with the
    time dimension sharded over ``axis_name``."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_loc, h, dh = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    from znicz_tpu.ops.attention import masked_scores

    def scores(k_blk, blk_idx):
        # masked_scores accumulates f32 over bf16 matmul inputs (MXU
        # fast path); K/V rotate in their input dtype so ICI traffic
        # stays bf16-sized
        return masked_scores(jnp, q, k_blk, causal,
                             q_offset=my_idx * t_loc,
                             k_offset=blk_idx * t_loc)

    def step(carry, _):
        o, m, l, k_blk, v_blk, blk_idx = carry
        # online-softmax state (o, m, l) accumulates in f32 even when
        # q/k/v are bf16 — the exp/rescale chain loses digits fast in
        # half precision (standard flash-attention accumulator rule)
        s = scores(k_blk, blk_idx).astype(jnp.float32)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        # p rides the MXU at the value dtype; accumulation stays f32
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        # rotate: after this step we hold the block of (blk_idx - 1) % n
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        blk_idx = (blk_idx - 1) % axis_size
        return (o, m_new, l, k_blk, v_blk, blk_idx), None

    # initial accumulators must carry the same varying-axis type as the
    # loop-updated values (shard_map scan vma rule); deriving them from q
    # inherits whatever axes q varies over (seq here, plus data/model when
    # composed with dp/tp)
    zeros_q = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32) * 0.0
    o0 = zeros_q                                       # (b, h, t_loc, dh)
    m0 = zeros_q[..., 0] - jnp.inf
    l0 = zeros_q[..., 0]
    (o, m, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, my_idx), None, length=axis_size)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))  # (b, t_loc, h, dh)


def _merge_blocks(o, lse, o_s, lse_s, include):
    """Numerically-stable lse-weighted merge of two NORMALIZED attention
    results over the same queries but disjoint key blocks:
    ``softmax``-combining ``(o, lse)`` with ``(o_s, lse_s)``;
    ``include=False`` leaves the accumulator unchanged (a causally
    excluded future block).  All f32; shapes ``o`` (bh, t, dh), ``lse``
    (bh, t, 1)."""
    m = jnp.maximum(lse, lse_s)
    w_old = jnp.exp(lse - m)
    w_new = jnp.exp(lse_s - m)
    tot = w_old + w_new
    o_out = (o * w_old + o_s.astype(jnp.float32) * w_new) / tot
    lse_out = m + jnp.log(tot)
    # excluded blocks leave the accumulator BIT-EXACT (a select, not a
    # zero-weight pass through the merge arithmetic)
    return (jnp.where(include, o_out, o),
            jnp.where(include, lse_out, lse))


def ring_flash_attention(q, k, v, axis_name: str, causal: bool = False,
                         interpret: bool = False):
    """Ring attention whose LOCAL block math is the Pallas flash kernel
    (ops/pallas/attention.py) — the long-context composition: K/V blocks
    rotate over ICI exactly as in :func:`ring_attention`, but each ring
    step computes its (q-block × k-block) attention without ever
    materializing the score matrix, and per-block results combine by the
    lse merge rule (:func:`_merge_blocks`).

    Block-aligned causality needs NO kernel offsets: the diagonal step
    (own k block) runs the kernel's causal mask as-is (q/k positions
    aligned), fully-past blocks run unmasked, fully-future blocks are
    excluded from the merge.  Gradients flow through the merge into both
    o and lse — :func:`flash_attention_lse` carries the lse cotangent
    into the shared backward kernel.

    Same signature/semantics as :func:`ring_attention` (``(b, t_loc, h,
    dh)`` sequence-sharded, called inside shard_map)."""
    from znicz_tpu.ops.pallas.attention import flash_attention_lse

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_loc, h, dh = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t_loc, dh)

    qf = fold(q)

    def step(carry, _):
        o, lse, k_blk, v_blk, blk_idx = carry
        kf, vf = fold(k_blk), fold(v_blk)
        if causal:
            # the first ring step holds the own (diagonal) block, so the
            # cond's causal branch runs at least once per device
            o_s, lse_s = lax.cond(
                blk_idx == my_idx,
                lambda: flash_attention_lse(qf, kf, vf, True, interpret),
                lambda: flash_attention_lse(qf, kf, vf, False, interpret))
            include = blk_idx <= my_idx
        else:
            o_s, lse_s = flash_attention_lse(qf, kf, vf, False, interpret)
            include = True
        o, lse = _merge_blocks(o, lse, o_s, lse_s, include)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        blk_idx = (blk_idx - 1) % axis_size
        return (o, lse, k_blk, v_blk, blk_idx), None

    # accumulator init mirrors ring_attention: derive from q so the
    # varying-axis type matches the loop-updated values
    o0 = fold(q).astype(jnp.float32) * 0.0             # (bh, t_loc, dh)
    lse0 = o0[..., :1] - jnp.inf                       # (bh, t_loc, 1)
    (o, _, _, _, _), _ = lax.scan(
        step, (o0, lse0, k, v, my_idx), None, length=axis_size)
    out = o.reshape(b, h, t_loc, dh).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))            # (b, t_loc, h, dh)


def ring_mha_forward(x, params: dict, n_heads: int, axis_name: str,
                     causal: bool = False):
    """MHA with ring attention: x ``(b, t_local, d)`` sequence-sharded;
    projection weights replicated (or tp-sharded by the caller).  Same
    projection/param convention as the dense op — only the core differs."""
    from znicz_tpu.ops.attention import mha_forward

    def core(q, k, v, causal):
        return ring_attention(q, k, v, axis_name, causal=causal)

    return mha_forward(jnp, x, params, n_heads, causal=causal,
                       attention_fn=core)
