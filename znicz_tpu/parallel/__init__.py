"""SPMD execution — the TPU-native replacement of the reference's
distributed runtime (SURVEY.md §3.4, §4.2).

The reference distributes training as an async parameter server over ZeroMQ
(veles/server.py :: Server, veles/client.py :: Client): slaves compute
weight deltas on their minibatches, the master applies them without a
barrier.  Here the whole job protocol dissolves into synchronous SPMD: the
accelerated segment of the control graph (forwards -> evaluator -> gradient
updates) is traced ONCE into a pure step function and ``shard_map``-ped over
a ``jax.sharding.Mesh`` with ``lax.psum`` gradient reduction riding ICI.
The semantic change (async -> sync) is deliberate and improves
reproducibility; convergence parity is pinned by the tier-2 tests.

Host-side units (Loader / Decision / Snapshotter / plotters) stay exactly
where the reference put them — outside the compiled step.
"""

from znicz_tpu.parallel.mesh import (make_mesh, make_hybrid_mesh,
                                     data_parallel_mesh)
from znicz_tpu.parallel.step import FusedTrainStep

__all__ = ["make_mesh", "make_hybrid_mesh", "data_parallel_mesh",
           "FusedTrainStep"]
