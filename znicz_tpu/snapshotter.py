"""Checkpoint/resume — rebuild of veles/snapshotter.py :: SnapshotterBase,
SnapshotterToFile and veles.znicz nn_units.py :: NNSnapshotter.

The reference pickles the whole live workflow object graph (SURVEY.md §4.3)
— code-coupled and fragile.  The rebuild keeps the *semantics* (full
training-state resume: weights, optimizer momenta, Decision counters,
Loader shuffle position, PRNG streams) but stores explicit arrays +
JSON metadata in one compressed ``.npz`` (orbax-style state dict, not
pickled code).  Resume is ``restore_state(workflow, path)`` into a freshly
constructed workflow — the analog of ``veles -w snap.pickle.gz``.

Exactness contract (pinned by tests/test_snapshotter.py): resume from the
epoch-N snapshot and the metric history of epochs N+1.. is bit-identical to
an uninterrupted run — the reference's snapshot-mid-run/compare trick.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
import zlib
from typing import Optional

import numpy as np

import jax

from znicz_tpu.core import prng
from znicz_tpu.core.units import Unit
from znicz_tpu.resilience.faults import fault_hook
from znicz_tpu.resilience.retry import DEFAULT_IO_RETRY

FORMAT_VERSION = 1


def process_rank_world() -> tuple[int, int]:
    """(rank, world) of this process in a multi-process job.

    The elastic fleet's env (``ZNICZ_TPU_ELASTIC_RANK`` /
    ``ZNICZ_TPU_ELASTIC_WORLD``, set per worker by
    ``resilience/elastic.py``) wins; an already-initialized
    ``jax.distributed`` is the fallback (only consulted when jax is
    ALREADY imported — rank discovery must never boot a backend);
    single-process default is ``(0, 1)``."""
    rank = os.environ.get("ZNICZ_TPU_ELASTIC_RANK")
    if rank is not None:
        return int(rank), int(os.environ.get("ZNICZ_TPU_ELASTIC_WORLD",
                                             "1"))
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            if jax_mod.process_count() > 1:
                return jax_mod.process_index(), jax_mod.process_count()
        except Exception:  # noqa: BLE001 — uninitialized runtime
            pass
    return 0, 1


class SnapshotCorruptError(ValueError):
    """Stored checksum does not match the snapshot's content — a torn or
    bit-rotted snapshot must never be silently resumed from."""


def content_checksum(arrays: dict) -> int:
    """CRC32 over the arrays' names, dtypes, shapes and bytes (sorted key
    order, so it is independent of dict insertion order)."""
    crc = 0
    for key in sorted(arrays):
        arr = np.ascontiguousarray(arrays[key])
        head = f"{key}:{arr.dtype.str}:{arr.shape}".encode()
        crc = zlib.crc32(arr.tobytes(), zlib.crc32(head, crc))
    return crc & 0xFFFFFFFF


# -- state collection -------------------------------------------------------
def _flatten_state(prefix: str, obj, out: dict) -> None:
    """Nested dict/list state -> flat npz keys (``.name`` for dict keys,
    ``#i`` for list positions) — how state_dict-only units (e.g. the
    transformer LM step's param pytree) ride the array snapshot."""
    if isinstance(obj, dict):
        for k in obj:
            _flatten_state(f"{prefix}.{k}", obj[k], out)
    elif isinstance(obj, (list, tuple)):
        for j, v in enumerate(obj):
            _flatten_state(f"{prefix}#{j}", v, out)
    else:
        out[prefix] = np.asarray(obj)


_PATH_STEP = re.compile(r"([.#])([^.#]+)")


def _unflatten_state(prefix: str, arrays: dict):
    """Inverse of :func:`_flatten_state` for one unit's key prefix."""
    root: dict = {}
    for key, val in arrays.items():
        if not key.startswith((prefix + ".", prefix + "#")):
            continue
        steps = _PATH_STEP.findall(key[len(prefix):])
        node = root
        for n, (sep, name) in enumerate(steps):
            k = int(name) if sep == "#" else name
            if n == len(steps) - 1:
                node[k] = val
            else:
                node = node.setdefault(k, {})

    def materialize(node):
        if not isinstance(node, dict):
            return node
        if node and all(isinstance(k, int) for k in node):
            return [materialize(node[i]) for i in sorted(node)]
        return {k: materialize(v) for k, v in node.items()}

    return materialize(root)


def _state_only_units(workflow) -> dict:
    """unit index -> unit, for forwards that snapshot through
    state_dict/load_state_dict instead of weights/bias Arrays."""
    out = {}
    for i, fwd in enumerate(workflow.forwards):
        has_arrays = any(getattr(fwd, a, None)
                         for a in ("weights", "bias"))
        if not has_arrays and hasattr(fwd, "state_dict") and \
                hasattr(fwd, "load_state_dict"):
            out[i] = fwd
    return out


def collect_state(workflow) -> tuple[dict, dict]:
    """-> (arrays, meta): every array the training state needs, plus
    JSON-able metadata.  Covers forwards' weights/bias, gds' momentum
    buffers, state_dict-only forwards (flattened pytrees), loader
    position + shuffle order, decision counters, and all PRNG streams."""
    step = getattr(workflow, "step", None)
    if step is not None and getattr(step, "_params", None) is not None \
            and hasattr(step, "sync_to_units"):
        step.sync_to_units()  # device params -> unit Arrays
    arrays: dict[str, np.ndarray] = {}
    # three-arg getattr: non-standard forwards (KohonenTrainer has no bias)
    # simply contribute fewer arrays
    state_only = _state_only_units(workflow)
    for i, fwd in enumerate(workflow.forwards):
        if i in state_only:
            _flatten_state(f"unitstate.{i}", fwd.state_dict(), arrays)
            continue
        for attr in ("weights", "bias"):
            arr = getattr(fwd, attr, None)
            if arr:
                arrays[f"forward.{i}.{attr}"] = np.asarray(arr.map_read())
    for i, gd in enumerate(getattr(workflow, "gds", []) or []):
        for attr in ("gradient_weights", "gradient_bias"):
            arr = getattr(gd, attr, None)
            if arr:
                arrays[f"gd.{i}.{attr}"] = np.asarray(arr.map_read())
    if step is not None and getattr(step, "_key", None) is not None:
        # the device-resident PRNG key is training state: per-step keys are
        # split from it, so bit-exact resume must restore it
        arrays["step.key"] = np.asarray(jax.device_get(step._key))
    if step is not None and hasattr(step, "extra_state_arrays"):
        # optimizer state with no unit home (adam 2nd moments, step count)
        for k, v in step.extra_state_arrays().items():
            arrays[f"step.opt.{k}"] = v
    loader_state = workflow.loader.state_dict()
    for cls, order in loader_state.pop("shuffled").items():
        arrays[f"loader.shuffled.{cls}"] = np.asarray(order)
    # fitted normalizers split into JSON meta + npz arrays (file loaders)
    norm_state = loader_state.pop("normalizer", None)
    if norm_state is not None:
        for k, v in norm_state["arrays"].items():
            arrays[f"loader.normalizer.{k}"] = np.asarray(v)
        loader_state["normalizer_meta"] = norm_state["meta"]
    meta = {
        "format_version": FORMAT_VERSION,
        "workflow_name": workflow.name,
        "loader": loader_state,
        "decision": workflow.decision.state_dict(),
        "prng": prng.state_dict(),
    }
    if step is not None and hasattr(step, "optimizer"):
        meta["optimizer"] = step.optimizer
    return arrays, meta


def restore_state(workflow, path: str) -> dict:
    """Load a snapshot into a freshly built workflow (post-``initialize``).
    Returns the metadata dict."""
    with np.load(path, allow_pickle=False) as zf:
        meta = json.loads(str(zf["__meta__"]))
        if meta["format_version"] != FORMAT_VERSION:
            raise ValueError(f"snapshot format {meta['format_version']} "
                             f"!= supported {FORMAT_VERSION}")
        arrays = {k: zf[k] for k in zf.files if k != "__meta__"}
    # poison-snapshot detection (resilience supervisor contract): the
    # checksum written at save time must match the content read back.
    # Pre-checksum snapshots (no key) load as before.
    stored = meta.get("checksum")
    if stored is not None and int(stored) != content_checksum(arrays):
        raise SnapshotCorruptError(
            f"snapshot {path} checksum mismatch: stored {stored}, "
            f"computed {content_checksum(arrays)} — refusing to resume "
            f"from a corrupt snapshot")
    # strict key/shape matching: a snapshot from a different architecture
    # must fail loudly, never silently resume from partly-random weights
    state_only = _state_only_units(workflow)
    targets: dict[str, object] = {}
    for i, fwd in enumerate(workflow.forwards):
        if i in state_only:
            continue
        for attr in ("weights", "bias"):
            if getattr(fwd, attr, None):
                targets[f"forward.{i}.{attr}"] = getattr(fwd, attr)
    for i, gd in enumerate(getattr(workflow, "gds", []) or []):
        for attr in ("gradient_weights", "gradient_bias"):
            if getattr(gd, attr, None):
                targets[f"gd.{i}.{attr}"] = getattr(gd, attr)
    param_keys = {k for k in arrays
                  if not k.startswith(("loader.", "step.", "unitstate."))}
    if param_keys != set(targets):
        raise ValueError(
            f"snapshot/workflow architecture mismatch: snapshot-only keys "
            f"{sorted(param_keys - set(targets))}, workflow-only keys "
            f"{sorted(set(targets) - param_keys)}")
    # ...and the same strictness for state_dict-only units: the pytree
    # STRUCTURE (key set) must match the unit's current state; shape
    # semantics are the unit's own load_state_dict contract (e.g. the LM
    # validates d/blocks/vocab — the vocab dimension may legitimately
    # track the restored loader rather than the fresh build)
    snap_state_units = {int(k[len("unitstate."):].split(".")[0]
                            .split("#")[0])
                        for k in arrays if k.startswith("unitstate.")}
    if snap_state_units != set(state_only):
        raise ValueError(
            f"snapshot/workflow architecture mismatch: snapshot carries "
            f"unit state for {sorted(snap_state_units)}, workflow expects "
            f"it for {sorted(state_only)}")
    for i, fwd in state_only.items():
        expected: dict = {}
        _flatten_state(f"unitstate.{i}", fwd.state_dict(), expected)
        got = {k for k in arrays
               if k.startswith((f"unitstate.{i}.", f"unitstate.{i}#"))}
        if got != set(expected):
            raise ValueError(
                f"snapshot/workflow architecture mismatch in unit {i} "
                f"state: snapshot-only keys {sorted(got - set(expected))},"
                f" workflow-only keys {sorted(set(expected) - got)}")
    for key, arr in targets.items():
        if tuple(arrays[key].shape) != tuple(arr.shape):
            raise ValueError(f"{key}: snapshot shape {arrays[key].shape} "
                             f"!= workflow shape {arr.shape}")
        arr.map_invalidate()
        arr.mem = arrays[key]
    loader_state = dict(meta["loader"])
    loader_state["shuffled"] = {
        int(k.rsplit(".", 1)[1]): v for k, v in arrays.items()
        if k.startswith("loader.shuffled.")}
    norm_meta = loader_state.pop("normalizer_meta", None)
    if norm_meta is not None:
        prefix = "loader.normalizer."
        loader_state["normalizer"] = {
            "meta": norm_meta,
            "arrays": {k[len(prefix):]: v for k, v in arrays.items()
                       if k.startswith(prefix)}}
    workflow.loader.load_state_dict(loader_state)
    workflow.decision.load_state_dict(meta["decision"])
    prng.load_state_dict(meta["prng"])
    # state_dict-only forwards (after the loader restore: their guards
    # may depend on restored loader state, e.g. the LM vocab check)
    for i, fwd in state_only.items():
        fwd.load_state_dict(_unflatten_state(f"unitstate.{i}", arrays))
    step = getattr(workflow, "step", None)
    if step is not None and getattr(step, "_params", None) is not None \
            and hasattr(step, "gather_params"):
        # (state_dict-only steps — the transformer LM — restored above;
        # this branch is the FusedTrainStep re-placement path)
        # optimizer identity is training state: resuming adam moments as
        # sgd momentum (or adam from zeroed second moments) would change
        # semantics silently — fail loudly like the architecture check.
        # Snapshots predating the meta key were all sgd.
        snap_opt = meta.get("optimizer", "sgd")
        if getattr(step, "optimizer", "sgd") != snap_opt:
            raise ValueError(
                f"snapshot optimizer {snap_opt!r} != workflow optimizer "
                f"{step.optimizer!r}; rebuild the workflow with "
                f"optimizer={snap_opt!r}")
        step._params = step.gather_params()  # re-place restored weights
        # a restored normalizer may have re-normalized the loader's served
        # data: refresh the HBM-pinned dataset copy too
        step._pin_dataset()
        if "step.key" in arrays:
            from jax.sharding import NamedSharding, PartitionSpec
            step._key = jax.device_put(
                arrays["step.key"],
                NamedSharding(step.mesh, PartitionSpec()))
        opt = {k[len("step.opt."):]: v for k, v in arrays.items()
               if k.startswith("step.opt.")}
        has_ema = any(k.split(".", 1)[1] in ("ew", "eb") for k in opt)
        if has_ema and step.ema_decay is None:
            # injecting ew/eb into a step whose compiled functions were
            # built without them would crash later with an opaque
            # pytree-structure mismatch — fail loudly here instead
            raise ValueError(
                "snapshot carries EMA weight mirrors but the workflow "
                "was built without ema_decay; rebuild with ema_decay set")
        if opt:
            step.load_extra_state(opt)
    return meta


def write_snapshot(path: str, arrays: dict, meta: dict,
                   retry=DEFAULT_IO_RETRY) -> None:
    """Crash-safe snapshot write: content checksum into the metadata,
    temp file + flush + fsync + atomic ``os.replace`` publish (a crash at
    ANY point leaves either the old snapshot or the new one, never a torn
    file), flaky-filesystem ``OSError`` s retried under ``retry``."""
    meta = {**meta, "checksum": content_checksum(arrays)}

    def _write_once() -> None:
        # pid-unique temp name: even if the rank-0 election is bypassed
        # (mixed versions, operator error) two processes racing the same
        # snapshot path can each publish atomically instead of tearing
        # one shared temp file
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.savez_compressed(
                    f, __meta__=np.array(json.dumps(meta)), **arrays)
                f.flush()
                os.fsync(f.fileno())
            # chaos hook (site "snapshot.write"): fires between the
            # durable temp write and the publish, so an injected failure
            # aborts the snapshot WITHOUT touching the previously
            # published one — the invariant the supervisor relies on
            fault_hook("snapshot.write", path=path)
            os.replace(tmp, path)  # atomic publish
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)     # never leave stale temp litter

    if retry is None:
        _write_once()
    else:
        retry.call(_write_once)


def verify_snapshot(path: str) -> bool:
    """True iff ``path`` is a readable snapshot whose stored checksum
    (when present) matches its content.  ANY failure — unreadable zip,
    truncated member, bad JSON, checksum mismatch — is "invalid": the
    supervisor treats it as poison and falls back to an older snapshot."""
    try:
        with np.load(path, allow_pickle=False) as zf:
            meta = json.loads(str(zf["__meta__"]))
            if meta.get("format_version") != FORMAT_VERSION:
                return False
            arrays = {k: zf[k] for k in zf.files if k != "__meta__"}
        stored = meta.get("checksum")
        return stored is None or int(stored) == content_checksum(arrays)
    except Exception:  # noqa: BLE001 — corruption surfaces many ways
        return False


# -- units ------------------------------------------------------------------
class SnapshotterBase(Unit):
    """Periodic snapshot unit (reference: SnapshotterBase).

    Sits in the gated side chain after Decision; StandardWorkflow wires
    ``gate_skip = ~decision.epoch_ended``.  ``interval`` further thins to
    every k-th epoch; when ``only_improved`` (reference: keyed on
    Decision.improved) epochs without validation improvement are skipped.
    """

    def __init__(self, workflow=None, prefix: str = "wf",
                 directory: Optional[str] = None, interval: int = 1,
                 only_improved: bool = True, keep_all: bool = False,
                 verify_timeout: float = 5.0,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.prefix = prefix
        self.directory = directory or os.getcwd()
        self.interval = int(interval)
        self.only_improved = only_improved
        self.keep_all = keep_all
        #: multi-process election (ISSUE 9): how long a non-zero rank
        #: waits for rank 0's snapshot to appear before degrading to a
        #: warning (the fleet's ranks run the same replicated decision
        #: logic, so they reach — and gate — the same epochs).  Keep it
        #: at or below the fleet's SIGTERM ``term_grace``: a verifier
        #: whose writer just died should warn and exit gracefully, not
        #: out-wait its own kill
        self.verify_timeout = float(verify_timeout)
        #: verification outcomes on non-zero ranks, for tests/status
        self.verified_ok = 0
        self.verified_failed = 0
        self.target_workflow = None
        self.decision = None
        #: path of the most recent snapshot (reference: destination)
        self.destination: Optional[str] = None
        self._epoch_counter = 0

    def link_workflow_state(self, workflow) -> "SnapshotterBase":
        self.target_workflow = workflow
        self.decision = workflow.decision
        return self

    def run(self) -> None:
        self._epoch_counter += 1
        if self._epoch_counter % self.interval != 0:
            return
        if self.only_improved and not bool(self.decision.improved):
            return
        self.export()

    def snapshot_path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"{self.prefix}_{epoch}.npz")

    def export(self) -> None:
        raise NotImplementedError


class SnapshotterToFile(SnapshotterBase):
    """Writes ``{prefix}_{epoch}.npz`` + ``{prefix}_latest.npz`` symlink
    (reference: SnapshotterToFile; compression is npz-deflate instead of
    the reference's gz/bz2/xz-by-extension)."""

    def _verify_published(self, path: str) -> bool:
        """Non-zero-rank half of the snapshot election: poll for rank
        0's file at ``path`` and checksum-verify it.  Degrades to a
        warning on timeout or corruption — a verifier must never kill
        the training run (rank 0 may have died; the fleet supervisor
        owns that failure)."""
        deadline = time.monotonic() + self.verify_timeout
        while not os.path.exists(path):
            if time.monotonic() >= deadline:
                self.verified_failed += 1
                self.warning(f"snapshot election: rank-0 snapshot {path} "
                             f"did not appear within "
                             f"{self.verify_timeout}s")
                return False
            time.sleep(0.05)
        # rank 0 publishes atomically (os.replace), so an existing path
        # is a complete file; a checksum failure is real corruption
        if verify_snapshot(path):
            self.verified_ok += 1
            self.debug(f"snapshot election: verified {path}")
            return True
        self.verified_failed += 1
        self.warning(f"snapshot election: {path} FAILED checksum "
                     f"verification")
        return False

    def _sweep_stale_temps(self) -> None:
        """Unlink ``<prefix>_*.npz.tmp.<pid>`` litter left by writers
        that were SIGKILL'd mid-write (pid-unique temps are crash-safe
        but not self-cleaning the way the old shared name was).  Only
        temps whose owning pid is gone are removed — a live concurrent
        writer keeps its file."""
        import glob as _glob
        for tmp in _glob.glob(os.path.join(
                self.directory, f"{self.prefix}_*.npz.tmp.*")):
            pid_text = tmp.rsplit(".", 1)[1]
            if pid_text.isdigit() and int(pid_text) != os.getpid():
                try:
                    os.kill(int(pid_text), 0)    # raises if pid is gone
                except ProcessLookupError:
                    try:
                        os.unlink(tmp)
                        self.debug(f"swept stale snapshot temp {tmp}")
                    except OSError:
                        pass
                except OSError:
                    pass                         # EPERM: someone else's

    def export(self) -> None:
        w = self.target_workflow
        rank, world = process_rank_world()
        if rank != 0:
            # rank-0-writes / all-ranks-verify: concurrent writers would
            # race each other into torn files; every other rank instead
            # verifies the published artifact so corruption is caught at
            # save time on some rank, not at restore time after a crash
            epoch = int(w.loader.epoch_number)
            self._verify_published(self.snapshot_path(epoch))
            return
        arrays, meta = collect_state(w)
        epoch = int(meta["loader"]["epoch_number"])
        path = self.snapshot_path(epoch)
        os.makedirs(self.directory, exist_ok=True)
        self._sweep_stale_temps()
        try:
            write_snapshot(path, arrays, meta)
        except OSError as exc:
            # a snapshot that cannot be written (full/flaky disk, even
            # after retries) must not kill the training run: the previous
            # published snapshot stays the resume point.  Injected
            # crashes (FaultInjected) are not OSError and do propagate.
            self.error(f"snapshot write failed after retries, keeping "
                       f"{self.destination!r} as resume point: {exc!r}")
            return
        # prune only after the new snapshot is durably published — a failed
        # write must never leave the run without a resumable checkpoint
        if not self.keep_all and self.destination and \
                self.destination != path and \
                os.path.exists(self.destination):
            os.unlink(self.destination)
        self.destination = path
        latest = os.path.join(self.directory, f"{self.prefix}_latest.npz")
        try:
            if os.path.lexists(latest):
                os.unlink(latest)
            os.symlink(os.path.basename(path), latest)
        except OSError:
            pass  # symlink-less filesystems: latest pointer is best-effort
        self.info(f"snapshot -> {path}")


class NNSnapshotter(SnapshotterToFile):
    """SnapshotterToFile + per-layer weight statistics logging (reference:
    nn_units.py :: NNSnapshotter logs min/max/avg of weights/bias)."""

    def export(self) -> None:
        super().export()
        for i, fwd in enumerate(self.target_workflow.forwards):
            for attr in ("weights", "bias"):
                # three-arg: state_dict-only forwards carry no Arrays
                arr = getattr(fwd, attr, None)
                if arr:
                    m = arr.map_read()
                    self.info(
                        f"{fwd.name}.{attr}: min {m.min():+.4f} "
                        f"max {m.max():+.4f} avg {m.mean():+.4f}")
