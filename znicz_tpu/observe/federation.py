"""Fleet telemetry — cross-process metric federation (ISSUE 11
tentpole).

Every observability surface so far (registry, tracer, watchtower,
flight recorder) is per-process, but PR 9's elastic supervisor and
PR 10's generate workers each keep a private registry nobody
aggregates — "queue saturation across N workers", the serving-fleet
ROADMAP item's autoscaler signal, was unobservable.  This module is the
VELES master-owns-the-global-view heritage (PAPER.md §1) rebuilt as a
telemetry plane: the same master/worker monitoring split TensorFlow's
runtime relies on at scale (Abadi et al. 2016, PAPERS.md).

Three pieces:

- **worker side**: :class:`MetricsExporter` / :func:`start_metrics_
  export` — a daemon thread atomically rewriting one rank-tagged JSON
  file (``{"schema", "rank", "ts", "prom"}``) with the process-global
  registry's Prometheus text every ``interval_s``.  Serve workers need
  none of this (their ``/metrics.prom`` endpoint IS the scrape
  surface); elastic training ranks get it wired by ``__main__`` off
  ``$ZNICZ_TPU_METRICS_EXPORT``, beside the PR 9 heartbeat files.
- **supervisor side**: :class:`FleetAggregator` — scrapes or ingests N
  workers' registries (HTTP ``/metrics.prom``, exporter files, or any
  zero-arg callable), injects a ``rank`` label onto every series, and
  merges them into one fleet view served as ``GET /fleet/metrics``
  (JSON), ``/fleet/metrics.prom`` (Prometheus text, one ``TYPE`` per
  family, per-rank sample lines) and ``/fleet/status.json`` (per-rank
  liveness + the fleet watchtower's rule states) — on its own
  :meth:`~FleetAggregator.serve` listener or mounted into a
  :class:`~znicz_tpu.web_status.WebStatus` via ``register_fleet``.
- **judgment**: the aggregator owns a fleet-level
  :class:`~znicz_tpu.observe.watchtower.Watchtower` whose ring samples
  the MERGED view — the existing rule machinery composes unchanged:
  a family selector sums across ranks (total queue depth), a
  ``rank="1"`` label filter isolates one worker, and the
  ``window_quantile`` reduce runs over rank-merged ``_bucket{le=}``
  deltas, so "fleet p95 latency" is one rule, not new code.  Trips ride
  the normal flight auto-dump, and because the aggregator registers
  itself as a flight *plane* (``flight.register_plane("fleet", ...)``),
  every artifact dumped in the supervisor process embeds each worker's
  last snapshot.

Distributed-trace merging rides the same topology: every worker's
``Tracer.export_dict()`` now carries its rank and a wall-clock anchor
for its monotonic origin, and :func:`merge_traces` aligns N such
documents onto one Perfetto-loadable timeline (``pid`` = rank, events
shifted onto the earliest origin).  ``GET /fleet/trace.json`` merges
the HTTP sources' live rings; ``python -m znicz_tpu trace --fleet -o
out.json SRC...`` merges URLs or exported files offline.

Everything here is stdlib — an aggregator never imports jax, so the
supervisor process stays as light as the PR 9 fleet loop.  Clock
alignment uses ``time.time()`` (shared on one host; across hosts it is
only as good as NTP — the merged doc keeps per-rank origins so skew is
auditable).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import urllib.request
import zlib
from concurrent.futures import ThreadPoolExecutor
from http.server import ThreadingHTTPServer
from typing import Callable, Optional, Sequence, Union

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import registry as _reg
from znicz_tpu.observe.watchtower import Rule, Watchtower

#: worker-side env contract (set per worker by resilience/elastic.py,
#: honored by __main__ exactly like $ZNICZ_TPU_HEARTBEAT)
METRICS_EXPORT_ENV = "ZNICZ_TPU_METRICS_EXPORT"
METRICS_EXPORT_INTERVAL_ENV = "ZNICZ_TPU_METRICS_EXPORT_INTERVAL"

#: exporter file schema identifier
EXPORT_SCHEMA = "znicz_tpu.metrics/1"

# aggregator self-telemetry (the SUPERVISOR's own registry — served by
# its own /metrics, never mixed into the merged worker view)
_M_WORKERS = _reg.gauge(
    "znicz_fleet_workers",
    "worker sources with a fresh scrape (live fleet width as the "
    "aggregator sees it)")
_M_SCRAPES = _reg.counter(
    "znicz_fleet_scrapes_total",
    "aggregator scrape attempts by worker and outcome",
    labelnames=("rank", "outcome"))
_M_SCRAPE_SECONDS = _reg.histogram(
    "znicz_fleet_scrape_seconds",
    "wall time of one worker scrape (HTTP fetch / file read + parse)")


def fleet_rank() -> Optional[int]:
    """This process's fleet rank, or None outside a fleet.  Reads the
    elastic env contract directly (``ZNICZ_TPU_ELASTIC_RANK``,
    resilience/elastic.py) — the observe plane must not import the
    resilience plane, which imports it."""
    rank = os.environ.get("ZNICZ_TPU_ELASTIC_RANK")
    if rank is None:
        return None
    try:
        return int(rank)
    except ValueError:
        return None


# -- Prometheus text ingestion ------------------------------------------------

def parse_prometheus(text: str):
    """Parse exposition text into ``(families, samples)``:
    ``families`` maps family name -> ``{"type", "help"}`` (registration
    order preserved); ``samples`` is ``[(family, name, inner, value)]``
    in document order, where ``inner`` is the raw label string between
    the braces (``'le="0.5"'``, '' when label-less) — kept raw so
    re-rendering and rank injection never re-escape label values.

    Histogram children (``_bucket``/``_sum``/``_count``) attach to the
    family their preceding ``# TYPE`` line declared, the exposition
    convention ``render_prometheus`` emits.  A sample line that does
    not parse raises ``ValueError`` naming it — the concurrent-scrape
    soak relies on torn text failing loudly, not half-merging."""
    families: dict = {}
    samples: list = []
    current: Optional[str] = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) == 4:
                current = parts[2]
                families.setdefault(current, {"type": None, "help": ""})
                families[current]["type"] = parts[3].strip()
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                families.setdefault(parts[2], {"type": None, "help": ""})
                families[parts[2]]["help"] = \
                    parts[3].strip() if len(parts) == 4 else ""
            continue
        if line.startswith("#"):
            continue
        # the closing brace is the LAST "}" on the line (label values
        # may contain a raw "}", but the value/timestamp tail is
        # numeric); an optional trailing timestamp (valid 0.0.4) is
        # accepted and dropped rather than mis-parsed as the value
        name_part, brace, rest = line.partition("{")
        if brace:
            inner, closed, tail = rest.rpartition("}")
            if not closed:
                raise ValueError(f"unclosed label block in exposition "
                                 f"line: {line!r}")
            name, fields = name_part, tail.split()
        else:
            fields = line.split()
            name, fields = fields[0], fields[1:]
            inner = ""
        if not name or not fields or len(fields) > 2:
            raise ValueError(f"unparseable exposition line: {line!r}")
        try:
            value = float(fields[0])
        except ValueError as exc:
            raise ValueError(
                f"unparseable sample value in line: {line!r}") from exc
        family = current if current is not None and \
            name.startswith(current) else name
        samples.append((family, name, inner, value))
    return families, samples


def inject_rank(inner: str, rank) -> str:
    """Append ``rank="N"`` to a raw label string (no-op when the series
    already carries a rank label — an aggregator scraping another
    aggregator must not double-tag)."""
    if 'rank="' in inner:
        return inner
    return f'{inner},rank="{rank}"' if inner else f'rank="{rank}"'


# -- worker-side exporter -----------------------------------------------------

class MetricsExporter:
    """Daemon thread atomically rewriting ``path`` with this process's
    registry rendered as Prometheus text, wrapped in a small JSON
    envelope (rank, wall-clock stamp) so the aggregator can tell a live
    worker from a stale file.  Write failures are swallowed — a full
    disk must not kill the trainer, only its telemetry (the PR 9
    heartbeat convention)."""

    def __init__(self, path: str, interval_s: float = 1.0,
                 registry: Optional[_reg.Registry] = None) -> None:
        self.path = str(path)
        self.interval_s = float(interval_s)
        self._registry = registry or _reg.REGISTRY
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="znicz-metrics-export")

    def start(self) -> "MetricsExporter":
        self._thread.start()
        return self

    def write_once(self) -> None:
        doc = {"schema": EXPORT_SCHEMA,
               "rank": fleet_rank() or 0,
               "pid": os.getpid(),
               "ts": time.time(),
               "prom": self._registry.render_prometheus()}
        tmp = f"{self.path}.{os.getpid()}.tmp"   # pid-unique: racers
        try:                                     # cannot tear a shared tmp
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.write_once()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        """Stop the cadence and publish one final snapshot — the state
        a post-mortem wants is the one at exit, not one interval ago."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        self.write_once()


def start_metrics_export(path: str, interval_s: float = 1.0,
                         registry: Optional[_reg.Registry] = None
                         ) -> MetricsExporter:
    """Start a :class:`MetricsExporter`; ``__main__`` calls this when
    ``$ZNICZ_TPU_METRICS_EXPORT`` is set (the elastic fleet's worker
    env contract)."""
    return MetricsExporter(path, interval_s, registry).start()


# -- trace merging ------------------------------------------------------------

def merge_traces(docs: Sequence[dict]) -> dict:
    """Align N ``Tracer.export_dict()`` documents onto ONE
    Perfetto-loadable timeline: each document's events shift by the
    difference between its wall-clock origin and the earliest one, its
    ``pid`` becomes the worker's rank (falling back to 1000+index for
    rank-less docs), and one ``process_name`` metadata row per rank
    labels the track.  Per-rank origins ride along under ``"origins"``
    so cross-host NTP skew stays auditable."""
    base = min((d["origin_unix_ts"] for d in docs
                if d.get("origin_unix_ts") is not None), default=None)
    events: list = []
    origins: dict = {}
    for i, doc in enumerate(docs):
        rank = doc.get("rank")
        pid = rank if rank is not None else 1000 + i
        origin = doc.get("origin_unix_ts")
        shift = 0.0 if base is None or origin is None \
            else (origin - base) * 1e6
        origins[str(pid)] = origin
        name = doc.get("label") or (f"rank {rank}" if rank is not None
                                    else f"source {i}")
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name", "args": {"name": name}})
        for ev in doc.get("traceEvents", ()):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue               # replaced by the rank row above
            ev = dict(ev)
            ev["pid"] = pid
            if ev["ph"] != "M":
                ev["ts"] = round(ev.get("ts", 0.0) + shift, 3)
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "origins": origins}


def _load_trace_source(src: str, timeout_s: float = 10.0) -> dict:
    """One ``merge_traces`` input from a URL (a worker base or a full
    ``/trace.json`` URL) or a local exported-trace file path."""
    if src.startswith(("http://", "https://")):
        url = src if src.endswith(".json") else \
            src.rstrip("/") + "/trace.json"
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.load(r)
    with open(src) as f:
        return json.load(f)


def fleet_trace_main(argv) -> int:
    """``python -m znicz_tpu trace --fleet -o out.json SRC [SRC ...]``
    — SRC is a worker base URL (its ``/trace.json`` is fetched), a full
    trace URL, or an exported trace file.  Writes the merged
    Perfetto-loadable timeline to ``-o`` (default
    ``fleet_trace.json``)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="znicz_tpu trace --fleet",
        description="merge worker trace timelines onto one clock")
    p.add_argument("sources", nargs="+",
                   help="worker base URLs, /trace.json URLs, or "
                        "exported trace files")
    p.add_argument("-o", "--output", default="fleet_trace.json")
    args = p.parse_args(argv)
    docs = []
    for src in args.sources:
        try:
            docs.append(_load_trace_source(src))
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"trace --fleet: cannot load {src!r}: {exc}",
                  file=sys.stderr)
            return 2
    merged = merge_traces(docs)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    n = sum(1 for e in merged["traceEvents"] if e["ph"] != "M")
    print(f"trace --fleet: merged {n} events from {len(docs)} "
          f"source(s) -> {args.output}")
    return 0


# -- the aggregator -----------------------------------------------------------

def _cumulative(family_type: Optional[str], name: str) -> bool:
    """Whether a sample is monotonic-cumulative (counter / histogram
    child) as opposed to a level (gauge).  Stale sources keep their
    cumulative series in the merge at last-known values — dropping a
    counter to 0 and snapping it back on recovery would register the
    worker's lifetime total as in-window growth and falsely trip every
    delta/quantile rule — while their gauges drop out (a dead worker's
    queue must not read saturated forever).  Untyped exposition falls
    back to the name-suffix convention."""
    if family_type in ("counter", "histogram"):
        return True
    if family_type == "gauge":
        return False
    return name.endswith(("_total", "_count", "_sum", "_bucket"))


class _Source:
    """One worker's scrape target + its last successful ingest."""

    __slots__ = ("rank", "kind", "target", "ts", "families", "samples",
                 "ok", "error", "scrapes", "label")

    def __init__(self, rank, kind: str, target,
                 label: Optional[str] = None) -> None:
        self.rank = rank
        self.kind = kind                  # "http" | "file" | "callable"
        self.target = target
        #: display name for non-worker sources (ISSUE 13: the serving
        #: fleet's ROUTER scrapes into the same merged view — its trace
        #: track reads "router", not "rank 9000")
        self.label = label
        self.ts: Optional[float] = None   # wall stamp of the last ingest
        self.families: dict = {}
        self.samples: list = []
        self.ok = False
        self.error: Optional[str] = None
        self.scrapes = 0


class FleetAggregator(Logger):
    """Merge N workers' registries into one rank-labeled fleet view;
    see module docstring.  ``stale_s`` bounds how old a source's data
    may be before it stops counting as a live worker — past it, the
    source's GAUGES drop out of the merge (a dead worker's queue-depth
    gauge must not read saturated forever — the watchtower ring's
    vanish-to-zero discipline) while its CUMULATIVE series (counters,
    histogram buckets) carry forward at their last value: vanishing a
    counter to 0 and snapping it back on recovery would register the
    worker's whole lifetime as in-window growth and falsely trip every
    delta/quantile fleet rule.  A transiently FAILING scrape keeps
    serving the cached data until it ages out, for the same reason.
    ``min_refresh_s`` coalesces concurrent scrape triggers (the fleet
    tower's cadence, HTTP requests, flight dumps) into one fetch per
    window; within a pass, sources are scraped concurrently so one
    unreachable worker costs the pass ``timeout_s`` once, not per
    caller per source."""

    def __init__(self, stale_s: float = 15.0, timeout_s: float = 5.0,
                 min_refresh_s: float = 0.25, capacity: int = 720) -> None:
        super().__init__()
        self.stale_s = float(stale_s)
        self.timeout_s = float(timeout_s)
        self.min_refresh_s = float(min_refresh_s)
        self._sources: dict = {}
        self._lock = threading.Lock()          # sources map + gate
        self._refresh_lock = threading.Lock()  # one scrape pass at a time
        self._executor = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="znicz-fleet-scrape")
        self._last_refresh: Optional[float] = None
        #: the fleet watchtower samples THIS object: ``snapshot_flat``
        #: below is the merged rank-labeled view, so every existing
        #: reduce (family sums, label filters, bucket-delta quantiles)
        #: works fleet-wide unchanged
        self.tower = Watchtower(capacity=capacity, registry=self)
        #: top-level ``/fleet/status.json`` blocks from the planes that
        #: own fleet-wide facts (ISSUE 14 satellite): the worker pool
        #: registers ``"package"`` (current fingerprint + convergence),
        #: the router's rollout registers ``"rollout"``, the learn
        #: bridge registers ``"learn"`` — so operators and the adoption
        #: gate read ONE document instead of folding per-worker /readyz
        #: answers themselves
        self._status_providers: dict = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self.port = 0
        # every flight artifact dumped in this process now embeds each
        # worker's last snapshot (newest aggregator wins the name, the
        # registry-gauge convention).  The bound method is stored so
        # close() can conditionally unregister the EXACT object it
        # registered — a fresh `self.workers_snapshot` access creates a
        # new bound-method object that would never compare `is`
        self._flight_plane = self.workers_snapshot
        _flight.register_plane("fleet", self._flight_plane)

    # -- sources -------------------------------------------------------------
    def add_http_source(self, rank, base_url: str,
                        label: Optional[str] = None) -> "FleetAggregator":
        """A serve/generate worker: ``<base_url>/metrics.prom`` is
        scraped; its ``/trace.json`` feeds the merged fleet trace.
        ``label`` names a non-worker source (the fleet ROUTER, ISSUE
        13) on the merged trace's process row and in
        ``/fleet/status.json``."""
        with self._lock:
            self._sources[int(rank)] = _Source(
                int(rank), "http", base_url.rstrip("/"), label=label)
        return self

    def add_file_source(self, rank, path: str) -> "FleetAggregator":
        """An elastic training rank: ``path`` is the worker's
        :class:`MetricsExporter` file beside its heartbeat."""
        with self._lock:
            self._sources[int(rank)] = _Source(int(rank), "file",
                                               str(path))
        return self

    def add_source(self, rank, fn: Callable[[], Union[str, dict]]
                   ) -> "FleetAggregator":
        """A zero-arg callable returning exposition text or an exporter
        envelope dict — the deterministic-test hook."""
        with self._lock:
            self._sources[int(rank)] = _Source(int(rank), "callable", fn)
        return self

    def remove_source(self, rank) -> None:
        with self._lock:
            self._sources.pop(int(rank), None)

    def clear_sources(self) -> None:
        with self._lock:
            self._sources.clear()

    def ranks(self) -> list:
        with self._lock:
            return sorted(self._sources)

    # -- scraping ------------------------------------------------------------
    def _fetch(self, src: _Source) -> tuple:
        """-> (wall_ts, prom_text) for one source; raises on any
        failure (unreachable worker, torn file, bad envelope)."""
        if src.kind == "http":
            with urllib.request.urlopen(src.target + "/metrics.prom",
                                        timeout=self.timeout_s) as r:
                return time.time(), r.read().decode()
        if src.kind == "file":
            with open(src.target) as f:
                doc = json.load(f)
            if doc.get("schema") != EXPORT_SCHEMA:
                raise ValueError(
                    f"{src.target}: not a metrics export "
                    f"(schema={doc.get('schema')!r})")
            return float(doc["ts"]), doc["prom"]
        out = src.target()
        if isinstance(out, dict):
            return float(out.get("ts", time.time())), out["prom"]
        return time.time(), out

    def _fresh(self, src: _Source, now: Optional[float] = None) -> bool:
        # data-age only, NOT the latest attempt's outcome: one
        # transient scrape failure (GC pause, torn file read) must not
        # instantly vanish a live worker's series — the data keeps
        # serving until it ages past stale_s (src.ok/src.error still
        # record the attempt for /fleet/status.json)
        if src.ts is None:
            return False
        return (now if now is not None else time.time()) - src.ts \
            <= self.stale_s

    def _scrape_one(self, src: _Source) -> None:
        t0 = time.perf_counter()
        try:
            ts, text = self._fetch(src)
            families, samples = parse_prometheus(text)
            src.ts, src.families, src.samples = ts, families, samples
            src.ok, src.error = True, None
            outcome = "ok"
        except Exception as exc:  # noqa: BLE001 — one dead worker
            src.ok, src.error = False, repr(exc)   # must not kill
            outcome = "error"                      # the fleet view
        src.scrapes += 1
        _M_SCRAPES.labels(rank=str(src.rank), outcome=outcome).inc()
        _M_SCRAPE_SECONDS.observe(time.perf_counter() - t0)

    def refresh(self, force: bool = False) -> None:
        """Scrape every source (coalesced to one pass per
        ``min_refresh_s`` unless forced; one pass at a time).  Sources
        scrape concurrently, so a pass over a fleet with K unreachable
        workers costs ~``timeout_s``, not K times it."""
        with self._refresh_lock:
            with self._lock:
                now = time.monotonic()
                if not force and self._last_refresh is not None and \
                        now - self._last_refresh < self.min_refresh_s:
                    return
                self._last_refresh = now
                sources = list(self._sources.values())
            if len(sources) == 1:
                self._scrape_one(sources[0])
            elif sources:
                list(self._executor.map(self._scrape_one, sources))
            wall = time.time()
            _M_WORKERS.set(sum(1 for s in sources
                               if self._fresh(s, wall)))

    # -- merged views --------------------------------------------------------
    def snapshot_flat(self, skip_zero: bool = True,
                      buckets: bool = False) -> dict:
        """The merged fleet view in the registry's flat-key shape —
        every worker series carries an injected ``rank`` label, plus a
        synthetic ``znicz_fleet_worker_up{rank=}`` 1/0 per source so
        rules can watch fleet width.  This is the
        ``Registry.snapshot_flat`` signature on purpose: the fleet
        :class:`Watchtower`'s ring samples this object directly."""
        self.refresh()
        wall = time.time()
        out: dict = {}
        with self._lock:
            sources = [self._sources[r] for r in sorted(self._sources)]
        for src in sources:
            up = self._fresh(src, wall)
            out[f'znicz_fleet_worker_up{{rank="{src.rank}"}}'] = \
                1.0 if up else 0.0
            for family, name, inner, value in src.samples:
                if not up and not _cumulative(
                        src.families.get(family, {}).get("type"), name):
                    continue           # stale gauges drop; counters stay
                if not buckets and name.endswith("_bucket"):
                    continue
                if skip_zero and value == 0.0:
                    continue
                out[f"{name}{{{inject_rank(inner, src.rank)}}}"] = value
        return out

    def render_prometheus(self) -> str:
        """The merged fleet exposition (``GET /fleet/metrics.prom``):
        one ``TYPE``/``HELP`` declaration per family (the first source
        carrying type metadata wins), then every rank's sample
        lines."""
        self.refresh()
        wall = time.time()
        with self._lock:
            sources = [self._sources[r] for r in sorted(self._sources)]
        fams: dict = {}        # name -> {"type", "help", "lines": []}
        up_lines = []
        for src in sources:
            up = self._fresh(src, wall)
            up_lines.append(
                f'znicz_fleet_worker_up{{rank="{src.rank}"}} '
                f'{1 if up else 0}')
            for family, name, inner, value in src.samples:
                meta = src.families.get(family, {})
                if not up and not _cumulative(meta.get("type"), name):
                    continue           # stale gauges drop; counters stay
                fam = fams.setdefault(
                    family, {"type": meta.get("type") or "untyped",
                             "help": meta.get("help", ""), "lines": []})
                if fam["type"] == "untyped" and meta.get("type"):
                    # the first source SEEN may lack metadata (e.g. a
                    # schema-drifted stale cache) — the first source
                    # CARRYING a type wins instead
                    fam["type"] = meta["type"]
                    fam["help"] = fam["help"] or meta.get("help", "")
                fam["lines"].append(
                    f"{name}{{{inject_rank(inner, src.rank)}}} "
                    f"{_reg._fmt(value)}")
        lines = ["# HELP znicz_fleet_worker_up 1 while the rank's last "
                 "scrape is fresh (aggregator-synthesized)",
                 "# TYPE znicz_fleet_worker_up gauge"] + up_lines
        for name, fam in fams.items():
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            lines.extend(fam["lines"])
        return "\n".join(lines) + "\n"

    def workers_snapshot(self) -> dict:
        """Per-rank last-known state — embedded into every flight
        artifact via the ``"fleet"`` plane.  Deliberately serves the
        CACHED scrape (no network in a crash path)."""
        wall = time.time()
        with self._lock:
            sources = [self._sources[r] for r in sorted(self._sources)]
        out = {}
        for src in sources:
            flat = {f"{name}{{{inject_rank(inner, src.rank)}}}": value
                    for _, name, inner, value in src.samples
                    if not name.endswith("_bucket")}
            out[str(src.rank)] = {
                "kind": src.kind,
                "label": src.label,
                "target": src.target if src.kind != "callable"
                else repr(src.target),
                "ok": src.ok, "error": src.error,
                "age_s": round(wall - src.ts, 3)
                if src.ts is not None else None,
                "scrapes": src.scrapes,
                "flat": flat}
        return out

    def metrics_doc(self) -> dict:
        """``GET /fleet/metrics``: the merged flat view + per-rank
        scrape health."""
        flat = self.snapshot_flat(skip_zero=True, buckets=False)
        return {"workers": {r: {k: v for k, v in w.items()
                                if k != "flat"}
                            for r, w in self.workers_snapshot().items()},
                "flat": flat}

    # -- status providers (ISSUE 14 satellite) -------------------------------
    def register_status_provider(self, key: str, fn: Callable[[], dict]
                                 ) -> None:
        """Merge ``fn()`` into ``/fleet/status.json`` under top-level
        ``key`` — fleet-wide facts (package fingerprint, rollout state,
        learn-plane adoption) surface in one document.  A provider
        failure degrades to an ``{"error": ...}`` block, never a 500."""
        with self._lock:
            self._status_providers[str(key)] = fn

    def unregister_status_provider(self, key: str, fn=None) -> None:
        """Remove ``key`` (only if still ``fn``, when given — the
        newest-registrant-wins convention the flight planes use)."""
        with self._lock:
            if fn is None or self._status_providers.get(key) is fn:
                self._status_providers.pop(str(key), None)

    def status_doc(self) -> dict:
        """``GET /fleet/status.json``: liveness + the fleet
        watchtower's rule states, plus every registered provider's
        top-level block (``package``/``rollout``/``learn``)."""
        self.refresh()
        with self._lock:
            providers = dict(self._status_providers)
        doc = {"workers": {r: {k: v for k, v in w.items()
                               if k != "flat"}
                           for r, w in self.workers_snapshot().items()},
               "watchtower": self.tower.snapshot()}
        for key, fn in providers.items():
            try:
                doc[key] = fn()
            except Exception as exc:  # noqa: BLE001 — one dead plane
                doc[key] = {"error": repr(exc)}   # must not 500 status
        return doc

    def trace_doc(self) -> dict:
        """``GET /fleet/trace.json``: the HTTP sources' live tracer
        rings merged onto one timeline (file/callable ranks cannot be
        trace-scraped — they are listed under ``"missing"``; training
        ranks export via ``--trace`` or flight artifacts instead)."""
        with self._lock:
            sources = [self._sources[r] for r in sorted(self._sources)]
        docs, missing = [], []
        for src in sources:
            if src.kind != "http":
                missing.append(src.rank)
                continue
            try:
                with urllib.request.urlopen(src.target + "/trace.json",
                                            timeout=self.timeout_s) as r:
                    doc = json.load(r)
                if doc.get("rank") is None:
                    # a worker outside an elastic fleet exports
                    # rank=None — the REGISTRATION rank is its identity
                    # here (setdefault would never fire on the
                    # explicit None export_dict always writes)
                    doc["rank"] = src.rank
                if src.label:
                    doc["label"] = src.label
                docs.append(doc)
            except Exception as exc:  # noqa: BLE001 — merge what lives
                missing.append(src.rank)
                self.warning(f"fleet trace scrape rank {src.rank} "
                             f"failed: {exc!r}")
        merged = merge_traces(docs)
        merged["missing"] = missing
        return merged

    # -- fleet watchtower ----------------------------------------------------
    def add_rule(self, rule: Rule) -> Rule:
        """Add one SLO rule over the MERGED view (family selectors sum
        across ranks; ``{rank="N"}`` filters isolate one worker)."""
        return self.tower.add_rule(rule)

    def add_rule_per_rank(self, make_rule: Callable[[int], Rule]) -> list:
        """Instantiate ``make_rule(rank)`` for every registered source
        — the "any-rank" pattern (e.g. a per-rank recompile storm: ONE
        misbehaving worker must trip even while the fleet sum stays
        quiet)."""
        return [self.add_rule(make_rule(rank)) for rank in self.ranks()]

    def start(self, interval_s: float = 2.0) -> None:
        """Background scrape-and-judge cadence (the fleet tower's
        sampler thread; each sample triggers one coalesced refresh)."""
        self.tower.start(interval_s)

    def stop(self) -> None:
        self.tower.stop()

    # -- HTTP ----------------------------------------------------------------
    def http_payload(self, path: str):
        """``(body_bytes, content_type)`` for one ``/fleet/*`` path, or
        None for paths this plane does not own — shared by the
        aggregator's own listener and ``WebStatus.register_fleet``."""
        if path.startswith("/fleet/metrics.prom"):
            return (self.render_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
        if path.startswith("/fleet/metrics"):
            return json.dumps(self.metrics_doc()).encode(), \
                "application/json"
        if path.startswith("/fleet/status.json"):
            return json.dumps(self.status_doc()).encode(), \
                "application/json"
        if path.startswith("/fleet/trace.json"):
            return json.dumps(self.trace_doc()).encode(), \
                "application/json"
        return None

    def serve(self, port: int = 0) -> int:
        """Standalone fleet listener (the supervisor case, where no
        WebStatus runs): serves the four ``/fleet/*`` endpoints;
        un-prefixed paths (``/metrics.prom``) alias into the fleet
        namespace for scraper convenience."""
        from http.server import BaseHTTPRequestHandler

        agg = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                path = self.path if self.path.startswith("/fleet/") \
                    else "/fleet" + (self.path if self.path != "/"
                                     else "/status.json")
                payload = agg.http_payload(path)
                if payload is None:
                    body, ctype = (json.dumps(
                        {"error": f"unknown path {self.path!r}"}).encode(),
                        "application/json")
                    self.send_response(404)
                else:
                    body, ctype = payload
                    self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="znicz-fleet-http")
        self._http_thread.start()
        self.info(f"fleet telemetry on http://127.0.0.1:{self.port}"
                  f"/fleet/ ({len(self.ranks())} source(s))")
        return self.port

    def stop_server(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def close(self) -> None:
        """Full teardown: cadence, listener, and this aggregator's
        flight plane (only if still the registered one)."""
        self.stop()
        self.stop_server()
        _flight.unregister_plane("fleet", self._flight_plane)
        self._executor.shutdown(wait=False)


# -- fleet rule catalogue (docs/OBSERVABILITY.md) -----------------------------

def fleet_queue_saturation(depth: float = 64.0, for_s: float = 5.0,
                           metric: str = "znicz_serve_queue_depth",
                           action: Optional[Callable] = None) -> Rule:
    """TOTAL admission-queue depth across every rank pinned above
    ``depth`` — the serving-fleet autoscaler signal (family selectors
    sum across the injected rank labels).  Point ``metric`` at
    ``znicz_generate_queue_depth`` for the generative plane."""
    return Rule(
        f"fleet_queue_saturation[{metric}]"
        if metric != "znicz_serve_queue_depth"
        else "fleet_queue_saturation",
        metric, lambda v: v > depth, for_s=for_s, action=action,
        description=f"fleet-total {metric} > {depth:g} for {for_s:g}s")


def fleet_latency_slo(p95_s: float, window_s: float = 60.0,
                      metric: str = "znicz_serve_latency_seconds",
                      min_count: int = 8,
                      action: Optional[Callable] = None) -> Rule:
    """Fleet p95 latency over ``window_s`` above ``p95_s`` seconds —
    the quantile runs over bucket-count deltas MERGED across ranks, so
    one slow worker degrades the fleet figure in proportion to its
    traffic share (point ``metric`` at ``znicz_generate_ttft_seconds``
    for a TTFT SLO)."""
    return Rule(
        f"fleet_latency_slo[{metric}]"
        if metric != "znicz_serve_latency_seconds" else "fleet_latency_slo",
        metric, lambda q: q > p95_s, window_s=window_s,
        reduce="window_quantile", quantile=0.95, min_count=min_count,
        action=action,
        description=f"fleet p95 {metric} > {p95_s:g}s over {window_s:g}s")


def any_rank_recompile_storm(rank: int, max_in_window: float = 3.0,
                             window_s: float = 60.0,
                             metric: str = "znicz_recompiles_total",
                             action: Optional[Callable] = None) -> Rule:
    """ONE rank recompiling after warmup — use with
    ``add_rule_per_rank(lambda r: any_rank_recompile_storm(r))``: the
    fleet sum would dilute a single worker's storm across N quiet
    peers, so each rank gets its own label-filtered rule."""
    return Rule(
        f"any_rank_recompile_storm[{rank}]",
        f'{metric}{{rank="{rank}"}}',
        lambda d: d > max_in_window, window_s=window_s, reduce="delta",
        action=action,
        description=f"> {max_in_window:g} recompiles on rank {rank} "
                    f"inside {window_s:g}s ({metric})")


def rank_straggler(rank: int, peers: Sequence[Rule],
                   spread: float = 1.5, window_s: float = 60.0,
                   min_count: int = 4,
                   metric: str = "znicz_anatomy_step_seconds",
                   action: Optional[Callable] = None) -> Rule:
    """ONE rank's windowed step-time median above ``spread``x the
    median of its PEERS' medians (ISSUE 20 straggler watch) — the
    SPMD failure mode no single-rank rule can see: every collective
    runs at the slowest rank's pace, so one degraded worker (thermal
    throttle, a sick host, an unlucky NUMA layout) silently taxes the
    whole fleet while its own absolute numbers still look plausible.

    Relative-to-peers rather than an absolute threshold: the fleet is
    its own baseline, so the rule needs no per-model tuning.  Each
    rank's rule reduces its OWN rank-filtered
    ``znicz_anatomy_step_seconds`` buckets to a windowed p50, then the
    predicate compares against the median of the sibling rules'
    ``last_value`` — ``peers`` is the shared (mutable) list of all the
    fleet's straggler rules, read at evaluation time, so build through
    :func:`add_straggler_rules` rather than by hand.  With fewer than
    two peers reporting there is no baseline and the rule stays quiet.
    """
    name = f"rank_straggler[{rank}]"

    def predicate(own_p50: float) -> bool:
        others = sorted(r.last_value for r in peers
                        if r.name != name and r.last_value is not None)
        if not others:
            return False
        mid = len(others) // 2
        peer_median = others[mid] if len(others) % 2 else \
            0.5 * (others[mid - 1] + others[mid])
        return peer_median > 0.0 and own_p50 > spread * peer_median

    return Rule(
        name, f'{metric}{{rank="{rank}"}}',
        predicate, window_s=window_s, reduce="window_quantile",
        quantile=0.5, min_count=min_count, action=action,
        description=f"rank {rank} windowed step p50 > {spread:g}x the "
                    f"peer-median p50 over {window_s:g}s ({metric})")


def add_straggler_rules(aggregator: "FleetAggregator", *,
                        spread: float = 1.5, window_s: float = 60.0,
                        min_count: int = 4,
                        metric: str = "znicz_anatomy_step_seconds",
                        action: Optional[Callable] = None) -> list:
    """Install one :func:`rank_straggler` per registered source and
    wire their shared peer list — the factory's baseline is the OTHER
    rules' last windowed p50, so the rules must know each other."""
    peers: list = []
    peers.extend(aggregator.add_rule_per_rank(
        lambda rank: rank_straggler(
            rank, peers, spread=spread, window_s=window_s,
            min_count=min_count, metric=metric, action=action)))
    return list(peers)


#: rolling id for requests minted at HTTP admission — combined with the
#: pid so ids stay unique across a worker fleet without coordination
_RID_SEQ = itertools.count(1)


def next_request_id() -> str:
    """Mint one request id (``<pid hex>-<seq hex>``) — the distributed
    tracing correlation key threaded HTTP admission -> batcher ->
    decode phases (serve/server.py)."""
    return f"{os.getpid():x}-{next(_RID_SEQ):x}"


def request_track(rid: str) -> int:
    """Deterministic synthetic trace track (Chrome-trace ``tid``) for
    one request: every phase span of a request shares a row in
    Perfetto instead of overlapping arbitrarily on the worker threads
    that happened to run it."""
    return 0x40000000 | zlib.crc32(rid.encode())
