"""Per-step span tracing into a bounded ring buffer, exportable as
Chrome-trace JSON (loads in ``chrome://tracing`` and Perfetto).

TensorFlow made the step timeline a first-class system feature (Abadi
et al., 2016); this is the native equivalent for the workflow plane:
``span("workflow.step", step=n)`` wraps one control-graph delivery,
``instant("resilience.fault", site=...)`` drops a point event, and
because the resilience plane emits its events into the SAME tracer, a
chaos restart or a NaN-guard trip lands on the same timeline as the
steps around it — post-hoc diagnosis reads one file instead of four
log formats.

Design constraints (pinned by tests/test_observe.py):

- **bounded**: events live in a ``deque(maxlen=capacity)`` ring — a
  10k-step soak holds memory flat and keeps the newest window;
- **cheap**: one ring append per span (events are stored as plain
  tuples, serialization happens only at export); a disabled tracer
  returns a shared no-op span object, so the off cost is one global
  load + one truthiness test;
- **deterministic**: the tracer never touches the PRNG or published
  training state — metric histories are bit-exact with tracing on,
  off, or toggled mid-run.

Export is the Chrome trace-event JSON array format: ``X`` (complete)
events for spans, ``i`` (instant) events for point events, ``M``
metadata rows naming the process and threads.  Timestamps are
microseconds on a per-tracer monotonic origin.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

#: default ring capacity — ~3 MB of tuples at the worst case, a few
#: thousand training steps of window with per-step spans on
DEFAULT_CAPACITY = 65536


class _Span:
    """Reusable-shape active span: records an ``X`` event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        tracer = self._tracer
        tracer._events.append(
            ("X", self._name, (self._t0 - tracer._origin) * 1e6,
             (t1 - self._t0) * 1e6, threading.get_ident(), self._args))


class _NoopSpan:
    """Shared singleton handed out while tracing is disabled."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopSpan()


class Tracer:
    """Bounded ring of trace events; see module docstring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._origin = time.perf_counter()
        # deque appends are atomic under the GIL — spans from the
        # prefetch worker, HTTP threads and the control walk interleave
        # without a lock on the hot path
        self._events: deque = deque(maxlen=self.capacity)

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **args):
        """Context manager timing one region:
        ``with tracer.span("workflow.step", step=n): ...``"""
        if not self.enabled:
            return _NOOP
        return _Span(self, name, args or None)

    def complete(self, name: str, start: float, duration: float,
                 args: Optional[dict] = None, tid: Optional[int] = None,
                 **kw) -> None:
        """Record an already-timed span: ``start`` is a
        ``time.perf_counter()`` stamp, ``duration`` in seconds — the
        workflow run loop times deliveries once and feeds both the
        step-latency histogram and the trace from the same reads.
        ``args`` takes a PRE-BUILT (reusable) dict so the per-signal
        path allocates only the event tuple; kwargs remain for cold
        callers.  ``tid`` overrides the recorded thread id with a
        synthetic track — the serving plane's per-request phase spans
        (queue/prefill/decode/stream) share one
        ``federation.request_track(rid)`` row so concurrent requests'
        overlapping phases render as parallel tracks in Perfetto
        instead of colliding on the worker thread's row."""
        if not self.enabled:
            return
        self._events.append(
            ("X", name, (start - self._origin) * 1e6, duration * 1e6,
             tid if tid is not None else threading.get_ident(),
             kw or args))

    def instant(self, name: str, **args) -> None:
        """Point event (fault fired, recompile, restart, ...)."""
        if not self.enabled:
            return
        self._events.append(
            ("i", name, (time.perf_counter() - self._origin) * 1e6,
             0.0, threading.get_ident(), args or None))
        # observability satellites share one machine-readable stream:
        # rare point events also land as log records, so a JSONL log
        # sink (core/logger.py configure(jsonl_path=...)) interleaves
        # them with ordinary log lines
        from znicz_tpu.core import logger as _logger

        _logger.event_log(name, args)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._events.clear()

    def __len__(self) -> int:
        return len(self._events)

    # -- export --------------------------------------------------------------
    @staticmethod
    def _format_event(event: tuple, pid: int) -> dict:
        ph, name, ts, dur, tid, args = event
        ev = {"ph": ph, "pid": pid, "tid": tid, "name": name,
              "ts": round(ts, 3), "cat": name.split(".", 1)[0]}
        if ph == "X":
            ev["dur"] = round(dur, 3)
        else:
            ev["s"] = "t"              # instant scoped to its thread
        if args:
            ev["args"] = args
        return ev

    def tail(self, n: int) -> list:
        """Newest ``n`` ring events as Chrome-trace dicts (no metadata
        rows) — the flight recorder's span window around a crash."""
        events = list(self._events)    # atomic snapshot of the ring
        pid = os.getpid()
        return [self._format_event(e, pid) for e in events[-n:]]

    def export_dict(self) -> dict:
        """Chrome trace JSON document (``{"traceEvents": [...]}``).
        Carries two fleet-merge anchors on top of the Chrome schema
        (extra top-level keys are ignored by Perfetto): ``rank`` (the
        elastic fleet env, None outside a fleet) and
        ``origin_unix_ts`` — the wall-clock instant of this tracer's
        ``ts == 0``, so ``federation.merge_traces`` can align N
        workers' monotonic clocks onto one timeline."""
        pid = os.getpid()
        events = list(self._events)   # atomic snapshot of the ring
        tids = {}
        out = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": "znicz_tpu"}}]
        for t in threading.enumerate():
            tids[t.ident] = t.name
        for event in events:
            out.append(self._format_event(event, pid))
        for ident, tname in tids.items():
            out.append({"ph": "M", "pid": pid, "tid": ident,
                        "name": "thread_name", "args": {"name": tname}})
        from znicz_tpu.observe.federation import fleet_rank

        origin_unix = time.time() - (time.perf_counter() - self._origin)
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "rank": fleet_rank(),
                "origin_unix_ts": round(origin_unix, 6)}

    def export(self, path: str) -> int:
        """Write the Chrome-trace JSON to ``path``; returns the number
        of trace events written (metadata rows excluded)."""
        doc = self.export_dict()
        n = sum(1 for e in doc["traceEvents"] if e["ph"] != "M")
        with open(path, "w") as f:
            json.dump(doc, f)
        return n


#: THE process-global tracer (mirrors registry.REGISTRY).
TRACER = Tracer()


def span(name: str, **args):
    return TRACER.span(name, **args)


def instant(name: str, **args) -> None:
    TRACER.instant(name, **args)


def export_trace(path: str) -> int:
    return TRACER.export(path)
