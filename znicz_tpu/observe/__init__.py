"""znicz_tpu.observe — the unified telemetry plane (ISSUE 5).

One process-global metrics registry (``registry.REGISTRY``: Counter /
Gauge / Histogram with labels, dict snapshots, Prometheus text
exposition), one bounded-ring span tracer (``trace.TRACER``:
``span()`` / ``instant()`` / Chrome-trace export), and the fixed
instrumentation hooks the runtime calls (``probe``: per-step timing,
recompile detection, staged-bytes accounting, resilience events).

Scrape surfaces: ``WebStatus`` serves ``GET /metrics`` (Prometheus
text) and ``GET /trace.json`` (ring dump); ``python -m znicz_tpu
trace out.json workflow.py`` runs a workflow and exports its timeline;
``bench.py`` attaches ``registry.snapshot_flat()`` to result lines.
Metric name catalogue: docs/OBSERVABILITY.md.
"""

from znicz_tpu.observe.registry import (REGISTRY, Registry, counter,
                                        gauge, histogram)
from znicz_tpu.observe.trace import (TRACER, Tracer, export_trace,
                                     instant, span)
from znicz_tpu.observe.probe import (check_recompiles, enabled,
                                     resilience_event, set_enabled,
                                     staged_bytes, watch_compiles)

__all__ = ["REGISTRY", "Registry", "counter", "gauge", "histogram",
           "TRACER", "Tracer", "span", "instant", "export_trace",
           "set_enabled", "enabled", "watch_compiles",
           "check_recompiles", "staged_bytes", "resilience_event"]
