"""znicz_tpu.observe — the unified telemetry plane (ISSUE 5 + 6).

One process-global metrics registry (``registry.REGISTRY``: Counter /
Gauge / Histogram with labels, dict snapshots, shared quantile
estimation, Prometheus text exposition), one bounded-ring span tracer
(``trace.TRACER``: ``span()`` / ``instant()`` / Chrome-trace export),
the fixed instrumentation hooks the runtime calls (``probe``: per-step
timing, recompile detection, cold-compile timing, staged-bytes
accounting, resilience events), the watchtower (``watchtower.
WATCHTOWER``: retained time-series ring + declarative SLO rules
evaluated by the sampler), the flight recorder (``flight``:
atomic crash post-mortem artifacts), and the fleet federation plane
(``federation``: rank-labeled cross-process metric aggregation,
``/fleet/*`` endpoints, merged distributed traces — ISSUE 11).

Scrape surfaces: ``WebStatus`` serves ``GET /metrics`` (Prometheus
text), ``GET /trace.json`` (ring dump) and ``GET /timeseries.json``
(watchtower delta ring); ``python -m znicz_tpu trace out.json
workflow.py`` exports a run's timeline; ``python -m znicz_tpu flight
artifact.json`` pretty-prints a flight; ``bench.py`` attaches
``registry.snapshot_flat()`` to result lines.  Metric name catalogue:
docs/OBSERVABILITY.md (statically checked by
tools/check_metric_catalogue.py).
"""

from znicz_tpu.observe.registry import (REGISTRY, Registry, counter,
                                        gauge, histogram,
                                        quantile_from_buckets)
from znicz_tpu.observe.trace import (TRACER, Tracer, export_trace,
                                     instant, span)
from znicz_tpu.observe.probe import (check_recompiles,
                                     compile_cache_event,
                                     compile_cache_stats,
                                     compile_observed,
                                     enabled, resilience_event,
                                     set_enabled, staged_bytes,
                                     time_compiles, watch_compiles)
from znicz_tpu.observe.anatomy import StepAnatomy, observe_phase
from znicz_tpu.observe.watchtower import (WATCHTOWER, Rule,
                                          TimeSeriesRing, Watchtower)
from znicz_tpu.observe import flight
from znicz_tpu.observe import federation
from znicz_tpu.observe.federation import (FleetAggregator,
                                          MetricsExporter, merge_traces,
                                          next_request_id,
                                          start_metrics_export)

__all__ = ["REGISTRY", "Registry", "counter", "gauge", "histogram",
           "quantile_from_buckets",
           "TRACER", "Tracer", "span", "instant", "export_trace",
           "set_enabled", "enabled", "watch_compiles",
           "check_recompiles", "staged_bytes", "resilience_event",
           "compile_observed", "time_compiles",
           "compile_cache_event", "compile_cache_stats",
           "StepAnatomy", "observe_phase",
           "WATCHTOWER", "Watchtower", "Rule", "TimeSeriesRing",
           "flight", "federation", "FleetAggregator", "MetricsExporter",
           "merge_traces", "next_request_id", "start_metrics_export"]
