"""Watchtower — retained time series + declarative SLO/health rules
(ISSUE 6 tentpole, parts 1–2).

The registry (``observe/registry.py``) is a point-in-time snapshot and
the tracer ring is only exported on demand, so before this module the
process could not answer "what was happening in the 30 seconds before
this crash / NaN trip / latency spike" without an external scraper.
VELES's master-side status plots (PAPER.md ``web_status`` heritage) and
the production-telemetry pattern in PAPERS.md (EQuARX's bytes-on-wire
wins, Xu et al.'s memory-gauge histories) both presuppose retained
series plus automated judgment over them.  Two pieces:

- :class:`TimeSeriesRing` — samples ``registry.snapshot_flat()`` into a
  bounded ring of **timestamped deltas** (a sample stores only the keys
  whose value changed; evicted deltas fold into a base snapshot, so
  reconstruction is exact while a quiet process costs ~nothing).
  Served as ``GET /timeseries.json`` on :class:`~znicz_tpu.web_status.
  WebStatus`; ``summary()`` (min/mean/max/last, rate for counters)
  rides ``/status.json``.
- :class:`Rule` — a declarative SLO/health predicate over one metric
  (exact flat key, a family summed across labelsets, or a label-filtered
  subset), reduced over a trailing window (``last`` / ``min`` / ``max``
  / ``mean`` / ``delta`` / ``rate`` / ``ratio_to_first``, plus the
  histogram-family ``window_quantile`` / ``quantile_ratio`` reduces
  over in-window bucket-count deltas), required to breach continuously
  for ``for_s`` seconds before tripping.  A trip
  increments ``znicz_watchtower_trips_total{rule=...}``, drops a
  ``watchtower.trip`` instant on the shared trace timeline, offers the
  flight recorder an auto-dump, and invokes the rule's pluggable action
  (log by default; any callback; :func:`supervisor_interrupt` for the
  cooperative hang-abort channel).

:class:`Watchtower` owns both and evaluates every rule on the SAME
thread that samples — a background cadence (``start(interval_s)``)
and/or the workflow run loop (``attach(workflow)`` samples every
``step_every``-th ``workflow.step`` boundary; deterministic by count,
not wall time).  Sampling only READS the registry: metric histories are
bit-exact with the sampler on, off, or attached mid-run, and the
``metrics_overhead`` bench pins the instrumented-vs-bare gap (sampler +
rules included) under 2 %.

Rule catalogue (docs/OBSERVABILITY.md): :func:`step_latency_regression`,
:func:`serve_queue_saturation`, :func:`nan_guard_trip_rate`,
:func:`recompile_storm`, :func:`pipeline_consumer_starvation`.
"""

from __future__ import annotations

import logging
import re
import threading
import time
from collections import deque
from typing import Callable, Optional

from znicz_tpu.observe import probe as _probe
from znicz_tpu.observe import registry as _reg
from znicz_tpu.observe import trace as _trace

#: default ring capacity — at the 5 s default cadence, one hour of
#: history; at per-32-signal step sampling, the newest few epochs
DEFAULT_CAPACITY = 720

#: default sampling stride for workflow-attached towers: one sample per
#: N control-graph signal deliveries (count-based => deterministic; 32
#: keeps the sampler's share of a fast CPU step loop well under the
#: bench's 2 % overhead bound)
DEFAULT_STEP_EVERY = 32

_TRIPS = _reg.counter(
    "znicz_watchtower_trips_total",
    "SLO/health rule trips (rule engine, observe/watchtower.py)",
    labelnames=("rule",))

#: flat-key suffixes treated as monotonic (rate shown in summaries)
_COUNTER_SUFFIXES = ("_total", "_count", "_sum")


def _is_counter_key(key: str) -> bool:
    name = key.split("{", 1)[0]
    return name.endswith(_COUNTER_SUFFIXES)


class TimeSeriesRing:
    """Bounded ring of timestamped ``snapshot_flat()`` deltas."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: Optional[_reg.Registry] = None) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._registry = registry or _reg.REGISTRY
        self._lock = threading.Lock()
        self._base: dict = {}          # values just before the oldest sample
        self._base_ts: Optional[float] = None
        self._samples: deque = deque()  # (ts, {key: new_value})
        self._last: dict = {}          # values as of the newest sample
        self._version = 0              # bumps per sample (summary cache)
        self._summary_cache: tuple = (-1, {})

    def __len__(self) -> int:
        return len(self._samples)

    # -- capture -------------------------------------------------------------
    def sample(self, flat: Optional[dict] = None,
               ts: Optional[float] = None) -> dict:
        """Capture one sample; returns the delta recorded.  ``flat`` and
        ``ts`` are injectable for deterministic tests; production callers
        pass neither.

        Production samples use ``skip_zero=False`` — with the default
        (compact) flavor, a gauge draining back to 0 simply VANISHES
        from the flat dict and its last nonzero value would be carried
        forward forever (a drained serve queue reading saturated in
        every later sample, rule, and flight artifact).  Keys that were
        present and then vanish are recorded as an explicit 0 delta for
        the same reason — belt and braces for injected test flats.

        A NaN value (a DEAD scrape-time gauge provider — the registry
        deliberately returns NaN instead of crashing the scrape) is
        treated as a vanish: NaN != NaN would re-record the key in
        EVERY delta, and a bare ``NaN`` token is invalid JSON for
        strict consumers of ``/timeseries.json`` — the series drops to
        an explicit 0 instead of carrying stale saturation forward."""
        if flat is None:
            flat = self._registry.snapshot_flat(skip_zero=False,
                                                buckets=True)
        if ts is None:
            ts = time.time()
        with self._lock:
            delta = {}
            for k, v in flat.items():
                if v == v and self._last.get(k) != v:
                    delta[k] = v
            for k, last in self._last.items():
                if last != 0.0 and (k not in flat
                                    or flat[k] != flat[k]):
                    delta[k] = 0.0
            self._samples.append((ts, delta))
            self._last.update(delta)
            while len(self._samples) > self.capacity:
                old_ts, old_delta = self._samples.popleft()
                self._base.update(old_delta)
                self._base_ts = old_ts
            self._version += 1
            return delta

    def current(self) -> dict:
        """Values as of the newest sample (one dict copy)."""
        with self._lock:
            return dict(self._last)

    # -- reconstruction ------------------------------------------------------
    def _snapshot_locked(self) -> tuple:
        # base_ts rides in the same locked copy — read unlocked it could
        # belong to a sample still visible in the samples list
        with self._lock:
            return (dict(self._base), self._base_ts,
                    list(self._samples), self._version)

    def series(self, metric: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> list:
        """``[(ts, value)]`` for ``metric`` (flat-key / family / label
        filter semantics of :func:`match_keys`), summed across matching
        keys with values carried forward between deltas.  ``window_s``
        keeps only samples within the trailing window ending at ``now``
        (default: the newest sample's stamp)."""
        base, _, samples, _ = self._snapshot_locked()
        if not samples:
            return []
        if now is None:
            now = samples[-1][0]
        cutoff = None if window_s is None else now - window_s
        cur = dict(base)
        out = []
        for ts, delta in samples:
            cur.update(delta)
            keys = match_keys(metric, cur)
            if not keys:
                continue
            if cutoff is not None and ts < cutoff:
                continue
            out.append((ts, sum(cur[k] for k in keys)))
        return out

    def summary(self) -> dict:
        """Per-key ``{min, mean, max, last}`` over the retained window,
        plus ``rate_per_s`` for counter-shaped keys — the ``/status.json``
        digest.  Per-bucket ``_bucket{le=}`` keys are distribution
        internals (the quantile keys already summarize them) and are
        skipped.  Memoized per ring version: a dashboard polling faster
        than the sampler pays one dict lookup, not a full replay of
        capacity x keys."""
        base, _, samples, version = self._snapshot_locked()
        if not samples:
            return {}
        cached_version, cached = self._summary_cache
        if cached_version == version:
            return cached
        stats: dict = {}
        first_ts = samples[0][0]
        last_ts = samples[-1][0]
        cur = dict(base)
        for ts, delta in samples:
            cur.update(delta)
            for key, value in cur.items():
                if "_bucket{" in key:
                    continue
                s = stats.get(key)
                if s is None:
                    stats[key] = [value, value, value, 1, value, value]
                else:                  # [min, max, sum, n, first, last]
                    if value < s[0]:
                        s[0] = value
                    if value > s[1]:
                        s[1] = value
                    s[2] += value
                    s[3] += 1
                    s[5] = value
        out = {}
        span = last_ts - first_ts
        for key, (mn, mx, total, n, first, last) in sorted(stats.items()):
            row = {"min": round(mn, 6), "mean": round(total / n, 6),
                   "max": round(mx, 6), "last": round(last, 6)}
            if _is_counter_key(key) and span > 0:
                row["rate_per_s"] = round((last - first) / span, 6)
            out[key] = row
        with self._lock:
            self._summary_cache = (version, out)
        return out

    def to_dict(self, last_n: Optional[int] = None) -> dict:
        """The ``GET /timeseries.json`` wire shape: the delta ring plus
        the fold-in base — a consumer replays ``base`` then ``samples``
        in order to reconstruct every series exactly.  ``last_n`` keeps
        only the newest N samples, folding the over-limit head into the
        base with the SAME invariant eviction uses (the flight recorder
        bounds its artifacts this way)."""
        base, base_ts, samples, _ = self._snapshot_locked()
        if last_n is not None and len(samples) > last_n:
            for ts, delta in samples[:-last_n]:
                base.update(delta)
                base_ts = ts
            samples = samples[-last_n:]
        return {"capacity": self.capacity,
                "base_ts": base_ts,
                "base": base,
                "samples": [{"ts": ts, "delta": delta}
                            for ts, delta in samples]}


def match_keys(metric: str, flat: dict) -> list:
    """Flat keys in ``flat`` selected by ``metric``:

    - ``"name"`` — the exact label-less key, or every labelset of the
      family (summed by callers);
    - ``'name{kind="nan_guard"}'`` — label filter: every key of the
      family whose label string carries ALL the given pairs.
    """
    if "{" in metric:
        name, _, rest = metric.partition("{")
        pairs = [p for p in rest.rstrip("}").split(",") if p]
        prefix = name + "{"
        return [k for k in flat if k.startswith(prefix)
                and all(p in k for p in pairs)]
    return [k for k in flat
            if k == metric or k.startswith(metric + "{")]


_LE_RE = re.compile(r'le="([^"]+)"')


def _bucket_layout(metric: str, flat: dict) -> Optional[tuple]:
    """``(edges, key_groups)`` for histogram family ``metric`` in
    ``flat``: ``key_groups`` is one tuple of flat keys per ``le``
    threshold (ascending, ``+Inf`` last when present), each group the
    matching labelsets to sum.  The layout depends only on WHICH keys
    exist — the sampler caches it and re-evaluates just the values."""
    if "{" in metric:
        name, _, rest = metric.partition("{")
        pairs = [p for p in rest.rstrip("}").split(",") if p]
    else:
        name, pairs = metric, []
    prefix = name + "_bucket{"
    groups: dict = {}
    for k in flat:
        if not k.startswith(prefix) or not all(p in k for p in pairs):
            continue
        m = _LE_RE.search(k)
        if m is None:
            continue
        le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
        groups.setdefault(le, []).append(k)
    if not groups:
        return None
    les = sorted(groups)
    edges = tuple(le for le in les if le != float("inf"))
    return edges, tuple(tuple(groups[le]) for le in les)


def _bucket_eval(layout: tuple, flat: dict) -> tuple:
    """Evaluate a :func:`_bucket_layout` against current values:
    ``(edges, per_bucket_counts)`` shaped for
    :func:`~znicz_tpu.observe.registry.quantile_from_buckets` — finite
    edges, per-bucket (non-cumulative) counts with overflow last."""
    edges, key_groups = layout
    cumulative = [sum(map(flat.__getitem__, keys))
                  for keys in key_groups]
    counts = [cumulative[0]] + [cumulative[i] - cumulative[i - 1]
                                for i in range(1, len(cumulative))]
    if len(edges) == len(key_groups):  # no +Inf labelset: empty overflow
        counts.append(0.0)
    return edges, tuple(counts)


def bucket_counts(metric: str, flat: dict) -> Optional[tuple]:
    """``(edges, per_bucket_counts)`` for histogram family ``metric``
    from a flat snapshot carrying cumulative ``_bucket{le=...}`` keys
    (``snapshot_flat(buckets=True)``), summed across matching labelsets
    (same label-filter semantics as :func:`match_keys`); None when the
    snapshot has no such keys."""
    layout = _bucket_layout(metric, flat)
    if layout is None:
        return None
    return _bucket_eval(layout, flat)


class Rule:
    """One declarative SLO/health rule; see module docstring.

    ``predicate(value) -> bool`` judges the reduced window value;
    ``for_s`` requires the breach to hold continuously that long before
    the trip fires; after firing, the rule re-arms only once the
    predicate goes false (no trip storms).  ``action(rule, value)`` is
    invoked on each trip (exceptions are swallowed — a broken action
    must not kill the sampler or the run loop).

    With ``quantile=q`` the rule watches a HISTOGRAM family: each sample
    stores the family's bucket-count vector (from the flat snapshot's
    ``_bucket{le=}`` keys) and the reduce runs over bucket-count DELTAS
    inside the window — ``window_quantile`` is the q-quantile of only
    the window's observations, ``quantile_ratio`` divides the newer
    half's q-quantile by the older half's (a trailing-baseline
    regression detector).  The lifetime ``_p95`` estimate in the flat
    snapshot cannot do either: cumulative buckets damp a mid-run
    regression in proportion to process age.  Each judged delta must
    hold >= ``min_count`` observations — volatile warm-up windows
    return None (no trip) instead of a noise verdict.

    The window is bounded by ``max_window`` entries as well as by
    ``window_s`` seconds: a step-attached tower on a fast CPU loop can
    sample hundreds of times per second, and an unbounded 60 s window
    would make every per-sample reduce scan thousands of entries — the
    oldest entries age out first, so the reduce still spans (up to)
    the full window duration at coarser granularity.
    """

    #: reduces over bucket-count deltas (require quantile=...)
    _QUANTILE = ("window_quantile", "quantile_ratio")
    #: reduces needing >= 2 samples / a real window
    _WINDOWED = ("delta", "rate", "ratio_to_first") + _QUANTILE
    REDUCES = ("last", "min", "max", "mean") + _WINDOWED

    def __init__(self, name: str, metric: str,
                 predicate: Callable[[float], bool], *,
                 window_s: float = 0.0, for_s: float = 0.0,
                 reduce: str = "last",
                 quantile: Optional[float] = None, min_count: int = 1,
                 max_window: int = 512,
                 action: Optional[Callable] = None,
                 description: str = "") -> None:
        if reduce not in self.REDUCES:
            raise ValueError(f"unknown reduce {reduce!r}; known: "
                             f"{self.REDUCES}")
        if reduce in self._WINDOWED and window_s <= 0.0:
            raise ValueError(f"reduce={reduce!r} needs window_s > 0")
        if (quantile is not None) != (reduce in self._QUANTILE):
            raise ValueError(f"reduce={reduce!r} and quantile="
                             f"{quantile!r} go together: bucket-delta "
                             f"reduces {self._QUANTILE} need a quantile "
                             f"and scalar reduces reject one")
        if quantile is not None and not 0.0 < quantile < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got "
                             f"{quantile}")
        self.name = name
        self.metric = metric
        self.predicate = predicate
        self.window_s = float(window_s)
        self.for_s = float(for_s)
        self.reduce = reduce
        self.quantile = quantile
        self.min_count = int(min_count)
        self.action = action
        self.description = description
        if max_window < 2:
            raise ValueError(f"max_window must be >= 2, got {max_window}")
        self.trips = 0
        #: set by the evaluating tower once the metric selector has
        #: matched at least one flat key — False in /status.json means
        #: the rule has NEVER been evaluated (metric not yet emitted,
        #: or a typo'd/mis-shaped selector: a histogram family with a
        #: scalar reduce only exists as _count/_sum/_p95/_bucket keys)
        self.matching = False
        self.last_value: Optional[float] = None
        self.last_trip_ts: Optional[float] = None
        #: (ts, raw metric value); maxlen ages out the oldest entries
        #: when the sampler cadence outruns window_s
        self._window: deque = deque(maxlen=int(max_window))
        self._breach_since: Optional[float] = None
        self._tripped = False

    # -- evaluation (called by the owning Watchtower's sampler) --------------
    def _quantile_reduced(self) -> Optional[float]:
        """Quantile over bucket-count deltas inside the window; the
        window stores ``(ts, (edges, counts))`` entries.  Entries whose
        edges differ from the newest (a re-declared histogram) are
        dropped rather than mis-subtracted."""
        edges = self._window[-1][1][0]
        entries = [e for e in self._window if e[1][0] == edges]
        if len(entries) < 2:
            return None

        def q_of(older, newer) -> Optional[float]:
            d = [b - a for a, b in zip(older[1][1], newer[1][1])]
            if sum(d) < self.min_count:
                return None
            return _reg.quantile_from_buckets(edges, d, self.quantile)

        if self.reduce == "window_quantile":
            return q_of(entries[0], entries[-1])
        mid = len(entries) // 2            # quantile_ratio
        older = q_of(entries[0], entries[mid])
        newer = q_of(entries[mid], entries[-1])
        if older is None or newer is None or older <= 0.0:
            return None
        return newer / older

    def _reduced(self) -> Optional[float]:
        if self.quantile is not None:
            return self._quantile_reduced()
        vals = [v for _, v in self._window]
        if not vals:
            return None
        if self.reduce == "last":
            return vals[-1]
        if self.reduce == "min":
            return min(vals)
        if self.reduce == "max":
            return max(vals)
        if self.reduce == "mean":
            return sum(vals) / len(vals)
        if len(vals) < 2:
            return None                    # windowed reduces need history
        first_ts, first = self._window[0]
        last_ts, last = self._window[-1]
        if self.reduce == "delta":
            return last - first
        if self.reduce == "rate":
            span = last_ts - first_ts
            return (last - first) / span if span > 0 else None
        return last / first if first > 0 else None   # ratio_to_first

    def observe(self, ts: float, value: float) -> Optional[float]:
        """Feed one sampled raw value; returns the reduced value when
        this observation TRIPS the rule, None otherwise."""
        self._window.append((ts, value))
        if self.window_s > 0.0:
            # evict past the window but keep ONE at-or-before-cutoff
            # anchor — delta/rate/ratio_to_first measure against the
            # window's trailing edge, not an arbitrary survivor
            cutoff = ts - self.window_s
            while len(self._window) > 1 and self._window[1][0] <= cutoff:
                self._window.popleft()
        else:
            while len(self._window) > 1:
                self._window.popleft()
        reduced = self._reduced()
        if reduced is None:
            return None
        self.last_value = reduced
        if not self.predicate(reduced):
            self._breach_since = None
            self._tripped = False          # re-arm after recovery
            return None
        if self._breach_since is None:
            self._breach_since = ts
        if ts - self._breach_since < self.for_s:
            return None
        if self._tripped:
            return None
        self._tripped = True
        self.trips += 1
        self.last_trip_ts = ts
        return reduced

    def snapshot(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "reduce": self.reduce, "quantile": self.quantile,
                "window_s": self.window_s,
                "for_s": self.for_s, "trips": self.trips,
                "matching": self.matching,
                "breaching": self._breach_since is not None,
                "last_value": self.last_value,
                "last_trip_ts": self.last_trip_ts,
                "description": self.description}


# -- trip actions ------------------------------------------------------------

def log_action(rule: Rule, value: float) -> None:
    """Default action: one WARNING on the watchtower logger."""
    logging.getLogger("znicz_tpu.watchtower").warning(
        "SLO rule %s tripped: %s %s = %.6g", rule.name, rule.metric,
        rule.reduce, value)


def supervisor_interrupt(rule: Rule, value: float) -> None:
    """Cooperative supervisor interrupt: abort injected hangs through
    the same channel the watchdog uses (``faults.interrupt_hangs``) —
    under ``run_supervised`` a rule tripping on a wedged metric unparks
    the hang so the attempt fails fast and restarts.  Real (non-
    injected) hangs still need the watchdog's ``step_timeout``."""
    from znicz_tpu.resilience import faults

    log_action(rule, value)
    faults.interrupt_hangs()


class Watchtower:
    """Sampler + rule engine over one :class:`TimeSeriesRing`."""

    THREAD_NAME = "znicz-watchtower"

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: Optional[_reg.Registry] = None,
                 step_every: int = DEFAULT_STEP_EVERY) -> None:
        if step_every < 1:
            raise ValueError(f"step_every must be >= 1, got {step_every}")
        self.ring = TimeSeriesRing(capacity, registry)
        self.rules: list[Rule] = []
        #: per-rule key-selection memo: rule index -> (n_keys,
        #: selection) — the rules list is append-only, so the index is
        #: a stable identity (id() could be reused after a GC).
        #: Flat-snapshot keys only ever ACCUMULATE (registry children
        #: are append-only and the ring's carried-forward dict never
        #: drops a key), so the key COUNT is a sound cache version —
        #: rescanning the whole dict per rule per sample was the
        #: sampler's dominant cost
        self._match_cache: dict = {}
        self.step_every = int(step_every)
        self._step_count = 0
        self._eval_lock = threading.Lock()
        self._stop_evt: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- rules ---------------------------------------------------------------
    def add_rule(self, rule: Rule) -> Rule:
        with self._eval_lock:
            self.rules.append(rule)
        return rule

    def _fire(self, rule: Rule, value: float) -> None:
        _TRIPS.labels(rule=rule.name).inc()
        _trace.instant("watchtower.trip", rule=rule.name,
                       metric=rule.metric, value=float(value))
        from znicz_tpu.observe import flight as _flight

        _flight.auto_dump("rule", rule=rule.name, metric=rule.metric,
                          value=float(value))
        action = rule.action or log_action
        try:
            action(rule, value)
        except Exception:  # noqa: BLE001 — a broken action must not
            logging.getLogger("znicz_tpu.watchtower").exception(
                "rule %s action failed", rule.name)   # kill the sampler

    # -- sampling ------------------------------------------------------------
    def observe_now(self, ts: Optional[float] = None) -> Optional[float]:
        """Take one sample and evaluate every rule against it (the
        sampler thread, the step hook and tests all funnel through
        here).  No-op while the observe plane is disabled — the bare
        walk stays bare.  Returns the sample timestamp, or None when
        disabled."""
        if not _probe.enabled():
            return None
        if ts is None:
            ts = time.time()
        # same flavor the ring's no-arg sample() would take: skip_zero
        # off so drained gauges record their 0, buckets on so quantile
        # rules can reduce over bucket-count deltas
        flat = self.ring._registry.snapshot_flat(skip_zero=False,
                                                 buckets=True)
        fired = []
        with self._eval_lock:
            self.ring.sample(flat=flat, ts=ts)
            # _eval_lock serializes every sampler, and only sample()
            # mutates _last — reading it uncopied here is safe and
            # skips a per-sample dict copy on the step hot path
            cur = self.ring._last
            n = len(cur)
            for i, rule in enumerate(self.rules):
                cached = self._match_cache.get(i)
                if cached is None or cached[0] != n:
                    sel = (_bucket_layout(rule.metric, cur)
                           if rule.quantile is not None
                           else match_keys(rule.metric, cur))
                    cached = (n, sel)
                    self._match_cache[i] = cached
                sel = cached[1]
                if not sel:
                    continue
                rule.matching = True
                if rule.quantile is not None:
                    # histogram-family rule: feed the bucket-count
                    # vector; the reduce runs over in-window deltas
                    value = _bucket_eval(sel, cur)
                else:
                    value = sum(map(cur.__getitem__, sel))
                tripped = rule.observe(ts, value)
                if tripped is not None:
                    fired.append((rule, tripped))
        # fire OUTSIDE the eval lock: an action (or the flight
        # recorder's auto-dump) may itself need to sample the ring —
        # under the lock that would deadlock (threading.Lock is not
        # reentrant), and `cur` must not be mutated mid-rule-loop
        for rule, value in fired:
            self._fire(rule, value)
        return ts

    def flight_sample(self) -> None:
        """One registry sample for a flight dump — bypasses the observe
        master switch (a post-mortem wants the numbers regardless) and
        takes the eval lock so it cannot race a concurrent
        :meth:`observe_now`'s rule evaluation over the ring's
        carried-forward dict."""
        with self._eval_lock:
            self.ring.sample()

    def on_step(self) -> None:
        """Workflow run-loop hook: sample every ``step_every``-th signal
        delivery — count-based, so chaos tests reproduce exactly."""
        self._step_count += 1
        if self._step_count % self.step_every:
            return
        self.observe_now()

    # -- workflow attachment -------------------------------------------------
    def attach(self, workflow) -> "Watchtower":
        """Register with ``workflow`` so the run loop calls
        :meth:`on_step` at every ``workflow.step`` boundary."""
        if self not in workflow.watchtowers:
            workflow.watchtowers.append(self)
        return self

    def detach(self, workflow) -> None:
        if self in workflow.watchtowers:
            workflow.watchtowers.remove(self)

    # -- background cadence --------------------------------------------------
    def start(self, interval_s: float = 5.0) -> None:
        """Sample + evaluate on a daemon thread every ``interval_s``
        seconds until :meth:`stop`."""
        if self._thread is not None:
            raise RuntimeError("watchtower sampler already started")
        self._stop_evt = threading.Event()
        stop = self._stop_evt

        def loop() -> None:
            log = logging.getLogger("znicz_tpu.watchtower")
            while not stop.wait(interval_s):
                try:
                    self.observe_now()
                except Exception:  # noqa: BLE001 — a dead provider (or
                    # a raising predicate) must not kill the cadence,
                    # but silently-dead sampling is worse than noise
                    log.exception("watchtower sample failed")
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=self.THREAD_NAME)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self._stop_evt = None

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``/status.json`` block: sample count, rule states, and
        the per-key min/mean/max/last (+rate) digest."""
        return {"samples": len(self.ring),
                "step_every": self.step_every,
                "rules": [r.snapshot() for r in self.rules],
                "summary": self.ring.summary()}

    def timeseries_dict(self) -> dict:
        """The ``GET /timeseries.json`` payload."""
        doc = self.ring.to_dict()
        doc["rules"] = [r.snapshot() for r in self.rules]
        return doc


# -- rule catalogue (docs/OBSERVABILITY.md) ----------------------------------

def step_latency_regression(factor: float = 2.0, window_s: float = 60.0,
                            for_s: float = 0.0, min_count: int = 8,
                            action: Optional[Callable] = None) -> Rule:
    """Step-latency p95 regressed vs the trailing baseline: the p95 of
    the window's newer half of ``znicz_workflow_step_seconds``
    observations (bucket-count deltas) grew more than ``factor``x over
    the older half's.  Windowed on purpose — the lifetime ``_p95``
    estimate damps a mid-run regression in proportion to process age."""
    return Rule(
        "step_latency_regression", "znicz_workflow_step_seconds",
        lambda r: r > factor, window_s=window_s, for_s=for_s,
        reduce="quantile_ratio", quantile=0.95, min_count=min_count,
        action=action,
        description=f"windowed step p95 > {factor}x the trailing "
                    f"baseline half-window")


def serve_queue_saturation(depth: float = 64.0, for_s: float = 5.0,
                           action: Optional[Callable] = None) -> Rule:
    """Serving admission queue pinned above ``depth`` chunks — the
    batcher is saturated and deadlines are about to shed load."""
    return Rule(
        "serve_queue_saturation", "znicz_serve_queue_depth",
        lambda v: v > depth, for_s=for_s, action=action,
        description=f"serve queue depth > {depth:g} for {for_s:g}s")


def nan_guard_trip_rate(max_per_s: float = 0.1, window_s: float = 60.0,
                        action: Optional[Callable] = None) -> Rule:
    """NaN-guard trips arriving faster than ``max_per_s`` — training is
    diverging faster than skip-batch can hide."""
    return Rule(
        "nan_guard_trip_rate",
        'znicz_resilience_events_total{kind="nan_guard"}',
        lambda r: r > max_per_s, window_s=window_s, reduce="rate",
        action=action,
        description=f"nan_guard trips > {max_per_s:g}/s over "
                    f"{window_s:g}s")


def recompile_storm(max_in_window: float = 3.0, window_s: float = 60.0,
                    action: Optional[Callable] = None,
                    metric: str = "znicz_recompiles_total") -> Rule:
    """Watched programs recompiling repeatedly after warmup — a shape
    leak (the serve engine's zero-steady-state-recompile property is
    being violated somewhere).  ``metric`` widens the net (ISSUE 7):
    pointed at ``znicz_compile_cache_misses_total`` the rule counts
    EVERY cold XLA compile the persistent cache observed — programs
    nobody registered with ``watch_compiles`` included — so a serve
    fleet alarms on compile storms a warm cache should have absorbed."""
    # a non-default metric gets its own rule name, so a tower carrying
    # both variants keeps their trips apart in znicz_watchtower_trips_
    # total{rule=...} and flight-dump tags
    name = ("recompile_storm" if metric == "znicz_recompiles_total"
            else f"recompile_storm[{metric}]")
    return Rule(
        name, metric,
        lambda d: d > max_in_window, window_s=window_s, reduce="delta",
        action=action,
        description=f"> {max_in_window:g} recompiles inside "
                    f"{window_s:g}s ({metric})")


def pipeline_consumer_starvation(ratio: float = 0.5,
                                 window_s: float = 30.0,
                                 action: Optional[Callable] = None) -> Rule:
    """Consumers starving on the prefetch queue more than ``ratio`` of
    wall time — the input pipeline (not compute) bounds throughput."""
    return Rule(
        "pipeline_consumer_starvation",
        "znicz_pipeline_consumer_starved_seconds_total",
        lambda r: r > ratio, window_s=window_s, reduce="rate",
        action=action,
        description=f"consumer starved > {ratio:g} s/s over "
                    f"{window_s:g}s")


#: THE process-global watchtower (mirrors registry.REGISTRY and
#: trace.TRACER): WebStatus serves its ring at /timeseries.json and its
#: summary inside /status.json; the flight recorder snapshots it.
WATCHTOWER = Watchtower()
