"""Automatic instrumentation hooks wiring the runtime into the registry
and tracer (ISSUE 5 tentpole, part 3).

The production code calls these at fixed sites, mirroring the
resilience plane's ``fault_hook`` discipline:

====================  =====================================================
hook                  call site
====================  =====================================================
``unit_observers``    ``core/units.py :: Unit._timed_run`` — donates
                      per-unit run counts/seconds into
                      ``znicz_unit_runs_total`` / ``znicz_unit_run_
                      seconds_total`` (labels: workflow, unit); the
                      registry children ARE what ``timing_table()`` reads
``step_histogram``    ``core/workflow.py`` run loop — per signal-delivery
                      wall time into ``znicz_workflow_step_seconds``
``watch_compiles`` /  ``parallel/step.py`` registers its jitted
``check_recompiles``  functions; the workflow loop polls their
                      ``_cache_size()`` sum — a positive delta increments
                      ``znicz_recompiles_total{fn}`` and drops a
                      ``compile.recompile`` instant on the trace timeline
``staged_bytes``      ``pipeline/prefetcher.py`` worker — H2D staging
                      volume (counter) + per-pipeline live gauges
``resilience_event``  ``resilience/{faults,retry,supervisor,health}.py``
                      — every fault firing / retry / restart / NaN-guard
                      action lands as a counter increment AND an instant
                      event, so failures correlate with steps on one
                      timeline
``compile_cache_      ``compilecache.py``'s jax monitoring listener —
event``               every persistent compilation-cache consultation
                      lands in ``znicz_compile_cache_{hits,misses}_
                      total`` so warm-vs-cold boot is a counter delta
====================  =====================================================

All hooks early-out on ``observe.set_enabled(False)`` (one module-global
load), which is how the ``metrics_overhead`` bench measures the bare
path and how determinism tests pin "instrumentation off == seed path".
"""

from __future__ import annotations

import time
import weakref
from typing import Optional

from znicz_tpu.observe import registry as _reg
from znicz_tpu.observe import trace as _trace

# -- enable/disable (module-global; also flips the tracer) -------------------

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool) -> None:
    """Master switch for every automatic probe AND the global tracer —
    registry families stay registered (their values simply stop moving),
    so a scrape during a disabled window still parses."""
    global _enabled
    _enabled = bool(flag)
    if flag:
        _trace.TRACER.enable()
    else:
        _trace.TRACER.disable()


# -- workflow plane ----------------------------------------------------------

_UNIT_RUNS = _reg.counter(
    "znicz_unit_runs_total", "control-graph unit firings",
    labelnames=("workflow", "unit"))
_UNIT_SECONDS = _reg.counter(
    "znicz_unit_run_seconds_total", "wall seconds inside unit.run()",
    labelnames=("workflow", "unit"))
_STEP_SECONDS = _reg.histogram(
    "znicz_workflow_step_seconds",
    "wall time of one control-graph signal delivery")
_SIGNALS = _reg.counter(
    "znicz_workflow_signals_total", "control-graph signals dispatched")
_WORKFLOW_RUNS = _reg.counter(
    "znicz_workflow_runs_total", "Workflow.run invocations",
    labelnames=("workflow",))


def unit_observers(workflow_name: str, unit_name: str):
    """(runs_counter, seconds_counter) children for one unit — cached by
    the unit itself so the hot path is one :func:`unit_run` call."""
    return (_UNIT_RUNS.labels(workflow=workflow_name, unit=unit_name),
            _UNIT_SECONDS.labels(workflow=workflow_name, unit=unit_name))


def unit_run(obs, dt_s: float) -> None:
    """Donate one unit firing: both children share the registry lock, so
    taking it ONCE for the pair halves the hot-path lock traffic (the
    metrics_overhead budget is per-microsecond at signal granularity)."""
    runs, secs = obs
    with runs._lock:
        runs.value += 1.0
        secs.value += dt_s


def unit_timing_rows(workflow_name: str, unit_names) -> list:
    """``timing_table()``'s data source: ``(seconds, runs, unit)`` rows
    from the registry for one workflow's units.  Counters are
    process-lifetime (Prometheus semantics), so a supervised restart's
    table shows the CUMULATIVE cost across attempts — by design: that is
    the number a restart storm inflates.  Units sharing a name merge."""
    rows = []
    for name in dict.fromkeys(unit_names):          # dedupe, keep order
        runs = _UNIT_RUNS.labels(workflow=workflow_name, unit=name).get()
        secs = _UNIT_SECONDS.labels(workflow=workflow_name,
                                    unit=name).get()
        rows.append((secs, int(runs), name))
    return rows


def step_histogram():
    return _STEP_SECONDS


def signal_dispatched(dt_s: float) -> None:
    """One control-graph delivery took ``dt_s`` wall seconds.  Only the
    histogram moves per signal; ``znicz_workflow_signals_total`` is
    batch-incremented per run (:func:`signals_add`) — one fewer lock
    round-trip on the per-signal path."""
    _STEP_SECONDS.observe(dt_s)


def signals_add(n: int) -> None:
    """Batch-donate ``n`` dispatched signals (called once per
    Workflow.run with the walk's delta)."""
    if n:
        _SIGNALS.inc(n)


def workflow_run(workflow_name: str) -> None:
    _WORKFLOW_RUNS.labels(workflow=workflow_name).inc()


# -- recompile detection -----------------------------------------------------

_RECOMPILES = _reg.counter(
    "znicz_recompiles_total",
    "XLA compile-cache growth observed on watched jitted functions",
    labelnames=("fn",))

#: key -> [tuple of weakrefs to jitted fns, last observed cache-size
#: sum, metric label].  Weak refs: a watched step that dies (dropped
#: workflow, supervised-restart rebuild) stops being polled and its
#: entry is reaped on the next poll, so two live steps never fight over
#: one key and dead ones never pin their compiled programs in memory.
_watched: dict[str, list] = {}


def watch_compiles(key: str, *fns, label: Optional[str] = None) -> None:
    """Register jitted function(s) for compile-cache delta polling.
    ``key`` must be unique per watched OBJECT (two live FusedTrainSteps
    in one process each keep their own watch); ``label`` is the
    ``znicz_recompiles_total{fn=...}`` label and defaults to ``key`` —
    instances of one class share a label while keeping separate
    baselines.  Functions without ``_cache_size`` (older jax, non-jit
    callables) are ignored.  A warm function registers its current
    cache size as the baseline, so only growth counts."""
    refs = tuple(weakref.ref(f) for f in fns
                 if hasattr(f, "_cache_size"))
    if not refs:
        return
    _watched[key] = [refs, _cache_total(refs), label or key]


def unwatch_compiles(key: str) -> None:
    _watched.pop(key, None)


def _cache_total(refs) -> Optional[int]:
    """Cache-size sum over the still-living functions; None when every
    ref is dead (the entry should be reaped)."""
    total, alive = 0, False
    for ref in refs:
        fn = ref()
        if fn is None:
            continue
        alive = True
        try:
            total += int(fn._cache_size())
        except Exception:  # noqa: BLE001 — a torn-down backend must not
            pass           # crash the run loop polling it
    return total if alive else None


def check_recompiles() -> int:
    """Poll watched functions; returns newly observed compiles.  The
    FIRST compile of a fresh function counts too — a steady-state loop
    asserts the counter moves exactly once per function, and the pinned
    zero-recompile tests keep holding because they compare cache sizes
    directly."""
    if not _watched or not _enabled:
        return 0
    new = 0
    for key, entry in list(_watched.items()):
        total = _cache_total(entry[0])
        if total is None:                 # every watched fn died
            _watched.pop(key, None)
            continue
        delta = total - entry[1]
        if delta > 0:
            entry[1] = total
            new += delta
            _RECOMPILES.labels(fn=entry[2]).inc(delta)
            _trace.instant("compile.recompile", fn=entry[2], new=delta,
                           cache_size=total)
        elif delta < 0:
            # a subset of the fns died (or a cache was cleared): rebase
            # so the shrink is not later mistaken for absence of growth
            entry[1] = total
    return new


# -- cold-compile timing -----------------------------------------------------

_COMPILE_SECONDS = _reg.histogram(
    "znicz_compile_seconds",
    "cold-path XLA compile wall time: first call of a wrapped jitted "
    "program, or a serve-engine bucket materializing",
    labelnames=("fn",))


def compile_observed(label: str, dt_s: float, **args) -> None:
    """One cold compile (+ first execution) took ``dt_s`` wall seconds:
    histogram observation plus a ``compile.cold`` complete-span on the
    trace timeline, so the ROADMAP compile-latency work lands with its
    baseline already recorded."""
    if not _enabled:
        return
    _COMPILE_SECONDS.labels(fn=label).observe(dt_s)
    _trace.TRACER.complete("compile.cold", time.perf_counter() - dt_s,
                           dt_s, fn=label, **args)


class _CompileTimed:
    """Thin wrapper over a jitted callable: the FIRST invocation — the
    trace+compile+run cold path — is timed into ``znicz_compile_seconds
    {fn=label}``; every later call is one attribute check of passthrough.
    ``_cache_size`` delegates so :func:`watch_compiles` keeps polling the
    real compile cache through the wrapper."""

    __slots__ = ("_fn", "_label", "_cold", "__weakref__")

    def __init__(self, fn, label: str) -> None:
        self._fn = fn
        self._label = label
        self._cold = True

    def _cache_size(self) -> int:
        size = getattr(self._fn, "_cache_size", None)
        return int(size()) if size is not None else 0

    def __call__(self, *args, **kw):
        if not self._cold:
            return self._fn(*args, **kw)
        self._cold = False
        t0 = time.perf_counter()
        out = self._fn(*args, **kw)
        compile_observed(self._label, time.perf_counter() - t0)
        return out


def time_compiles(label: str, fn):
    """Wrap ``fn`` (a jitted program) so its first call lands in the
    compile-time histogram; ``None`` passes through for optional
    programs."""
    if fn is None:
        return None
    return _CompileTimed(fn, label)


# -- persistent compilation cache (ISSUE 7) ----------------------------------

_CACHE_HITS = _reg.counter(
    "znicz_compile_cache_hits_total",
    "persistent XLA compilation-cache hits (an executable was loaded "
    "from disk instead of compiled)")
_CACHE_MISSES = _reg.counter(
    "znicz_compile_cache_misses_total",
    "persistent compilation-cache misses — cold compiles; feeds "
    "watchtower.recompile_storm when pointed at this family")


def compile_cache_event(kind: str) -> None:
    """One cache consultation, fed by ``compilecache``'s jax monitoring
    listener.  ``kind``: ``hit`` | ``miss``.  Counted even while probes
    are disabled: the warm-vs-cold contract (tests, the
    ``compile_latency`` bench, t1's zero-JIT smoke) must stay assertable
    through an ``observe.set_enabled(False)`` window, and a compile is
    not on any per-signal hot path."""
    (_CACHE_HITS if kind == "hit" else _CACHE_MISSES).inc()


def compile_cache_stats() -> tuple:
    """Lifetime ``(hits, misses)`` — scenario lines and the serve
    warmup summary report deltas of these."""
    return int(_CACHE_HITS.get()), int(_CACHE_MISSES.get())


# -- ZeRO sharding plane (ISSUE 15) ------------------------------------------

_ZERO_PARAM_BYTES = _reg.gauge(
    "znicz_zero_param_bytes",
    "per-chip bytes of persistent model parameters held by a fused "
    "train step (full when replicated; 1/n flat shards + padding under "
    "shard_params)", labelnames=("unit",))
_ZERO_OPT_BYTES = _reg.gauge(
    "znicz_zero_opt_state_bytes",
    "per-chip bytes of persistent optimizer/EMA state held by a fused "
    "train step (1/n flat shards under shard_update/shard_params)",
    labelnames=("unit",))
_ZERO_GATHERED = _reg.counter(
    "znicz_zero_gathered_bytes_total",
    "bytes all-gathered on demand to materialize full weights for a "
    "forward/backward dispatch under shard_params",
    labelnames=("unit",))


def zero_memory(unit: str, param_bytes: int, opt_bytes: int) -> None:
    """Per-chip persistent-state accounting, set once per step build.
    Recorded even while probes are disabled (the compile_cache_event
    precedent): the memory contract must stay assertable through a
    bench's bare arm, and a step build is never on the per-signal hot
    path."""
    _ZERO_PARAM_BYTES.labels(unit=unit).set(float(param_bytes))
    _ZERO_OPT_BYTES.labels(unit=unit).set(float(opt_bytes))


def zero_gather_counter(unit: str):
    """Cached child handle for the per-dispatch gathered-bytes counter
    (the step increments it on its hot path — one ``inc`` per dispatch,
    gated on :func:`enabled` by the caller)."""
    return _ZERO_GATHERED.labels(unit=unit)


# -- quantized collectives (ISSUE 18) ----------------------------------------

#: ``collective`` label values: "grad_psum" (the explicit gradient
#: reduction) and "zero_gather" (the shard_params regather chain)
_QCOMM_WIRE = _reg.counter(
    "znicz_qcomm_bytes_on_wire_total",
    "bytes actually shipped by quantized collectives (int8/bf16 payload "
    "+ per-chunk scales), per unit and collective site",
    labelnames=("unit", "collective"))
_QCOMM_EXACT = _reg.counter(
    "znicz_qcomm_bytes_exact_total",
    "bytes the SAME collectives would have shipped unquantized (f32) — "
    "the before to znicz_qcomm_bytes_on_wire_total's after",
    labelnames=("unit", "collective"))
_QCOMM_RATIO = _reg.gauge(
    "znicz_qcomm_compression_ratio",
    "exact/wire byte ratio of a quantized collective (~4 for int8 with "
    "the default chunk, 2 for bf16); set once per step build",
    labelnames=("unit", "collective"))
_QCOMM_RESIDUAL = _reg.gauge(
    "znicz_qcomm_residual_norm",
    "L2 norm of the error-feedback residual tree carried by a fused "
    "train step (quantization error deferred into the next step)",
    labelnames=("unit",))


def qcomm_ratio(unit: str, collective: str, wire_bytes: int,
                exact_bytes: int) -> None:
    """Static per-dispatch compression figure, set once per step build.
    Recorded even while probes are disabled (the zero_memory precedent:
    the wire contract must stay assertable through a bench's bare arm,
    and a build is never on the per-signal hot path)."""
    _QCOMM_RATIO.labels(unit=unit, collective=collective).set(
        float(exact_bytes) / max(float(wire_bytes), 1.0))


def qcomm_counters(unit: str, collective: str) -> tuple:
    """Cached ``(wire, exact)`` counter children for one collective site
    (the step increments both per dispatch, gated on :func:`enabled`)."""
    return (_QCOMM_WIRE.labels(unit=unit, collective=collective),
            _QCOMM_EXACT.labels(unit=unit, collective=collective))


def qcomm_residual_norm(unit: str, value: float) -> None:
    """Error-feedback residual L2 norm (published at class-pass ends —
    the caller owns the device reduction and the :func:`enabled` gate)."""
    _QCOMM_RESIDUAL.labels(unit=unit).set(float(value))


# -- pipeline plane ----------------------------------------------------------

_BYTES_STAGED = _reg.counter(
    "znicz_pipeline_bytes_staged_total",
    "host bytes shipped through prefetch stagers")


def staged_bytes(nbytes: int) -> None:
    if _enabled:
        _BYTES_STAGED.inc(nbytes)


# -- resilience plane --------------------------------------------------------

_RESILIENCE = _reg.counter(
    "znicz_resilience_events_total",
    "resilience-plane events (fault fired, retry, restart, hang, "
    "nan_guard, snapshot_resume)", labelnames=("kind", "site"))


def resilience_event(kind: str, site: str = "", **args) -> None:
    """Counter + same-timeline instant event for one resilience action.
    ``kind``: fault | retry | restart | hang | nan_guard |
    snapshot_resume; ``site`` is the fault-plan site / fn name / '' when
    not site-shaped."""
    if not _enabled:
        return
    _RESILIENCE.labels(kind=kind, site=site).inc()
    _trace.instant(f"resilience.{kind}", site=site, **args)


# -- elastic fleet (ISSUE 9) -------------------------------------------------

_ELASTIC_RESTARTS = _reg.counter(
    "znicz_elastic_restarts_total",
    "elastic fleet restart rounds (a worker died or hung; the remainder "
    "was killed and the fleet relaunched)")
_ELASTIC_DEATHS = _reg.counter(
    "znicz_elastic_worker_deaths_total",
    "worker processes observed dead without being asked to stop",
    labelnames=("cause",))
_ELASTIC_RESUMES = _reg.counter(
    "znicz_elastic_resumes_total",
    "fleet relaunches that resumed from a valid snapshot (vs cold "
    "restarts)")
_ELASTIC_WORLD = _reg.gauge(
    "znicz_elastic_world_size",
    "worker-process count of the currently running fleet round (0 when "
    "no fleet is up)")


def elastic_event(kind: str, **args) -> None:
    """One elastic-fleet lifecycle event: counter + timeline instant.
    ``kind``: restart | resume | worker_death (``cause`` = exit |
    signal | hung | boot | wedged).  Counted in the SUPERVISOR process
    — workers keep their own registries."""
    if not _enabled:
        return
    if kind == "worker_death":
        _ELASTIC_DEATHS.labels(cause=args.get("cause", "exit")).inc()
    elif kind == "restart":
        _ELASTIC_RESTARTS.inc()
    elif kind == "resume":
        _ELASTIC_RESUMES.inc()
    _trace.instant(f"elastic.{kind}", **args)


def elastic_world_size(n: int) -> None:
    """Gauge: the fleet's live world size (set at each round launch,
    zeroed when the fleet returns)."""
    _ELASTIC_WORLD.set(float(n))


def elastic_counts() -> dict:
    """Lifetime elastic counters — the drill asserts these match its
    event counts."""
    deaths = sum(child.get() for _, child in _ELASTIC_DEATHS.items())
    return {"restarts": int(_ELASTIC_RESTARTS.get()),
            "worker_deaths": int(deaths),
            "resumes": int(_ELASTIC_RESUMES.get()),
            "world_size": int(_ELASTIC_WORLD.get())}


# -- step anatomy (ISSUE 20) -------------------------------------------------


def anatomy_phase(plane: str, phase: str, dt_s: float,
                  t0: Optional[float] = None) -> None:
    """One already-timed anatomy phase from a producer that owns its
    own clock (prefetcher input-wait/stage, the continuous batcher's
    prefill/decode/verify).  Thin delegate so producers only import
    probe; the import is lazy to keep anatomy off probe's module-load
    path."""
    if not _enabled:
        return
    from znicz_tpu.observe import anatomy as _anatomy
    _anatomy.observe_phase(plane, phase, dt_s, t0=t0)


# -- goodput ledger (ISSUE 20; supervisor-side, like the elastic plane) ------

_GOODPUT_PRODUCTIVE = _reg.counter(
    "znicz_goodput_productive_seconds_total",
    "per-rank wall seconds the elastic fleet spent making step progress "
    "(completed rounds + failed-round time covered by a later-valid "
    "snapshot)", labelnames=("rank",))
_GOODPUT_LOST = _reg.counter(
    "znicz_goodput_lost_seconds_total",
    "per-rank wall seconds of work discarded by a failure (failed-round "
    "time past the newest valid snapshot — recomputed after restart)",
    labelnames=("rank",))
_GOODPUT_SNAPSHOT = _reg.counter(
    "znicz_goodput_snapshot_seconds_total",
    "per-rank wall seconds inside teardown/snapshot grace windows "
    "(SIGTERM grace, snapshot-then-exit)", labelnames=("rank",))
_GOODPUT_IDLE = _reg.counter(
    "znicz_goodput_idle_seconds_total",
    "per-rank wall seconds with no fleet running (spawn windows, "
    "restart backoff, flight dumps)", labelnames=("rank",))
_GOODPUT_RATIO = _reg.gauge(
    "znicz_goodput_ratio",
    "productive / (productive + lost + snapshot + idle) over the "
    "supervisor's lifetime — the fleet-level goodput figure")

_GOODPUT = {"productive": _GOODPUT_PRODUCTIVE, "lost": _GOODPUT_LOST,
            "snapshot": _GOODPUT_SNAPSHOT, "idle": _GOODPUT_IDLE}


def goodput_pretouch(ranks) -> None:
    """Materialize every goodput child before the first fleet sample
    (PR 11 delta-rule lesson — see ``anatomy.pretouch``)."""
    for rank in ranks:
        for fam in _GOODPUT.values():
            fam.labels(rank=str(rank)).inc(0.0)
    _GOODPUT_RATIO.set(0.0)


def goodput_note(category: str, rank, dt_s: float) -> None:
    """Donate ``dt_s`` wall seconds of ``category`` (productive | lost |
    snapshot | idle) for one rank.  Recorded even while probes are
    disabled (the zero_memory precedent): the goodput drill must stay
    assertable through a bench's bare arm, and the supervisor's round
    bookkeeping is never on a per-signal hot path."""
    if dt_s <= 0.0:
        return
    fam = _GOODPUT.get(category)
    if fam is None:
        raise ValueError(f"unknown goodput category: {category!r}")
    fam.labels(rank=str(rank)).inc(float(dt_s))
    total = sum(child.get() for f in _GOODPUT.values()
                for _, child in f.items())
    if total > 0.0:
        _GOODPUT_RATIO.set(
            sum(c.get() for _, c in _GOODPUT_PRODUCTIVE.items()) / total)


def goodput_totals() -> dict:
    """Per-category second sums across ranks — what the elastic drill
    reconciles against supervisor wall time."""
    return {cat: float(sum(child.get() for _, child in fam.items()))
            for cat, fam in _GOODPUT.items()}
