"""Process-global metrics registry — the shared schema every subsystem
donates into (ISSUE 5 tentpole).

Before this module the tree had four ad-hoc telemetry surfaces
(``Workflow.timing_table()`` strings, ``PipelineStats``,
``serve/metrics.py::ServingMetrics``, per-subsystem ``WebStatus``
JSON blocks) with no common schema and nothing scrapeable.  This is the
one substrate: three metric kinds modeled on the Prometheus data model —

- :class:`Counter` — monotonically increasing float (``inc``);
- :class:`Gauge`   — settable level (``set``/``inc``/``dec``), or a
  zero-arg callable evaluated at scrape time (``set_function``);
- :class:`Histogram` — fixed upper-bound buckets (``observe``), exposed
  with cumulative bucket counts plus ``_sum``/``_count`` so a scraper
  can run ``histogram_quantile`` over it.

Families support labels (declared at creation, ``labels(**kv)`` returns
the per-labelset child).  Getters are get-or-create and idempotent, so
any module can say ``counter("znicz_x_total")`` without ordering
concerns; re-declaring with a different type or label set is an error.

Everything is stdlib; one registry-wide lock guards both family
creation and child mutation (hot-path cost: one uncontended lock + one
float add, ~1 µs — the ``metrics_overhead`` bench scenario pins the
end-to-end cost at <2 %).  Counters are process-lifetime monotonic,
exactly like a real Prometheus client: a supervised restart keeps
counting, which is what makes restart storms visible on a dashboard.

Export surfaces: ``snapshot()`` (structured dict, merged into
``WebStatus.snapshot()`` under ``"metrics"``), ``snapshot_flat()``
(compact ``name{labels} -> number`` dict, attached to bench JSON
lines), and ``render_prometheus()`` (text exposition served by
``GET /metrics``).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Callable, Optional, Sequence

#: default buckets for second-valued histograms: 100 µs (a no-op unit
#: fire) .. 60 s (a cold XLA compile inside a step); beyond -> +Inf.
SECONDS_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0)

_TYPES = ("counter", "gauge", "histogram")


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class _Child:
    """One (family, labelset) time series.  All mutation goes through the
    owning registry's lock (passed in) — a single shared lock keeps the
    hot path allocation-free."""

    __slots__ = ("_lock", "value", "fn", "counts", "sum", "count",
                 "_edges")

    def __init__(self, lock: threading.Lock,
                 edges: Optional[tuple] = None) -> None:
        self._lock = lock
        self.value = 0.0
        self.fn: Optional[Callable[[], float]] = None
        self._edges = edges
        if edges is not None:
            self.counts = [0] * (len(edges) + 1)
            self.sum = 0.0
            self.count = 0

    # counter / gauge -------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)
            self.fn = None

    def set_function(self, fn: Callable[[], float]) -> None:
        """Gauge evaluated at scrape time (e.g. a QPS window or a live
        queue depth owned by another object)."""
        with self._lock:
            self.fn = fn

    def get(self) -> float:
        # the callable runs OUTSIDE the registry lock: scrape-time
        # providers (e.g. ServingMetrics.qps) take their own locks, and
        # their event hooks take the registry lock — evaluating under
        # ours would invert the order and deadlock
        with self._lock:
            fn = self.fn
            value = self.value
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — a dead provider must
                return float("nan")        # not kill the scrape
        return value

    # histogram -------------------------------------------------------------
    def observe(self, value: float) -> None:
        # bisect_left == first edge >= value — the "value <= edge"
        # bucket (C-speed: this runs once per control-graph signal)
        i = bisect_left(self._edges, value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value

    def hist_dict(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "buckets": {("+Inf" if i == len(self._edges)
                                 else f"{self._edges[i]:g}"): c
                                for i, c in enumerate(self.counts)}}


class _Family:
    """A named metric family: type + help + label schema + children."""

    __slots__ = ("name", "type", "help", "labelnames", "buckets",
                 "_children", "_lock")

    def __init__(self, name: str, mtype: str, help_: str,
                 labelnames: tuple, lock: threading.Lock,
                 buckets: Optional[tuple] = None) -> None:
        self.name = name
        self.type = mtype
        self.help = help_
        self.labelnames = labelnames
        self.buckets = buckets
        self._children: dict[tuple, _Child] = {}
        self._lock = lock
        if not labelnames:
            self._children[()] = _Child(lock, buckets)

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    key, _Child(self._lock, self.buckets))
        return child

    # label-less convenience: the family proxies its single child --------
    def _solo(self) -> _Child:
        if self.labelnames:
            raise ValueError(f"metric {self.name!r} has labels "
                             f"{self.labelnames}; use .labels(...)")
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._solo().set_function(fn)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def get(self) -> float:
        return self._solo().get()

    def items(self):
        return list(self._children.items())


class Registry:
    """Named families, one lock, three export formats."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # -- declaration (get-or-create, idempotent) ----------------------------
    def _family(self, name: str, mtype: str, help_: str,
                labelnames: Sequence[str],
                buckets: Optional[Sequence[float]] = None) -> _Family:
        labelnames = tuple(labelnames)
        buckets = tuple(float(b) for b in buckets) if buckets else None
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, mtype, help_, labelnames, self._lock, buckets)
                return fam
        if fam.type != mtype:
            raise ValueError(f"metric {name!r} already registered as "
                             f"{fam.type}, not {mtype}")
        if fam.labelnames != labelnames:
            raise ValueError(f"metric {name!r} already registered with "
                             f"labels {fam.labelnames}, not {labelnames}")
        if mtype == "histogram" and fam.buckets != buckets:
            raise ValueError(f"metric {name!r} already registered with "
                             f"buckets {fam.buckets}, not {buckets} — "
                             f"observations would land in edges the "
                             f"second declarer never asked for")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = SECONDS_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labelnames, buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def clear(self) -> None:
        """Drop every family — TESTS ONLY (cached child handles held by
        long-lived objects keep writing into orphaned children)."""
        with self._lock:
            self._families.clear()

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured dict: name -> {type, help, values: [{labels, value}]}.
        Histogram values are {count, sum, buckets} dicts."""
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in fams:
            values = []
            for key, child in fam.items():
                labels = dict(zip(fam.labelnames, key))
                if fam.type == "histogram":
                    values.append({"labels": labels,
                                   "value": child.hist_dict()})
                else:
                    values.append({"labels": labels, "value": child.get()})
            out[fam.name] = {"type": fam.type, "help": fam.help,
                             "values": values}
        return out

    def snapshot_flat(self, skip_zero: bool = True) -> dict:
        """Compact ``name{labels} -> number`` dict (histograms contribute
        ``_count`` and ``_sum``) — the per-scenario snapshot bench.py
        attaches to its JSON result lines.  ``skip_zero`` drops
        never-touched series so artifact lines stay small."""
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in fams:
            for key, child in fam.items():
                ls = _label_str(fam.labelnames, key)
                if fam.type == "histogram":
                    h = child.hist_dict()
                    if skip_zero and h["count"] == 0:
                        continue
                    out[f"{fam.name}_count{ls}"] = h["count"]
                    out[f"{fam.name}_sum{ls}"] = round(h["sum"], 6)
                else:
                    v = child.get()
                    if skip_zero and v == 0.0:
                        continue
                    out[f"{fam.name}{ls}"] = round(v, 6)
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 — the ``GET /metrics``
        body.  Stable ordering: families in registration order, children
        in creation order."""
        with self._lock:
            fams = list(self._families.values())
        lines = []
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.type}")
            for key, child in fam.items():
                if fam.type == "histogram":
                    h = child.hist_dict()
                    acc = 0
                    for edge, c in h["buckets"].items():
                        acc += c
                        names = tuple(fam.labelnames) + ("le",)
                        vals = key + (edge,)
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_label_str(names, vals)} {acc}")
                    ls = _label_str(fam.labelnames, key)
                    lines.append(f"{fam.name}_sum{ls} {_fmt(h['sum'])}")
                    lines.append(f"{fam.name}_count{ls} {h['count']}")
                else:
                    ls = _label_str(fam.labelnames, key)
                    lines.append(f"{fam.name}{ls} {_fmt(child.get())}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    # NaN/inf reach here via dead scrape-time gauge providers — Prometheus
    # text accepts them spelled out, and int(nan) would raise
    if f != f or f in (float("inf"), float("-inf")):
        return repr(f)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: THE process-global registry (the Prometheus default-registry shape).
REGISTRY = Registry()


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> _Family:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> _Family:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = SECONDS_BUCKETS) -> _Family:
    return REGISTRY.histogram(name, help, labelnames, buckets)
