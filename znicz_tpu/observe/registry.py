"""Process-global metrics registry — the shared schema every subsystem
donates into (ISSUE 5 tentpole).

Before this module the tree had four ad-hoc telemetry surfaces
(``Workflow.timing_table()`` strings, ``PipelineStats``,
``serve/metrics.py::ServingMetrics``, per-subsystem ``WebStatus``
JSON blocks) with no common schema and nothing scrapeable.  This is the
one substrate: three metric kinds modeled on the Prometheus data model —

- :class:`Counter` — monotonically increasing float (``inc``);
- :class:`Gauge`   — settable level (``set``/``inc``/``dec``), or a
  zero-arg callable evaluated at scrape time (``set_function``);
- :class:`Histogram` — fixed upper-bound buckets (``observe``), exposed
  with cumulative bucket counts plus ``_sum``/``_count`` so a scraper
  can run ``histogram_quantile`` over it.

Families support labels (declared at creation, ``labels(**kv)`` returns
the per-labelset child).  Getters are get-or-create and idempotent, so
any module can say ``counter("znicz_x_total")`` without ordering
concerns; re-declaring with a different type or label set is an error.

Everything is stdlib; one registry-wide lock guards both family
creation and child mutation (hot-path cost: one uncontended lock + one
float add, ~1 µs — the ``metrics_overhead`` bench scenario pins the
end-to-end cost at <2 %).  Counters are process-lifetime monotonic,
exactly like a real Prometheus client: a supervised restart keeps
counting, which is what makes restart storms visible on a dashboard.

Export surfaces: ``snapshot()`` (structured dict, merged into
``WebStatus.snapshot()`` under ``"metrics"``), ``snapshot_flat()``
(compact ``name{labels} -> number`` dict, attached to bench JSON
lines), and ``render_prometheus()`` (text exposition served by
``GET /metrics``).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Callable, Optional, Sequence

#: default buckets for second-valued histograms: 100 µs (a no-op unit
#: fire) .. 60 s (a cold XLA compile inside a step); beyond -> +Inf.
SECONDS_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0)

_TYPES = ("counter", "gauge", "histogram")


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


def quantile_from_buckets(edges: Sequence[float], counts: Sequence[int],
                          q: float,
                          overflow_hi: Optional[float] = None) -> float:
    """THE histogram quantile estimator (ISSUE 6 satellite): linear
    interpolation inside the winning bucket, the Prometheus
    ``histogram_quantile`` convention — accuracy bounded by bucket
    width, no per-observation sample retention.  ``counts`` are
    per-bucket (NOT cumulative) with the ``+Inf`` overflow last, so
    ``len(counts) == len(edges) + 1``; ``q`` in [0, 1].  A quantile
    landing in the overflow bucket interpolates toward ``overflow_hi``
    (callers pass ``max(last_edge, mean)`` — the serving plane's
    long-standing convention) or clamps to the last edge.  Shared by
    :meth:`_Child.quantile` and ``serve/metrics.py::LatencyHistogram``
    instead of two private percentile codes."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, count in enumerate(counts):
        if count == 0:
            continue
        if seen + count >= rank:
            lo = edges[i - 1] if i > 0 else 0.0
            if i < len(edges):
                hi = edges[i]
            else:
                hi = overflow_hi if overflow_hi is not None else edges[-1]
            return lo + (hi - lo) * (rank - seen) / count
        seen += count
    return edges[-1]


class _Child:
    """One (family, labelset) time series.  All mutation goes through the
    owning registry's lock (passed in) — a single shared lock keeps the
    hot path allocation-free."""

    __slots__ = ("_lock", "value", "fn", "counts", "sum", "count",
                 "_edges")

    def __init__(self, lock: threading.Lock,
                 edges: Optional[tuple] = None) -> None:
        self._lock = lock
        self.value = 0.0
        self.fn: Optional[Callable[[], float]] = None
        self._edges = edges
        if edges is not None:
            self.counts = [0] * (len(edges) + 1)
            self.sum = 0.0
            self.count = 0

    # counter / gauge -------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)
            self.fn = None

    def set_function(self, fn: Callable[[], float]) -> None:
        """Gauge evaluated at scrape time (e.g. a QPS window or a live
        queue depth owned by another object)."""
        with self._lock:
            self.fn = fn

    def get(self) -> float:
        # the callable runs OUTSIDE the registry lock: scrape-time
        # providers (e.g. ServingMetrics.qps) take their own locks, and
        # their event hooks take the registry lock — evaluating under
        # ours would invert the order and deadlock
        with self._lock:
            fn = self.fn
            value = self.value
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — a dead provider must
                return float("nan")        # not kill the scrape
        return value

    # histogram -------------------------------------------------------------
    def observe(self, value: float) -> None:
        # bisect_left == first edge >= value — the "value <= edge"
        # bucket (C-speed: this runs once per control-graph signal)
        i = bisect_left(self._edges, value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value

    def hist_dict(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "buckets": {("+Inf" if i == len(self._edges)
                                 else f"{self._edges[i]:g}"): c
                                for i, c in enumerate(self.counts)}}

    def raw(self) -> tuple:
        """``(count, sum, per-bucket counts)`` in ONE lock round-trip —
        the ``snapshot_flat`` hot path (the watchtower samples it on
        every stride; three separate ``quantile()`` calls would pay
        three lock+copy rounds)."""
        with self._lock:
            return self.count, self.sum, list(self.counts)

    def quantile(self, q: float) -> float:
        """Estimated ``q``-quantile (0 when empty) via the shared
        :func:`quantile_from_buckets`; the overflow bucket interpolates
        toward ``max(last_edge, mean)``."""
        total, total_sum, counts = self.raw()
        if total == 0:
            return 0.0
        return quantile_from_buckets(
            self._edges, counts, q,
            overflow_hi=max(self._edges[-1], total_sum / total))


class _Family:
    """A named metric family: type + help + label schema + children."""

    __slots__ = ("name", "type", "help", "labelnames", "buckets",
                 "_children", "_lock", "_flat_keys")

    def __init__(self, name: str, mtype: str, help_: str,
                 labelnames: tuple, lock: threading.Lock,
                 buckets: Optional[tuple] = None) -> None:
        self.name = name
        self.type = mtype
        self.help = help_
        self.labelnames = labelnames
        self.buckets = buckets
        self._children: dict[tuple, _Child] = {}
        self._lock = lock
        self._flat_keys: dict[tuple, object] = {}
        if not labelnames:
            self._children[()] = _Child(lock, buckets)

    def _flat_key(self, key: tuple):
        """Memoized flat-snapshot key strings for one labelset: key
        formatting dominates ``snapshot_flat`` once the watchtower
        samples it every stride, and the strings never change (label
        schema and bucket edges are both declaration-frozen).  Scalars
        cache the single ``name{labels}`` string; histograms cache
        ``(count_key, sum_key, ((quantile_key, q), ...),
        (bucket_key, ...))``."""
        entry = self._flat_keys.get(key)
        if entry is not None:
            return entry
        ls = _label_str(self.labelnames, key)
        if self.type == "histogram":
            names = self.labelnames + ("le",)
            edge_strs = [f"{e:g}" for e in self.buckets] + ["+Inf"]
            entry = (
                f"{self.name}_count{ls}", f"{self.name}_sum{ls}",
                tuple((f"{self.name}_{tag}{ls}", q)
                      for q, tag in ((0.5, "p50"), (0.95, "p95"),
                                     (0.99, "p99"))),
                tuple(f"{self.name}_bucket"
                      f"{_label_str(names, key + (e,))}"
                      for e in edge_strs))
        else:
            entry = f"{self.name}{ls}"
        self._flat_keys[key] = entry       # idempotent; GIL-atomic
        return entry

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    key, _Child(self._lock, self.buckets))
        return child

    # label-less convenience: the family proxies its single child --------
    def _solo(self) -> _Child:
        if self.labelnames:
            raise ValueError(f"metric {self.name!r} has labels "
                             f"{self.labelnames}; use .labels(...)")
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._solo().set_function(fn)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def get(self) -> float:
        return self._solo().get()

    def items(self):
        return list(self._children.items())


class Registry:
    """Named families, one lock, three export formats."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # -- declaration (get-or-create, idempotent) ----------------------------
    def _family(self, name: str, mtype: str, help_: str,
                labelnames: Sequence[str],
                buckets: Optional[Sequence[float]] = None) -> _Family:
        labelnames = tuple(labelnames)
        buckets = tuple(float(b) for b in buckets) if buckets else None
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, mtype, help_, labelnames, self._lock, buckets)
                return fam
        if fam.type != mtype:
            raise ValueError(f"metric {name!r} already registered as "
                             f"{fam.type}, not {mtype}")
        if fam.labelnames != labelnames:
            raise ValueError(f"metric {name!r} already registered with "
                             f"labels {fam.labelnames}, not {labelnames}")
        if mtype == "histogram" and fam.buckets != buckets:
            raise ValueError(f"metric {name!r} already registered with "
                             f"buckets {fam.buckets}, not {buckets} — "
                             f"observations would land in edges the "
                             f"second declarer never asked for")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = SECONDS_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labelnames, buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def clear(self) -> None:
        """Drop every family — TESTS ONLY (cached child handles held by
        long-lived objects keep writing into orphaned children)."""
        with self._lock:
            self._families.clear()

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured dict: name -> {type, help, values: [{labels, value}]}.
        Histogram values are {count, sum, buckets} dicts."""
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in fams:
            values = []
            for key, child in fam.items():
                labels = dict(zip(fam.labelnames, key))
                if fam.type == "histogram":
                    values.append({"labels": labels,
                                   "value": child.hist_dict()})
                else:
                    values.append({"labels": labels, "value": child.get()})
            out[fam.name] = {"type": fam.type, "help": fam.help,
                             "values": values}
        return out

    def snapshot_flat(self, skip_zero: bool = True,
                      buckets: bool = False) -> dict:
        """Compact ``name{labels} -> number`` dict (histograms contribute
        ``_count`` / ``_sum`` plus estimated ``_p50`` / ``_p95`` /
        ``_p99`` so SLO rules and time series can target latency
        quantiles directly) — the per-scenario snapshot bench.py
        attaches to its JSON result lines and the watchtower ring
        samples.  ``skip_zero`` drops never-touched series so artifact
        lines stay small.  ``buckets`` additionally emits each
        histogram's cumulative ``name_bucket{...,le="..."}`` counts
        (Prometheus convention) — the watchtower samples with it so
        windowed quantiles can be computed over bucket-count deltas
        (the lifetime ``_p95`` estimate damps mid-run regressions)."""
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in fams:
            for key, child in fam.items():
                if fam.type == "histogram":
                    count, total_sum, counts = child.raw()
                    if skip_zero and count == 0:
                        continue
                    count_key, sum_key, q_keys, bucket_keys = \
                        fam._flat_key(key)
                    out[count_key] = count
                    out[sum_key] = round(total_sum, 6)
                    if count:
                        hi = max(child._edges[-1], total_sum / count)
                        for qk, q in q_keys:
                            out[qk] = round(quantile_from_buckets(
                                child._edges, counts, q,
                                overflow_hi=hi), 6)
                    else:
                        for qk, _ in q_keys:
                            out[qk] = 0.0
                    if buckets:
                        acc = 0
                        for bk, c in zip(bucket_keys, counts):
                            acc += c
                            out[bk] = acc
                else:
                    v = child.get()
                    if skip_zero and v == 0.0:
                        continue
                    out[fam._flat_key(key)] = round(v, 6)
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 — the ``GET /metrics``
        body.  Stable ordering: families in registration order, children
        in creation order."""
        with self._lock:
            fams = list(self._families.values())
        lines = []
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.type}")
            for key, child in fam.items():
                if fam.type == "histogram":
                    h = child.hist_dict()
                    acc = 0
                    for edge, c in h["buckets"].items():
                        acc += c
                        names = tuple(fam.labelnames) + ("le",)
                        vals = key + (edge,)
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_label_str(names, vals)} {acc}")
                    ls = _label_str(fam.labelnames, key)
                    lines.append(f"{fam.name}_sum{ls} {_fmt(h['sum'])}")
                    lines.append(f"{fam.name}_count{ls} {h['count']}")
                else:
                    ls = _label_str(fam.labelnames, key)
                    lines.append(f"{fam.name}{ls} {_fmt(child.get())}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    # NaN/inf reach here via dead scrape-time gauge providers — Prometheus
    # text accepts them spelled out, and int(nan) would raise
    if f != f or f in (float("inf"), float("-inf")):
        return repr(f)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: THE process-global registry (the Prometheus default-registry shape).
REGISTRY = Registry()


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> _Family:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> _Family:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = SECONDS_BUCKETS) -> _Family:
    return REGISTRY.histogram(name, help, labelnames, buckets)
