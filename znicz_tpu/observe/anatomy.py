"""Step-time anatomy — phase-attributed accounting for train steps and
decode rounds (ISSUE 20 tentpole).

The observe plane could already say *that* a step got slow (histograms,
watchtower rules) but not *why*.  This module is the attribution layer:
producers stamp phase boundaries and the accountant turns them into

- ``znicz_anatomy_phase_seconds{plane,phase}`` histograms — wall seconds
  of one phase of one step (``plane`` names the producer: ``fused``,
  ``transformer``, ``pipeline``, ``serve``);
- ``znicz_anatomy_step_seconds{plane}`` — the whole step, measured at
  the same clock so per-phase sums reconcile against it (the anatomy
  smoke pins the residual under 10 %);
- ``znicz_anatomy_steps_total{plane}`` — step count (the delta-rule
  friendly companion; pre-touched at init per the PR 11 lesson);
- ``znicz_anatomy_mfu{plane}`` — model FLOPs (``utils/flops.py``) over
  measured step wall time vs the chip's peak — honest on TPU, nominal
  on CPU via ``$ZNICZ_TPU_PEAK_FLOPS`` (see OBSERVABILITY.md);
- complete-spans ``anatomy.<plane>.<phase>`` on the shared tracer ring,
  so phase breakdowns land on the SAME timeline as compiles, faults and
  unit firings.

Phase taxonomy (the label vocabulary — producers reuse, never invent):

==============  =============================================================
phase           meaning
==============  =============================================================
``input_wait``  consumer blocked on the input pipeline (prefetcher ring
                empty — the loader is the bottleneck)
``stage``       host->device staging of one batch (H2D put + ring fence)
``zero_gather`` ZeRO shard_params regather: flat shards -> full weights
``grad``        forward + backward compute producing per-rank local grads
``collective``  the explicit gradient psum (quantized or f32 — the
                cross-rank reduction dispatch)
``update``      optimizer apply: grads + state -> new params
``prefill``     serving: prompt attach / KV-cache prefill of admitted rows
``decode``      serving: one batched decode dispatch over live rows
``verify``      serving: speculative draft+verify round (scoring the
                draft's proposals with the target model)
==============  =============================================================

Host-clock semantics: anatomy phases are *dispatch-boundary* wall times
(``block_until_ready`` between stamps when a producer runs in the
split-dispatch mode).  That loses fwd/bwd overlap a device profiler
would show, but it needs no backend support, costs nothing when off,
and sums to the step wall time by construction — the property the
goodput and straggler layers are built on.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from znicz_tpu.observe import registry as _reg
from znicz_tpu.observe import trace as _trace

#: the closed phase vocabulary (docs/OBSERVABILITY.md catalogue) —
#: :func:`pretouch` materializes exactly these children per plane
PHASES = ("input_wait", "stage", "zero_gather", "grad", "collective",
          "update", "prefill", "decode", "verify")

#: phases a train-step plane owns (the subset pretouch uses for fused /
#: transformer planes; serving planes own prefill/decode/verify)
TRAIN_PHASES = ("zero_gather", "grad", "collective", "update")
SERVE_PHASES = ("prefill", "decode", "verify")

_PHASE_SECONDS = _reg.histogram(
    "znicz_anatomy_phase_seconds",
    "wall seconds of one phase of one step, attributed at dispatch "
    "boundaries (phase taxonomy in docs/OBSERVABILITY.md)",
    labelnames=("plane", "phase"))
_STEP_SECONDS = _reg.histogram(
    "znicz_anatomy_step_seconds",
    "whole-step wall seconds measured at the same clock as the phase "
    "stamps (per-phase sums reconcile against this)",
    labelnames=("plane",))
_STEPS = _reg.counter(
    "znicz_anatomy_steps_total",
    "steps accounted by the anatomy layer (delta-rule companion to the "
    "histograms)", labelnames=("plane",))
_MFU = _reg.gauge(
    "znicz_anatomy_mfu",
    "model-FLOPs utilisation: analytic step FLOPs / (step wall seconds "
    "x peak FLOPs); nominal-peak CPU fallback via $ZNICZ_TPU_PEAK_FLOPS",
    labelnames=("plane",))


def _probe_enabled() -> bool:
    # late import: probe imports registry/trace like we do, and keeping
    # anatomy off probe's import path lets probe expose thin delegating
    # hooks without a cycle
    from znicz_tpu.observe import probe as _probe
    return _probe.enabled()


def pretouch(plane: str, phases: Optional[Sequence[str]] = None) -> None:
    """Materialize every child this plane will ever emit, BEFORE the
    first fleet sample (the PR 11 delta-rule lesson: a labeled child
    absent at the baseline sample makes a fleet delta/quantile rule
    silently never trip).  Histogram/gauge children materialize on
    ``labels()``; the counter additionally takes an ``inc(0)`` so a
    ``skip_zero`` snapshot keeps it too."""
    for phase in (phases if phases is not None else PHASES):
        _PHASE_SECONDS.labels(plane=plane, phase=phase)
    _STEP_SECONDS.labels(plane=plane)
    _STEPS.labels(plane=plane).inc(0.0)
    _MFU.labels(plane=plane).set(0.0)


def observe_phase(plane: str, phase: str, dt_s: float,
                  t0: Optional[float] = None) -> None:
    """One already-timed phase from a producer that owns its own clock
    (prefetcher input-wait/stage, the serving batcher's round phases):
    histogram observation + a complete-span on the tracer ring.  ``t0``
    is the phase's ``time.perf_counter()`` start when the producer has
    it (exact span placement); defaults to now-minus-duration."""
    if not _probe_enabled():
        return
    _PHASE_SECONDS.labels(plane=plane, phase=phase).observe(dt_s)
    start = t0 if t0 is not None else time.perf_counter() - dt_s
    _trace.TRACER.complete(f"anatomy.{plane}.{phase}", start, dt_s)


class StepAnatomy:
    """Cursor-based accountant for one producer plane.

    The producer calls :meth:`begin` at step start, :meth:`stamp` at
    each phase boundary (charging cursor->now to that phase), and
    :meth:`finish` at step end — which emits the step histogram, the
    steps counter, the tracer spans, and (when the producer registered
    an analytic FLOPs figure via :meth:`set_flops`) the MFU gauge.

    Children are resolved once at construction — the stamping hot path
    is two ``perf_counter`` reads and one histogram observe.
    """

    __slots__ = ("plane", "_phase_children", "_step_child", "_steps",
                 "_mfu", "_t0", "_cursor", "_spans", "_flops",
                 "_peak")

    def __init__(self, plane: str,
                 phases: Optional[Sequence[str]] = None) -> None:
        self.plane = str(plane)
        phases = tuple(phases if phases is not None else PHASES)
        pretouch(self.plane, phases)
        self._phase_children = {
            p: _PHASE_SECONDS.labels(plane=self.plane, phase=p)
            for p in phases}
        self._step_child = _STEP_SECONDS.labels(plane=self.plane)
        self._steps = _STEPS.labels(plane=self.plane)
        self._mfu = _MFU.labels(plane=self.plane)
        self._t0 = self._cursor = 0.0
        self._spans: list = []
        self._flops: float = 0.0
        self._peak: Optional[float] = None

    # -- MFU wiring ---------------------------------------------------------
    def set_flops(self, flops_per_step: float) -> None:
        """Analytic model FLOPs of ONE step (``utils/flops.
        train_step_flops`` for the fused plane).  Resolves the peak once;
        a backend without a known peak (bare CPU, no
        ``$ZNICZ_TPU_PEAK_FLOPS``) leaves the MFU gauge at 0 — absent
        would break the pre-touch contract."""
        from znicz_tpu.utils import flops as _flops
        self._flops = float(flops_per_step)
        self._peak = _flops.peak_flops()

    # -- stamping -----------------------------------------------------------
    def begin(self) -> float:
        self._t0 = self._cursor = time.perf_counter()
        self._spans.clear()
        return self._t0

    def stamp(self, phase: str, now: Optional[float] = None) -> None:
        """Charge cursor->now to ``phase`` and advance the cursor."""
        now = time.perf_counter() if now is None else now
        dt = now - self._cursor
        self._spans.append((phase, self._cursor, dt))
        self._cursor = now
        child = self._phase_children.get(phase)
        if child is None:       # producer used an out-of-vocabulary
            child = _PHASE_SECONDS.labels(plane=self.plane,  # phase —
                                          phase=phase)       # still count
            self._phase_children[phase] = child
        child.observe(dt)

    def observe(self, phase: str, dt_s: float) -> None:
        """Record an externally-timed phase WITHOUT moving the cursor
        (e.g. input-wait measured by the loader before begin())."""
        self._phase_children.get(phase, _PHASE_SECONDS.labels(
            plane=self.plane, phase=phase)).observe(dt_s)
        self._spans.append((phase, time.perf_counter() - dt_s, dt_s))

    def finish(self) -> float:
        """Close the step: whole-step histogram + counter + tracer spans
        + MFU.  Returns the step wall seconds."""
        now = time.perf_counter()
        wall = now - self._t0
        self._step_child.observe(wall)
        self._steps.inc()
        if self._flops and self._peak and wall > 0.0:
            self._mfu.set(self._flops / (wall * self._peak))
        tracer = _trace.TRACER
        if tracer.enabled:
            for phase, start, dt in self._spans:
                tracer.complete(f"anatomy.{self.plane}.{phase}",
                                start, dt)
            tracer.complete(f"anatomy.{self.plane}.step", self._t0, wall)
        self._spans.clear()
        return wall
