"""Flight recorder — self-contained crash post-mortems (ISSUE 6
tentpole, part 3).

When a process dies, the telemetry that explains WHY dies with it: the
tracer ring, the watchtower's time series and the registry are all
in-memory.  ``dump()`` freezes them into ONE atomically-written
``flight_<ts>_<reason>.json`` artifact:

- the newest N trace-ring events (``workflow.step`` spans around the
  crash — the failing delivery is recorded with ``error: true`` by the
  run loop — plus every ``resilience.*`` / ``compile.*`` /
  ``watchtower.trip`` instant);
- the last K time-series samples from the global watchtower ring (a
  fresh sample is taken at dump time, so even a never-sampled process
  records its state at the moment of failure);
- the full registry snapshot, a config/mesh fingerprint, and the tail
  of the JSONL log sink when one is configured.

Triggers: explicit ``dump()``; the supervisor dumps into its snapshot
directory before every restore-and-resume (and on budget exhaustion) so
the post-mortem survives the process; ``auto_dump()`` fires on injected
faults, NaN-guard trips and watchtower rule trips but is a no-op until
``configure(dir=...)`` opts in (chaos tests inject thousands of faults —
they must not spray artifacts), and is rate-limited to one artifact per
``min_interval_s``.

``python -m znicz_tpu flight <artifact.json>`` pretty-prints one.

Everything here is stdlib — a flight can be dumped (and read) without
jax in the process; the mesh fingerprint is captured only when jax is
ALREADY imported.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time
from typing import Optional

from znicz_tpu.core import logger as _logger
from znicz_tpu.observe import registry as _reg
from znicz_tpu.observe import trace as _trace
from znicz_tpu.observe import watchtower as _watchtower

#: artifact schema identifier — pinned by tests/test_watchtower.py.
#: /2 added the top-level ``planes`` key (live-subsystem snapshots from
#: registered providers: the serve/generate admission ledgers, the
#: fleet aggregator's per-worker view); the viewer still reads /1
SCHEMA = "znicz_tpu.flight/2"
_READABLE_SCHEMAS = ("znicz_tpu.flight/1", SCHEMA)

#: auto-dump configuration (process-global, mirrors the plane's other
#: singletons); ``dir=None`` keeps auto_dump a no-op
_config = {"dir": None, "last_spans": 256, "last_samples": 120,
           "log_lines": 200, "min_interval_s": 1.0}
# None, not 0.0: time.monotonic() counts from BOOT on Linux, so on a
# machine (or container) up for less than min_interval_s a 0.0 sentinel
# reads as "dumped recently" and silently suppresses the first artifact
_last_auto_dump: Optional[float] = None


def configure(dir: Optional[str] = None, last_spans: int = 256,
              last_samples: int = 120, log_lines: int = 200,
              min_interval_s: float = 1.0) -> None:
    """Opt in to automatic dumps: artifacts land in ``dir`` on every
    injected fault / NaN-guard trip / watchtower rule trip, at most one
    per ``min_interval_s``.  ``configure()`` with no dir disables.
    Reconfiguring resets the rate limiter: an explicit opt-in starts a
    fresh window, so a dump made under the PREVIOUS config (possibly
    with a tiny interval) cannot suppress the new config's first
    artifact for up to its whole ``min_interval_s``."""
    global _last_auto_dump
    _config.update(dir=dir, last_spans=int(last_spans),
                   last_samples=int(last_samples),
                   log_lines=int(log_lines),
                   min_interval_s=float(min_interval_s))
    _last_auto_dump = None


def configured() -> bool:
    return _config["dir"] is not None


#: live-subsystem snapshot providers embedded into every artifact under
#: ``planes`` (ISSUE 11): name -> zero-arg callable returning a JSON-able
#: dict.  The continuous batcher registers its admission ledger here so
#: a post-mortem can check ``admitted == completed+failed+abandoned``
#: without a live scrape; the fleet aggregator registers each worker's
#: last snapshot.  Newest registration per name wins (the registry-gauge
#: convention); a raising provider degrades to an error string.
_planes: dict = {}


def register_plane(name: str, fn) -> None:
    _planes[str(name)] = fn


def unregister_plane(name: str, fn=None) -> None:
    """Remove a provider — with ``fn`` given, only if it is still the
    registered one (a torn-down batcher must not evict its
    replacement)."""
    if fn is None or _planes.get(str(name)) is fn:
        _planes.pop(str(name), None)


def _jsonable(value):
    """Best-effort JSON coercion for config trees (Tune leaves, numpy
    scalars, tuples) — a fingerprint must never fail a dump."""
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set)):
        return [_jsonable(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    try:
        return float(value)
    except (TypeError, ValueError):
        return repr(value)


def _config_fingerprint() -> dict:
    """The active config tree + device/mesh shape — enough to answer
    "what was this process actually running" from the artifact alone."""
    out: dict = {"argv": list(sys.argv)}
    try:
        from znicz_tpu.core.config import root

        out["root"] = _jsonable(root.as_dict())
    except Exception:  # noqa: BLE001 — fingerprint is best-effort
        out["root"] = None
    jax = sys.modules.get("jax")   # fingerprint only an ALREADY-imported
    if jax is not None:            # jax — a dump must never boot a backend
        try:
            devices = jax.devices()
            out["mesh"] = {"platform": devices[0].platform,
                           "device_kind": getattr(devices[0],
                                                  "device_kind", ""),
                           "device_count": len(devices),
                           "process_index": getattr(
                               jax, "process_index", lambda: 0)()}
        except Exception:  # noqa: BLE001
            out["mesh"] = None
    else:
        out["mesh"] = None
    return out


def _log_tail(max_lines: int) -> list:
    """Tail of the newest configured JSONL log sink ([] without one)."""
    paths = [p for p in _logger.jsonl_paths() if os.path.isfile(p)]
    if not paths:
        return []
    newest = max(paths, key=os.path.getmtime)
    try:
        with open(newest, "rb") as f:
            # read at most ~256 KiB off the end — log files rotate but
            # a dump must stay O(artifact), not O(run length)
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 262144))
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return []
    return lines[-max_lines:]


def build_artifact(reason: str, extra: Optional[dict] = None,
                   last_spans: Optional[int] = None,
                   last_samples: Optional[int] = None) -> dict:
    """Assemble (but do not write) one flight document."""
    n_spans = last_spans if last_spans is not None else \
        _config["last_spans"]
    n_samples = last_samples if last_samples is not None else \
        _config["last_samples"]
    # freeze the state AT the failure: one fresh ring sample guarantees
    # >= 1 time-series sample even in a process that never attached the
    # watchtower (flight_sample bypasses the observe master switch — a
    # post-mortem wants the numbers regardless — and holds the tower's
    # eval lock so a dump from another thread cannot race a concurrent
    # rule evaluation)
    tower = _watchtower.WATCHTOWER
    tower.flight_sample()
    ts_doc = tower.ring.to_dict(last_n=n_samples)
    ts_doc["summary"] = tower.ring.summary()
    ts_doc["rules"] = [r.snapshot() for r in tower.rules]
    planes = {}
    for name, fn in list(_planes.items()):
        try:
            planes[name] = _jsonable(fn())
        except Exception as exc:  # noqa: BLE001 — a dead plane must
            planes[name] = {"error": repr(exc)}   # not fail the dump
    now = time.time()
    return {
        "schema": SCHEMA,
        "reason": str(reason),
        "ts": round(now, 6),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now)),
        "host": platform.node(),
        "pid": os.getpid(),
        "extra": _jsonable(extra or {}),
        "spans": _trace.TRACER.tail(n_spans),
        "timeseries": ts_doc,
        "metrics": _reg.REGISTRY.snapshot(),
        "planes": planes,
        "config": _config_fingerprint(),
        "log_tail": _log_tail(_config["log_lines"]),
    }


def dump(dir: Optional[str] = None, reason: str = "manual",
         extra: Optional[dict] = None, last_spans: Optional[int] = None,
         last_samples: Optional[int] = None) -> str:
    """Write one flight artifact atomically (tmp + fsync + rename) into
    ``dir`` (default: the configured auto-dump dir, else CWD); returns
    the artifact path."""
    target_dir = dir or _config["dir"] or "."
    os.makedirs(target_dir, exist_ok=True)
    doc = build_artifact(reason, extra, last_spans, last_samples)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.localtime(doc["ts"]))
    micros = int((doc["ts"] % 1) * 1e6)
    slug = "".join(c if c.isalnum() else "_" for c in doc["reason"])[:32]
    path = os.path.join(target_dir,
                        f"flight_{stamp}_{micros:06d}_{slug}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)     # a crash mid-dump leaves no torn artifact
    return path


def auto_dump(reason: str, **ctx) -> Optional[str]:
    """Event-triggered dump (fault fired, NaN-guard trip, rule trip):
    no-op until :func:`configure` set a directory, rate-limited, and
    NEVER raises — the failure path must not fail harder because the
    recorder did."""
    global _last_auto_dump
    if _config["dir"] is None:
        return None
    now = time.monotonic()
    if _last_auto_dump is not None and \
            now - _last_auto_dump < _config["min_interval_s"]:
        return None
    try:
        path = dump(reason=reason, extra=ctx)
    except Exception:  # noqa: BLE001
        return None
    # stamp AFTER a successful write: a failed attempt (disk full,
    # unwritable dir) must not arm the rate limiter and suppress the
    # next real artifact
    _last_auto_dump = now
    return path


def load(path: str) -> dict:
    """Read + schema-check one artifact."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") not in _READABLE_SCHEMAS:
        raise ValueError(f"{path}: not a flight artifact "
                         f"(schema={doc.get('schema')!r}, "
                         f"expected one of {_READABLE_SCHEMAS})")
    return doc


# -- CLI (python -m znicz_tpu flight <artifact.json>) ------------------------

def print_flight(doc: dict, out=None, span_rows: int = 20) -> None:
    """Human rendering of one artifact: reason, the newest spans and
    instants, rule states, the time-series digest, and the log tail."""
    out = out or sys.stdout
    w = out.write
    w(f"flight: {doc['reason']} at {doc['iso']} "
      f"(host {doc['host']}, pid {doc['pid']})\n")
    if doc.get("extra"):
        w(f"  extra: {json.dumps(doc['extra'])}\n")
    cfg = doc.get("config") or {}
    if cfg.get("mesh"):
        m = cfg["mesh"]
        w(f"  mesh: {m.get('device_count')}x {m.get('platform')} "
          f"({m.get('device_kind')})\n")
    spans = doc.get("spans", [])
    w(f"\nspans: {len(spans)} ring events (newest last)\n")
    for ev in spans[-span_rows:]:
        args = f"  {json.dumps(ev['args'])}" if ev.get("args") else ""
        if ev.get("ph") == "X":
            w(f"  {ev['ts']:>14.1f}us  {ev.get('dur', 0):>11.1f}us  "
              f"{ev['name']}{args}\n")
        else:
            w(f"  {ev['ts']:>14.1f}us  {'instant':>13}  "
              f"{ev['name']}{args}\n")
    ts = doc.get("timeseries", {})
    samples = ts.get("samples", [])
    w(f"\ntimeseries: {len(samples)} samples")
    if len(samples) >= 2:
        w(f" over {samples[-1]['ts'] - samples[0]['ts']:.1f}s")
    w("\n")
    for rule in ts.get("rules", []):
        w(f"  rule {rule['name']}: trips={rule['trips']} "
          f"last={rule['last_value']}\n")
    summary = ts.get("summary", {})
    for key, row in sorted(summary.items()):
        rate = (f"  rate={row['rate_per_s']:g}/s"
                if "rate_per_s" in row else "")
        w(f"  {key}: last={row['last']:g} min={row['min']:g} "
          f"mean={row['mean']:g} max={row['max']:g}{rate}\n")
    w(f"\nmetrics: {len(doc.get('metrics', {}))} registry families\n")
    for name, plane in sorted((doc.get("planes") or {}).items()):
        w(f"  plane {name}: {json.dumps(plane)[:200]}\n")
    tail = doc.get("log_tail", [])
    if tail:
        w(f"\nlog tail ({len(tail)} lines):\n")
        for line in tail[-20:]:
            w(f"  {line}\n")


def flight_main(argv) -> int:
    """``znicz_tpu flight <artifact.json> [--json]`` entry point."""
    args = [a for a in argv if not a.startswith("-")]
    if len(args) != 1:
        print("usage: znicz_tpu flight <flight_artifact.json> [--json]",
              file=sys.stderr)
        return 2
    try:
        doc = load(args[0])
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"flight: {exc}", file=sys.stderr)
        return 1
    if "--json" in argv:
        json.dump(doc, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print_flight(doc)
    return 0
