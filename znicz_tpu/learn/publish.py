"""LM package publication — the trainer's half of the VELES
master-loop (ISSUE 14): every K epochs the live training params are
exported through the existing ``export_lm`` path and announced in an
atomic manifest the adoption bridge polls.

Publish protocol (all writes atomic, so a reader never sees a torn
package or a manifest naming a half-written file):

1. ``step.export_lm`` writes ``lm_e<epoch>.npz`` (export_lm's own
   pid-unique tmp + rename);
2. ``manifest.json`` is rewritten (tmp + rename) with the package
   path, its content fingerprint (``utils/naming.py``), the epoch and
   a wall stamp — the fingerprint in the manifest is what the bridge
   compares against the fleet's current one, and the wall stamp is the
   start of the publish-to-adopted latency clock.

Republishing after an elastic resume is harmless by construction: the
resumed trainer's params are bit-identical (the ISSUE 14 drill pin),
so epoch K's re-export carries the same sha256 and the bridge sees
nothing new.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from znicz_tpu.core.units import Unit
from znicz_tpu.observe import registry as _reg
from znicz_tpu.utils.naming import package_fingerprint

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = "znicz_tpu.learn/1"

_M_PUBLISHES = _reg.counter(
    "znicz_learn_publishes_total",
    "LM packages the trainer exported and announced in the publish "
    "manifest (one per K-epoch boundary; the adoption bridge's input)")


def manifest_path(publish_dir: str) -> str:
    return os.path.join(publish_dir, MANIFEST_NAME)


def latest_manifest(publish_dir: str) -> Optional[dict]:
    """The newest published package, or None while nothing was
    published (or the manifest is mid-rewrite — rename is atomic, so a
    parse failure only ever means "not yet")."""
    try:
        with open(manifest_path(publish_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != MANIFEST_SCHEMA:
        return None
    return doc


def publish_package(step, publish_dir: str, epoch: int,
                    seq: int, keep: int = 8) -> dict:
    """Export the step's live params and announce them; returns the
    manifest written.  ``keep`` bounds the publish dir the way
    ``max_segments`` bounds the spool: superseded ``lm_e*.npz``
    packages beyond the newest ``keep`` are unlinked (the manifest's
    current package is always among them, since it is always the
    newest) — a long-running continuous-learning deployment must not
    grow the disk one dead package per K epochs."""
    os.makedirs(publish_dir, exist_ok=True)
    pkg = os.path.join(publish_dir, f"lm_e{epoch:05d}.npz")
    step.export_lm(pkg)
    doc = {"schema": MANIFEST_SCHEMA, "package": os.path.abspath(pkg),
           "epoch": int(epoch), "seq": int(seq),
           "fingerprint": package_fingerprint(pkg),
           "ts": round(time.time(), 3)}
    path = manifest_path(publish_dir)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    _M_PUBLISHES.inc()
    stale = sorted(n for n in os.listdir(publish_dir)
                   if n.startswith("lm_e") and n.endswith(".npz"))[
                       :-max(1, int(keep))]
    for name in stale:
        try:
            os.unlink(os.path.join(publish_dir, name))
        except OSError:
            pass                      # retention must never fail a
    return doc                        # publish


class LMPublisher(Unit):
    """Workflow unit: export + announce every ``every``-th epoch.

    Linked after the snapshotter (decision -> snapshotter -> publisher)
    with ``gate_skip = ~decision.epoch_ended``, so a publish happens at
    the SAME boundary the training snapshot covers — the published
    weights are always resumable state, never mid-epoch params.  Rank 0
    only (the single-writer election the snapshotter uses).
    """

    def __init__(self, workflow=None, step=None, decision=None,
                 publish_dir: str = "", every: int = 1,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if step is None or decision is None or not publish_dir:
            raise ValueError("LMPublisher needs step=, decision= and "
                             "publish_dir=")
        self.step = step
        self.decision = decision
        self.publish_dir = str(publish_dir)
        self.every = int(every)
        if self.every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.published: list[dict] = []

    def run(self) -> None:
        epoch = int(self.decision.epoch_number)
        if epoch % self.every:
            return
        from znicz_tpu.snapshotter import process_rank_world
        if process_rank_world()[0] != 0:
            return
        doc = publish_package(self.step, self.publish_dir, epoch,
                              seq=len(self.published) + 1)
        self.published.append(doc)
        self.info(f"published {os.path.basename(doc['package'])} "
                  f"(epoch {epoch}, sha256 "
                  f"{doc['fingerprint']['sha256'][:12]})")
