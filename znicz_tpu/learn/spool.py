"""Feedback spool — the crash-safe traffic log between the serving
fleet and the trainer (ISSUE 14).

One spool is a DIRECTORY of append-only JSONL segments::

    spool/
        seg_00000000.jsonl      one JSON record per line
        seg_00000001.jsonl      (writers roll at segment_bytes)
        CURSOR.json             the trainer's published consumption floor

**Writers** are the serving workers (`--feedback-spool`): every
accepted request becomes one record appended as a SINGLE ``os.write``
to an ``O_APPEND`` fd — POSIX append atomicity is what lets N worker
processes share one segment without a coordinator, and it fixes a
TOTAL ORDER over records the moment the bytes land, which is the
property the trainer's bit-exact resume stands on (two readers of the
same byte range always see the same records, whenever they read).

**Crash model**: a writer SIGKILL'd mid-``write`` leaves at most one
torn fragment.  If nothing follows it, the fragment just sits at EOF
(never a complete line, never consumed); if another worker appends
after it, the fragment and that line merge into one unparseable line —
the reader counts it (``znicz_learn_spool_torn_total``), skips it, and
keeps going.  Torn traffic is LOST (it was never acknowledged as
trained), never a crash and never a half-parsed record.

**Bounded**: a writer that rolls past ``max_segments`` unlinks the
oldest segment (``znicz_learn_spool_dropped_segments_total``).  A
cursor pointing into a dropped segment fails loudly at read time — a
trainer that fell a whole retention window behind must say so, not
silently skip.

**Reader** (:class:`SpoolReader`): a cursor is ``{"seg", "offset",
"records"}``.  ``read(cursor, n)`` returns exactly the next ``n``
parseable records and the advanced cursor; re-reading from a saved
cursor returns byte-identical results (exactly-once replay — the
snapshot-resume contract of ``loader/spool.py``).  A partial line at
the EOF of the TOP segment is "not written yet" (the reader waits); the
same bytes below a higher segment are "torn by a dead writer" (counted
and skipped) — both verdicts are stable once made, because segments are
never un-created and appended bytes never change.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from znicz_tpu.observe import registry as _reg

SEGMENT_PREFIX = "seg_"
SEGMENT_SUFFIX = ".jsonl"
CURSOR_FILE = "CURSOR.json"

_M_RECORDS = _reg.counter(
    "znicz_learn_spool_records_total",
    "feedback records appended to the spool by serving workers, by "
    "record kind (generate / predict)",
    labelnames=("kind",))
_M_TORN = _reg.counter(
    "znicz_learn_spool_torn_total",
    "unparseable spool lines skipped by the reader — a writer died "
    "mid-append (the record was never acknowledged; skipping is the "
    "crash-safety contract, docs/LEARNING.md)")
_M_DROPPED = _reg.counter(
    "znicz_learn_spool_dropped_segments_total",
    "spool segments unlinked by writer retention (max_segments) — "
    "records a trainer never consumed before the window closed")
_M_LAG = _reg.gauge(
    "znicz_learn_spool_lag_records",
    "complete records in the spool beyond the trainer's consumption "
    "cursor (stamped at each epoch ingest) — the trainer's backlog")


class SpoolTimeout(TimeoutError):
    """``SpoolReader.read`` ran out of wait budget before ``n`` records
    existed — the spool's writers have gone quiet."""


class SpoolGone(RuntimeError):
    """The cursor points into a segment writer retention dropped."""


def segment_name(seq: int) -> str:
    return f"{SEGMENT_PREFIX}{seq:08d}{SEGMENT_SUFFIX}"


def segment_seq(name: str) -> Optional[int]:
    if not (name.startswith(SEGMENT_PREFIX) and
            name.endswith(SEGMENT_SUFFIX)):
        return None
    body = name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
    return int(body) if body.isdigit() else None


def list_segments(directory: str) -> list:
    """Sorted segment sequence numbers present in ``directory``."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(s for s in (segment_seq(n) for n in names)
                  if s is not None)


def initial_cursor(directory: str) -> dict:
    """Where a cold trainer starts: the oldest RETAINED segment (the
    spool may already have rolled since boot)."""
    segs = list_segments(directory)
    return {"seg": segs[0] if segs else 0, "offset": 0, "records": 0}


def read_cursor_file(directory: str) -> Optional[dict]:
    try:
        with open(os.path.join(directory, CURSOR_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_cursor_file(directory: str, cursor: dict) -> None:
    """Atomically publish the trainer's consumption floor — operator
    visibility and retention guidance, NOT the resume authority (that
    is the training snapshot, which carries the cursor inside the
    loader state)."""
    path = os.path.join(directory, CURSOR_FILE)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({**cursor, "ts": round(time.time(), 3)}, f)
        os.replace(tmp, path)
    except OSError:
        pass                    # full disk must not kill the trainer


class FeedbackSpool:
    """Multi-process-safe spool writer; see module docstring.  One
    instance per worker process; ``append`` is one ``os.write`` to an
    ``O_APPEND`` fd, so concurrent workers interleave whole records,
    never bytes."""

    def __init__(self, directory: str, segment_bytes: int = 16 << 20,
                 max_segments: int = 16) -> None:
        if segment_bytes < 1 or max_segments < 2:
            raise ValueError(f"need segment_bytes >= 1 and "
                             f"max_segments >= 2, got {segment_bytes}/"
                             f"{max_segments}")
        self.directory = str(directory)
        self.segment_bytes = int(segment_bytes)
        self.max_segments = int(max_segments)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()     # threaded HTTP handlers share
        self._fd: Optional[int] = None    # one writer per process
        self._seq: Optional[int] = None
        self._needs_newline = False       # segment tail is a dead
        #                                   writer's torn fragment

    # -- segment management --------------------------------------------------
    def _open_top(self) -> None:
        """(Re)open the top segment, rolling to a fresh one when the
        top is full; GC segments past the retention window."""
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
        segs = list_segments(self.directory)
        seq = segs[-1] if segs else 0
        path = os.path.join(self.directory, segment_name(seq))
        try:
            if os.path.getsize(path) >= self.segment_bytes:
                seq += 1
        except OSError:
            pass                          # not created yet: seq stands
        self._seq = seq
        self._fd = os.open(
            os.path.join(self.directory, segment_name(seq)),
            os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        # a segment whose tail is not "\n" ends in a dead writer's torn
        # fragment: prefix our first append with a newline so ONLY the
        # fragment is lost (as its own unparseable line), not our
        # record merged into it.  A racing double-prefix just leaves an
        # empty line, which the reader skips silently.
        try:
            size = os.fstat(self._fd).st_size
            if size:
                with open(os.path.join(self.directory,
                                       segment_name(seq)), "rb") as f:
                    f.seek(size - 1)
                    self._needs_newline = f.read(1) != b"\n"
            else:
                self._needs_newline = False
        except OSError:
            self._needs_newline = False
        # retention: every writer may GC; unlink is idempotent enough
        # (a racing second unlink just ENOENTs)
        for old in [s for s in segs if s <= seq - self.max_segments]:
            try:
                os.unlink(os.path.join(self.directory,
                                       segment_name(old)))
                _M_DROPPED.inc()
            except OSError:
                pass

    def append(self, record: dict) -> None:
        """Append one record (one line, one syscall).  Raises
        ``ValueError`` on a record that does not serialize; swallows
        ``OSError`` after one reopen attempt — feedback must never
        take the serving worker down."""
        line = (json.dumps(record, separators=(",", ":")) + "\n").encode()
        with self._lock:
            for attempt in (0, 1):
                if self._fd is None or \
                        os.fstat(self._fd).st_size >= self.segment_bytes:
                    self._open_top()
                try:
                    if self._needs_newline:
                        line = b"\n" + line
                        self._needs_newline = False
                    os.write(self._fd, line)
                    break
                except OSError:
                    if attempt:           # reopened once already: drop
                        return            # the record, keep serving
                    self._fd = None
        _M_RECORDS.labels(kind=str(record.get("kind", "unknown"))).inc()

    # -- the serving planes' record shapes -----------------------------------
    def append_generate(self, request_id: str, prompt, tokens) -> None:
        """One accepted generation: the prompt and the continuation the
        client actually received, with request-id provenance."""
        self.append({"kind": "generate", "rid": str(request_id),
                     "prompt": [int(t) for t in prompt],
                     "tokens": [int(t) for t in tokens],
                     "ts": round(time.time(), 3)})

    def append_predict(self, request_id: str, inputs, outputs) -> None:
        """One served prediction: the labeled (input, output) pair."""
        self.append({"kind": "predict", "rid": str(request_id),
                     "input": inputs, "output": outputs,
                     "ts": round(time.time(), 3)})

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


class SpoolReader:
    """Cursor-driven exactly-once reader; see module docstring."""

    def __init__(self, directory: str, poll_s: float = 0.05) -> None:
        self.directory = str(directory)
        self.poll_s = float(poll_s)

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.directory, segment_name(seq))

    def _scan(self, cursor: dict, budget: Optional[int],
              records: list, count_torn: bool = True) -> dict:
        """One non-blocking sweep from ``cursor``: parse complete lines
        into ``records`` until ``budget`` is met or the data runs out.
        Returns the advanced cursor.  Once the budget is met the cursor
        NEVER advances past a segment boundary — the end cursor of a
        read is therefore canonical (independent of whether a later
        rotation has happened by scan time), which is what lets a
        snapshot's stored span replay to the identical end offset.
        ``count_torn=False`` suppresses the torn counter (lag probes
        re-scan the same backlog every epoch and must not re-count the
        same dead line)."""
        seg = int(cursor["seg"])
        offset = int(cursor["offset"])
        count = int(cursor["records"])
        while budget is None or len(records) < budget:
            path = self._segment_path(seg)
            segs = list_segments(self.directory)
            if not os.path.exists(path):
                if segs and seg < segs[0]:
                    raise SpoolGone(
                        f"cursor points into segment {seg} but the "
                        f"spool retains only {segs[0]}..{segs[-1]} — "
                        f"the trainer fell behind the retention window")
                if segs and seg < segs[-1]:
                    seg += 1              # a gap the GC tore open
                    offset = 0
                    continue
                break                     # top not created yet: no data
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read()
            newline = chunk.rfind(b"\n")
            complete, tail = (chunk[:newline + 1], chunk[newline + 1:]) \
                if newline >= 0 else (b"", chunk)
            # split on \n ONLY (json.dumps output never contains raw
            # control bytes, but a torn fragment must not be re-split
            # on them either)
            for raw in complete.split(b"\n")[:-1]:
                consumed = len(raw) + 1
                if budget is not None and len(records) >= budget:
                    break
                offset += consumed
                if not raw:
                    continue              # writer newline-prefix races
                try:
                    records.append(json.loads(raw))
                    count += 1
                except ValueError:
                    if count_torn:        # merged/torn line: skip it
                        _M_TORN.inc()
            else:
                # every complete line consumed — the budget check comes
                # BEFORE any segment advance: a read that is satisfied
                # exactly at a segment's end must return (seg, end),
                # whether or not a later rotation exists by now
                if budget is not None and len(records) >= budget:
                    break
                if seg < (list_segments(self.directory) or [seg])[-1]:
                    # a higher segment exists: this one is finished;
                    # a leftover fragment is a dead writer's torn line
                    if tail and count_torn:
                        _M_TORN.inc()
                    seg += 1
                    offset = 0
                    continue
                break                     # top segment: wait for more
        return {"seg": seg, "offset": offset, "records": count}

    def read(self, cursor: dict, n: int,
             wait_s: Optional[float] = None) -> tuple:
        """-> ``(records, new_cursor)`` — exactly the next ``n``
        parseable records after ``cursor``.  Blocks up to ``wait_s``
        for writers to produce them (None = do not wait); raises
        :class:`SpoolTimeout` on an exhausted wait and
        :class:`SpoolGone` on a cursor below the retention window.
        Replaying a stored cursor returns identical records — the
        exactly-once contract."""
        if n < 1:
            raise ValueError(f"need n >= 1, got {n}")
        deadline = None if wait_s is None else time.monotonic() + wait_s
        records: list = []
        while True:
            cursor = self._scan(cursor, n, records)
            if len(records) >= n:
                return records, cursor
            if deadline is None or time.monotonic() > deadline:
                raise SpoolTimeout(
                    f"spool {self.directory!r} produced only "
                    f"{len(records)}/{n} records within the wait "
                    f"budget (writers quiet?)")
            time.sleep(self.poll_s)

    def lag(self, cursor: dict) -> int:
        """Complete records currently readable beyond ``cursor`` (the
        trainer's backlog; also stamped on the lag gauge)."""
        records: list = []
        try:
            self._scan(dict(cursor), None, records, count_torn=False)
        except SpoolGone:
            pass
        _M_LAG.set(float(len(records)))
        return len(records)
