"""Adoption bridge — publish-to-rollout glue (ISSUE 14).

The trainer announces packages in the publish manifest
(learn/publish.py); the serving fleet adopts packages through the
ISSUE 13 :class:`~znicz_tpu.fleet.rollout.RollingUpdate`.  The bridge
is the small daemon that closes the gap: poll the manifest, and when
it names a fingerprint the fleet does not serve yet (gated on the
pool's ``expected_fingerprint`` — the same field
``/fleet/status.json`` now surfaces top-level), drive one rolling
update and stamp the publish-to-adopted latency.

Failure posture mirrors the rollout's: a failed adoption leaves the
fleet serving what it served (counted ``outcome="failed"``), and the
bridge retries on the NEXT manifest change rather than hammering the
same package — a bad export must not turn into a rollout storm.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from znicz_tpu.core.logger import Logger
from znicz_tpu.learn.publish import latest_manifest
from znicz_tpu.observe import registry as _reg

_M_ADOPTIONS = _reg.counter(
    "znicz_learn_adoptions_total",
    "publish-triggered rolling updates by outcome (adopted / failed)",
    labelnames=("outcome",))
_M_ADOPTION_S = _reg.gauge(
    "znicz_learn_adoption_seconds",
    "latest publish-to-adopted latency: manifest wall stamp to fleet "
    "convergence on the published fingerprint")


class AdoptionBridge(Logger):
    """Poll ``publish_dir``'s manifest; roll the fleet onto every new
    fingerprint.  ``pool`` and ``rollout`` are the live ISSUE 13
    objects (the learn CLI runs all three in one process)."""

    def __init__(self, publish_dir: str, pool, rollout,
                 poll_s: float = 0.5,
                 rollout_timeout_s: float = 600.0) -> None:
        super().__init__()
        self.publish_dir = str(publish_dir)
        self.pool = pool
        self.rollout = rollout
        self.poll_s = float(poll_s)
        self.rollout_timeout_s = float(rollout_timeout_s)
        self.adoptions = 0
        self.failures = 0
        self.last_adoption_s: Optional[float] = None
        self.last_manifest: Optional[dict] = None
        self._skip_sha: Optional[str] = None   # failed sha: wait for a
        self._stop = threading.Event()         # NEW publish to retry
        self._thread: Optional[threading.Thread] = None

    # -- the decision --------------------------------------------------------
    def poll_once(self) -> Optional[dict]:
        """One decision: adopt the manifest's package when its
        fingerprint is new to the fleet.  Returns the rollout report
        when one ran (the deterministic-test hook)."""
        doc = latest_manifest(self.publish_dir)
        if doc is None:
            return None
        self.last_manifest = doc
        sha = (doc.get("fingerprint") or {}).get("sha256")
        if not sha or sha == self._skip_sha:
            return None
        if sha == (self.pool.expected_fingerprint or {}).get("sha256"):
            return None                  # fleet already on it
        if self.rollout.rolling:
            return None                  # one at a time; next poll
        self.info(f"adopting published package "
                  f"{doc['package']} (epoch {doc.get('epoch')}, "
                  f"sha256 {sha[:12]})")
        try:
            self.rollout.start(doc["package"])
        except ValueError as exc:        # raced another rollout / gone
            self.warning(f"adoption not started: {exc}")
            return None
        report = self.rollout.join(timeout_s=self.rollout_timeout_s)
        if report.get("state") == "done":
            self.adoptions += 1
            latency = max(0.0, time.time() - float(doc.get("ts") or
                                                   time.time()))
            self.last_adoption_s = latency
            _M_ADOPTIONS.labels(outcome="adopted").inc()
            _M_ADOPTION_S.set(latency)
            self.info(f"fleet adopted sha256 {sha[:12]} "
                      f"{latency:.1f}s after publish")
        else:
            self.failures += 1
            self._skip_sha = sha         # retry only on a NEW publish
            _M_ADOPTIONS.labels(outcome="failed").inc()
            self.error(f"adoption of sha256 {sha[:12]} failed: "
                       f"{report.get('error')}")
        return report

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "AdoptionBridge":
        if self._thread is not None:
            return self
        # pre-touch both outcome children so fleet delta rules see the
        # 0 baseline (the PR 11 test-won lesson)
        _M_ADOPTIONS.labels(outcome="adopted").inc(0)
        _M_ADOPTIONS.labels(outcome="failed").inc(0)

        def loop() -> None:
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll_once()
                except Exception as exc:  # noqa: BLE001 — the bridge
                    self.warning(f"bridge poll failed: {exc!r}")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="znicz-learn-bridge")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def status(self) -> dict:
        """The ``/fleet/status.json`` ``"learn"`` block (the learn CLI
        registers it as a status provider)."""
        return {"publish_dir": self.publish_dir,
                "adoptions": self.adoptions,
                "failures": self.failures,
                "last_adoption_s": self.last_adoption_s,
                "manifest": self.last_manifest}
