"""Trainer worker program for the learn plane (ISSUE 14) — the file
``python -m znicz_tpu`` (and therefore the elastic supervisor) runs as
the continuous-learning trainer:

    python -m znicz_tpu elastic --workers 1 --no-spmd \\
        --snap-dir /run/learn/snaps \\
        znicz_tpu/learn/trainer_workflow.py \\
        -o root.learn.spool_dir=/run/learn/spool \\
        -o root.learn.package=/run/lm.npz \\
        -o root.learn.publish_dir=/run/learn/publish

Control graph (the char_lm shape over the streaming loader)::

    Repeater -> SpoolSequenceLoader -> TransformerLMStep
             -> DecisionMSE -> NNSnapshotter -> LMPublisher -> Repeater

The base LM package supplies the vocabulary AND the starting weights —
the trainer continues the weights the fleet is serving (the VELES
master-owns-canonical-weights loop), and every ``publish_every`` epochs
exports a fresh package the adoption bridge rolls out.

Config (``root.learn.*``, all overridable with ``-o``):

=====================  ======================================================
``spool_dir``          feedback spool directory (required)
``package``            base LM package: charmap + architecture + init params
                       (required)
``publish_dir``        manifest + exported packages (default:
                       ``<spool_dir>/../publish``)
``publish_every``      publish every K epochs (default 2)
``max_epochs``         stop after this many epochs (default 4)
``records_per_epoch``  stream slice one epoch trains on (default 8)
``seq_len``            training window length (default 16)
``minibatch_size``     rows per minibatch (default 8)
``lr``                 SGD learning rate (default 0.05)
``pipeline_depth``     async input-pipeline depth (0 = sync; default 2)
``wait_timeout_s``     epoch-ingest wait budget (default 300)
=====================  ======================================================

Snapshots land in ``$ZNICZ_TPU_SNAP_DIR`` (the elastic env contract);
on natural completion the worker drops ``history_<rank>.json`` beside
them — the overlap drill's bit-exactness evidence, exactly the
``tools/elastic_workflow.py`` convention.
"""

from __future__ import annotations

import json
import os


def build():
    from znicz_tpu.core.config import root
    from znicz_tpu.core.plumbing import Repeater
    from znicz_tpu.learn.publish import LMPublisher
    from znicz_tpu.loader.spool import SpoolSequenceLoader
    from znicz_tpu.units.decision import DecisionMSE
    from znicz_tpu.units.lm import TransformerLMStep
    from znicz_tpu.units.nn_units import NNWorkflow
    from znicz_tpu.utils.export import load_lm

    cfg = root.learn
    spool_dir = str(cfg.get("spool_dir", "") or "")
    package = str(cfg.get("package", "") or "")
    if not spool_dir or not package:
        raise ValueError(
            "the learn trainer needs -o root.learn.spool_dir=DIR and "
            "-o root.learn.package=LM.npz")
    publish_dir = str(cfg.get("publish_dir", "") or "") or \
        os.path.join(os.path.dirname(os.path.abspath(spool_dir)),
                     "publish")
    params, meta = load_lm(package)
    charmap = meta.get("charmap")
    if not charmap:
        raise ValueError(f"{package!r} carries no charmap — the learn "
                         f"plane trains char LMs over the serving "
                         f"vocabulary")

    w = NNWorkflow(name="LearnTrainer")
    w.repeater = Repeater(w)
    w.loader = SpoolSequenceLoader(
        w, spool_dir=spool_dir, charmap=charmap,
        seq_len=int(cfg.get("seq_len", 16)),
        records_per_epoch=int(cfg.get("records_per_epoch", 8)),
        minibatch_size=int(cfg.get("minibatch_size", 8)),
        wait_timeout_s=float(cfg.get("wait_timeout_s", 300.0)))
    step = w.step = TransformerLMStep(
        w, loader=w.loader, n_layers=int(meta["n_layers"]),
        d=int(meta["d"]), heads=int(meta["heads"]), ff=int(meta["ff"]),
        lr=float(cfg.get("lr", 0.05)))
    # continuous learning: start from the weights the fleet serves
    # (xla_init places a pre-set pytree instead of initializing fresh)
    step._params = params
    dec = w.decision = DecisionMSE(
        w, max_epochs=int(cfg.get("max_epochs", 4)))
    w.forwards = [step]
    w.gds = []

    w.repeater.link_from(w.start_point)
    w.loader.link_from(w.repeater)
    step.link_from(w.loader)
    dec.link_from(step)
    tail = dec
    snap_dir = os.environ.get("ZNICZ_TPU_SNAP_DIR")
    if snap_dir:
        from znicz_tpu.snapshotter import NNSnapshotter
        snap = w.snapshotter = NNSnapshotter(
            w, directory=snap_dir, prefix="learn",
            only_improved=False, keep_all=True, verify_timeout=2.0)
        snap.link_from(dec)
        snap.link_workflow_state(w)
        snap.gate_skip = ~dec.epoch_ended
        tail = snap
    pub = w.publisher = LMPublisher(
        w, step=step, decision=dec, publish_dir=publish_dir,
        every=int(cfg.get("publish_every", 2)))
    pub.link_from(tail)
    # publish at the same boundary the snapshot covers: the announced
    # weights are always resumable state
    pub.gate_skip = ~dec.epoch_ended
    tail = pub
    w.repeater.link_from(tail)
    w.end_point.link_from(tail)
    w.end_point.gate_block = ~dec.complete

    dec.link_attrs(w.loader, "minibatch_class", "last_minibatch",
                   "class_lengths", "epoch_number")
    dec.link_attrs(step, "minibatch_mse", "minibatch_size")
    depth = int(cfg.get("pipeline_depth", 2))
    if depth:
        from znicz_tpu.pipeline import attach_prefetcher
        attach_prefetcher(w.loader, stager=step.make_stager(),
                          depth=depth)
    return w


def run(load, main):
    w, _ = load(build)
    main()
    snap_dir = os.environ.get("ZNICZ_TPU_SNAP_DIR")
    if snap_dir:
        # the bit-exactness evidence (elastic_workflow.py convention):
        # a SIGTERM'd worker exits 143 inside main() and never writes
        rank = os.environ.get("ZNICZ_TPU_ELASTIC_RANK", "0")
        out = os.path.join(snap_dir, f"history_{rank}.json")
        with open(out, "w") as f:
            json.dump({"rank": int(rank),
                       "history": w.decision.metrics_history},
                      f, default=float)
