"""``python -m znicz_tpu learn`` — the train-while-serve loop in one
command (ISSUE 14).

::

    python -m znicz_tpu learn lm.npz --workers 2 --port 8080 \\
        --publish-every 2 --max-epochs 4 -- --slots 2 --max-len 64

Assembles, in one process tree:

- an ISSUE 13 serving fleet (router + N ``generate --serve`` workers
  booted from ``lm.npz``), each worker appending accepted traffic to
  the shared feedback spool (``--feedback-spool``);
- ONE trainer process under the elastic supervisor
  (``resilience/elastic.py``, world size 1) running
  ``learn/trainer_workflow.py`` over the spool — crash/kill of the
  trainer resumes from its newest snapshot with a bit-exact cursor;
- the adoption bridge: every package the trainer publishes rolls onto
  the fleet through the ISSUE 13 zero-downtime ``RollingUpdate``.

``GET /fleet/status.json`` on the router carries the whole loop's
state: top-level ``package`` (fleet fingerprint + convergence),
``rollout``, and ``learn`` (manifest + adoption latency).  SIGTERM
drains the fleet and stops the trainer at its next poll.  Everything
after a literal ``--`` passes to the worker CLI verbatim.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def build_learn_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="znicz_tpu learn",
        description="continuous learning on live traffic: serving "
                    "fleet + spool-fed trainer + adoption bridge")
    p.add_argument("package", help="base LM package (utils/export.py "
                                   "export_lm) the fleet serves and "
                                   "the trainer continues from")
    p.add_argument("--workers", type=int, default=2,
                   help="serving worker count")
    p.add_argument("--port", type=int, default=8080,
                   help="router listen port (0 picks a free one)")
    p.add_argument("--run-dir", default=None,
                   help="spool/publish/snapshots/logs root (default: "
                        "<package dir>/learn)")
    p.add_argument("--publish-every", type=int, default=2,
                   help="trainer publishes every K epochs")
    p.add_argument("--max-epochs", type=int, default=4,
                   help="trainer epoch budget (the fleet keeps serving "
                        "after it completes)")
    p.add_argument("--records-per-epoch", type=int, default=8,
                   help="spool records one training epoch consumes")
    p.add_argument("--seq-len", type=int, default=16,
                   help="training window length")
    p.add_argument("--minibatch", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="trainer async input-pipeline depth (0 = sync)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="trainer elastic restart budget")
    p.add_argument("--ready-timeout-s", type=float, default=180.0)
    p.add_argument("--trainer-fault-plan", default=None,
                   metavar="JSON",
                   help="serialized FaultPlan armed in the ROUND-0 "
                        "trainer's env (seeded chaos drills)")
    p.add_argument("--smoke-test", action="store_true",
                   help="drive the loop once: self-traffic until one "
                        "publish is adopted fleet-wide, print a JSON "
                        "verdict, exit (CI probe)")
    p.epilog = ("everything after a literal -- passes through to the "
                "generate worker CLI verbatim")
    return p


def _self_traffic(base: str, stop, results, lock) -> None:
    """Background self-requests through the router — the smoke's
    traffic source (and therefore the spool's).  Throttled: the spool
    only needs a trickle, and an unthrottled loop starves the
    co-resident trainer of the whole box."""
    import urllib.error
    import urllib.request

    n = 0
    while not stop.wait(0.1):
        n += 1
        # records must out-length the training window (seq_len + 1
        # ids) or they window to nothing — 2 prompt chars + 12 tokens
        # covers the smoke's --seq-len comfortably
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": "ab" if n % 2 else "cd",
                             "max_tokens": 12,
                             "timeout_s": 30}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=90) as r:
                lines = [json.loads(raw) for raw in r]
            terminal = lines[-1] if lines else {}
            with lock:
                results.append(
                    "completed" if terminal.get("done") and
                    "error" not in terminal else "errored")
        except urllib.error.HTTPError as exc:
            exc.read()
            with lock:
                results.append("rejected")
        except Exception:  # noqa: BLE001 — counted, judged at the end
            with lock:
                results.append("broken")


def learn_main(argv) -> int:
    from znicz_tpu.fleet.rollout import RollingUpdate
    from znicz_tpu.fleet.router import FleetRouter
    from znicz_tpu.fleet.workers import WorkerPool
    from znicz_tpu.learn.bridge import AdoptionBridge
    from znicz_tpu.resilience.elastic import run_elastic
    from znicz_tpu.resilience.supervisor import SupervisorPolicy

    worker_args: list = []
    argv = list(argv)
    if "--" in argv:
        i = argv.index("--")
        argv, worker_args = argv[:i], argv[i + 1:]
    args = build_learn_parser().parse_args(argv)
    if args.workers < 1:
        print("learn: --workers must be >= 1", file=sys.stderr)
        return 2
    run_dir = args.run_dir or os.path.join(
        os.path.dirname(os.path.abspath(args.package)) or ".", "learn")
    spool_dir = os.path.join(run_dir, "spool")
    publish_dir = os.path.join(run_dir, "publish")
    snap_dir = os.path.join(run_dir, "snaps")
    for d in (run_dir, spool_dir, publish_dir, snap_dir):
        os.makedirs(d, exist_ok=True)
    try:
        pool = WorkerPool(
            args.package, plane="generate",
            worker_args=[*worker_args, "--feedback-spool", spool_dir],
            run_dir=os.path.join(run_dir, "fleet"),
            ready_timeout_s=args.ready_timeout_s)
    except (OSError, ValueError) as exc:
        print(f"learn: cannot use {args.package!r}: {exc}",
              file=sys.stderr)
        return 2
    trainer_wf = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "trainer_workflow.py")
    trainer_argv = [
        trainer_wf,
        "-o", f"root.learn.spool_dir={spool_dir}",
        "-o", f"root.learn.package={os.path.abspath(args.package)}",
        "-o", f"root.learn.publish_dir={publish_dir}",
        "-o", f"root.learn.publish_every={args.publish_every}",
        "-o", f"root.learn.max_epochs={args.max_epochs}",
        "-o", f"root.learn.records_per_epoch={args.records_per_epoch}",
        "-o", f"root.learn.seq_len={args.seq_len}",
        "-o", f"root.learn.minibatch_size={args.minibatch}",
        "-o", f"root.learn.lr={args.lr}",
        "-o", f"root.learn.pipeline_depth={args.pipeline_depth}",
    ]
    router = bridge = None
    trainer_stop = threading.Event()
    trainer_box: dict = {"report": None, "error": None}
    prev_sigterm = None
    try:
        for _ in range(args.workers):
            pool.spawn()
        if not pool.wait_all_ready():
            print("learn: serving workers never became ready (see "
                  f"{pool.run_dir}/worker_w*.log)", file=sys.stderr)
            return 1
        pool.start_probes()
        router = FleetRouter(pool, port=args.port)
        rollout = RollingUpdate(pool)
        router.attach_rollout(rollout)
        port = router.start()
        bridge = AdoptionBridge(publish_dir, pool, rollout)
        pool.aggregator.register_status_provider("learn", bridge.status)
        bridge.start()

        def train() -> None:
            try:
                trainer_box["report"] = run_elastic(
                    trainer_argv, snap_dir, workers=1, spmd=False,
                    policy=SupervisorPolicy(
                        max_restarts=args.max_restarts),
                    run_dir=os.path.join(run_dir, "trainer"),
                    fault_plans={0: args.trainer_fault_plan}
                    if args.trainer_fault_plan else None,
                    stop_event=trainer_stop)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                trainer_box["error"] = exc

        trainer = threading.Thread(target=train, daemon=True,
                                   name="znicz-learn-trainer")
        trainer.start()
        base = f"http://127.0.0.1:{port}"
        print(f"learn: fleet on {base}/ ({args.workers} workers), "
              f"trainer supervised over {spool_dir}", flush=True)
        if args.smoke_test:
            return _smoke(args, pool, router, bridge, trainer,
                          trainer_box, base)
        done = threading.Event()
        prev_sigterm = signal.signal(signal.SIGTERM,
                                     lambda *a: done.set())
        try:
            while not done.is_set():
                if trainer_box["error"] is not None:
                    print(f"learn: trainer supervision failed: "
                          f"{trainer_box['error']!r}", file=sys.stderr)
                    return 1
                done.wait(0.5)
        except KeyboardInterrupt:
            pass
        print("learn: draining...")
        return 0
    finally:
        trainer_stop.set()
        if bridge is not None:
            bridge.stop()
        if router is not None:
            router.stop()
        pool.stop()
        # the trainer thread tears its worker down via run_elastic's
        # stop_event + finally; bounded join so SIGTERM stays prompt
        t = threading.enumerate()
        for th in t:
            if th.name == "znicz-learn-trainer":
                th.join(timeout=60.0)
        if prev_sigterm is not None:
            signal.signal(signal.SIGTERM, prev_sigterm)


def _smoke(args, pool, router, bridge, trainer, trainer_box,
           base: str) -> int:
    """CI probe: self-traffic feeds the spool, the trainer publishes,
    the bridge rolls the fleet — verdict on one adopted publish."""
    import time

    from znicz_tpu.utils.naming import package_fingerprint

    stop = threading.Event()
    results: list = []
    lock = threading.Lock()
    threads = [threading.Thread(target=_self_traffic,
                                args=(base, stop, results, lock),
                                daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 600
    ok, why = False, "timeout before an adoption"
    while time.monotonic() < deadline:
        if trainer_box["error"] is not None:
            why = f"trainer failed: {trainer_box['error']!r}"
            break
        if bridge.adoptions >= 1 and not router.rollout.rolling:
            ok, why = True, ""
            break
        time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    manifest = bridge.last_manifest or {}
    converged = bool(manifest) and all(
        (w.fingerprint or {}).get("sha256") ==
        (manifest.get("fingerprint") or {}).get("sha256")
        for w in pool.workers())
    ledger = router.snapshot()
    closed = ledger["admitted"] == ledger["completed"] + \
        ledger["failed"] + ledger["client_gone"]
    with lock:
        kinds = {k: results.count(k) for k in set(results)}
    verdict = ok and converged and closed and \
        not kinds.get("broken", 0)
    print(json.dumps({
        "smoke": "ok" if verdict else "bad", "why": why,
        "adoptions": bridge.adoptions,
        "adoption_latency_s": bridge.last_adoption_s,
        "converged": converged, "ledger": ledger,
        "traffic": kinds,
        "fingerprint": (manifest.get("fingerprint") or {}).get(
            "sha256", "")[:12],
        "base_fingerprint": package_fingerprint(
            args.package)["sha256"][:12]}), flush=True)
    return 0 if verdict else 1
