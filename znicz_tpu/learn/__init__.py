"""Continuous learning on live traffic (ISSUE 14) — the VELES
master-loop closed end to end: serving workers append accepted traffic
to a crash-safe feedback spool, a supervised trainer consumes it as a
streaming dataset, publishes a fresh LM package every K epochs, and an
adoption bridge rolls the serving fleet onto it with zero lost
requests.

Pieces (each importable on its own; the spool never imports jax, so
serving workers stay as light as before):

- :mod:`znicz_tpu.learn.spool` — the bounded multi-writer JSONL spool
  (:class:`FeedbackSpool`) and its exactly-once cursor reader
  (:class:`SpoolReader`);
- :mod:`znicz_tpu.loader.spool` — ``SpoolSequenceLoader``, the
  streaming dataset loader tailing the spool into the async
  ``BatchPrefetcher`` with a snapshot-durable consumption cursor;
- :mod:`znicz_tpu.learn.publish` — the every-K-epochs LM export unit
  and the atomic publish manifest;
- :mod:`znicz_tpu.learn.bridge` — the publish-to-rollout adoption
  bridge over the ISSUE 13 :class:`RollingUpdate`;
- :mod:`znicz_tpu.learn.cli` — ``python -m znicz_tpu learn <pkg>``,
  the one-command assembly (serve fleet + trainer under the elastic
  supervisor + bridge).

docs/LEARNING.md is the operator's guide.
"""

from znicz_tpu.learn.spool import (FeedbackSpool, SpoolReader,  # noqa: F401
                                   SpoolTimeout, initial_cursor)
from znicz_tpu.learn.publish import (latest_manifest,  # noqa: F401
                                     publish_package)
from znicz_tpu.learn.bridge import AdoptionBridge  # noqa: F401
