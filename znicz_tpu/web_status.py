"""Web status dashboard — rebuild of veles/web_status.py.

The reference ran a tornado dashboard aggregating running workflows'
progress over ZMQ (SURVEY.md §3.3 Web status row).  The rebuild is a
minimal in-process HTTP endpoint on the TPU-VM host: ``/status.json``
reports every registered workflow's name, epoch, metrics history and
per-unit timing (plus the watchtower's time-series digest under
``"watchtower"``); ``/metrics`` serves the process-global telemetry
registry in Prometheus text exposition format (scrapeable);
``/trace.json`` dumps the global tracer's ring buffer as Chrome-trace
JSON (loads in Perfetto); ``/timeseries.json`` serves the watchtower's
retained delta ring (observe/watchtower.py) so history is readable
without an external scraper; ``register_fleet`` additionally mounts a
fleet aggregator's merged cross-process view under ``/fleet/*``
(observe/federation.py); ``/`` renders a plain HTML table.  Stdlib
``http.server`` on a daemon thread — zero dependencies, CLI ``-s``
(stealth) simply never starts it.  Endpoint table:
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from znicz_tpu import observe
from znicz_tpu.core.logger import Logger


class WebStatus(Logger):
    """Serve live status for one or more workflows."""

    def __init__(self, port: int = 0) -> None:
        super().__init__()
        self.workflows: list = []
        self.serving: list = []
        self.health: list = []
        self.pipelines: list = []
        self.fleet = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port = port

    def register(self, workflow) -> "WebStatus":
        self.workflows.append(workflow)
        return self

    def register_serving(self, name: str, source) -> "WebStatus":
        """Surface a serving plane's metrics in ``/status.json``.

        ``source``: a ``ServeServer`` (its ``metrics_snapshot``), any
        object with a ``snapshot()`` (e.g. ``ServingMetrics``), or a
        zero-arg callable returning a dict.
        """
        fn = getattr(source, "metrics_snapshot", None) or \
            getattr(source, "snapshot", None) or source
        if not callable(fn):
            raise TypeError(f"register_serving needs a snapshot source, "
                            f"got {source!r}")
        self.serving.append((str(name), fn))
        return self

    def register_health(self, name: str, guard) -> "WebStatus":
        """Surface a resilience guard's trip counters in ``/status.json``
        (next to the serving metrics): ``guard`` is a
        :class:`~znicz_tpu.resilience.health.HealthGuard`, anything with
        a ``snapshot()``, or a zero-arg callable returning a dict."""
        fn = getattr(guard, "snapshot", None) or guard
        if not callable(fn):
            raise TypeError(f"register_health needs a snapshot source, "
                            f"got {guard!r}")
        self.health.append((str(name), fn))
        return self

    def register_pipeline(self, name: str, pipeline) -> "WebStatus":
        """Surface an input pipeline's stall accounting in
        ``/status.json`` (next to the serving and health metrics):
        ``pipeline`` is a
        :class:`~znicz_tpu.pipeline.BatchPrefetcher` (its
        ``stats_snapshot``), anything with a ``snapshot()``, or a
        zero-arg callable returning a dict."""
        fn = getattr(pipeline, "stats_snapshot", None) or \
            getattr(pipeline, "snapshot", None) or pipeline
        if not callable(fn):
            raise TypeError(f"register_pipeline needs a snapshot source, "
                            f"got {pipeline!r}")
        self.pipelines.append((str(name), fn))
        return self

    def register_fleet(self, aggregator) -> "WebStatus":
        """Mount a :class:`~znicz_tpu.observe.federation.
        FleetAggregator`'s merged cross-process view under ``/fleet/*``
        (``/fleet/metrics``, ``/fleet/metrics.prom``,
        ``/fleet/status.json``, ``/fleet/trace.json``) — ISSUE 11."""
        self.fleet = aggregator
        return self

    # -- payload ------------------------------------------------------------
    def snapshot(self) -> dict:
        out = []
        for w in self.workflows:
            dec = getattr(w, "decision", None)
            out.append({
                "name": w.name,
                "epoch": (int(dec.epoch_number) if dec is not None else None),
                "complete": bool(dec.complete) if dec is not None else None,
                "best_metric": dec.best_metric if dec is not None else None,
                "history": list(dec.metrics_history) if dec is not None
                else [],
                "units": [
                    {"name": u.name, "runs": u.timing[0],
                     "time_s": round(u.timing[1], 4)} for u in w.units],
            })
        doc = {"workflows": out}
        for key, sources in (("serving", self.serving),
                             ("health", self.health),
                             ("pipeline", self.pipelines)):
            section = {}
            for name, fn in sources:
                try:
                    section[name] = fn()
                except Exception as exc:  # noqa: BLE001 — a dead plane
                    section[name] = {"error": repr(exc)}  # must not kill
            if section:                                   # the dashboard
                doc[key] = section
        # the shared telemetry plane rides along under its own top-level
        # keys — "metrics"/"watchtower" collide with none of the
        # per-plane sections above (workflows/serving/health/pipeline),
        # pinned by tests/test_observe.py
        doc["metrics"] = observe.REGISTRY.snapshot()
        doc["watchtower"] = observe.WATCHTOWER.snapshot()
        return doc

    # -- server -------------------------------------------------------------
    def start(self) -> int:
        status = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence request logging
                pass

            def do_GET(self):
                if self.path.startswith("/fleet/") and \
                        status.fleet is not None:
                    payload = status.fleet.http_payload(self.path)
                    if payload is not None:
                        body, ctype = payload
                        self.send_response(200)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                if self.path.startswith("/status.json"):
                    body = json.dumps(status.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    # Prometheus text exposition of the process-global
                    # registry — the scrape target
                    body = observe.REGISTRY.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/trace.json"):
                    # Chrome-trace dump of the tracer ring (Perfetto)
                    body = json.dumps(
                        observe.TRACER.export_dict()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/timeseries.json"):
                    # the watchtower's retained delta ring: replay
                    # base + samples in order to reconstruct every
                    # metric's history (docs/OBSERVABILITY.md)
                    body = json.dumps(
                        observe.WATCHTOWER.timeseries_dict()).encode()
                    ctype = "application/json"
                else:
                    rows = "".join(
                        f"<tr><td>{w['name']}</td><td>{w['epoch']}</td>"
                        f"<td>{w['best_metric']}</td>"
                        f"<td>{w['complete']}</td></tr>"
                        for w in status.snapshot()["workflows"])
                    body = (f"<html><body><h1>znicz_tpu status</h1>"
                            f"<table border=1><tr><th>workflow</th>"
                            f"<th>epoch</th><th>best</th><th>done</th></tr>"
                            f"{rows}</table></body></html>").encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info(f"web status on http://127.0.0.1:{self.port}/")
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
