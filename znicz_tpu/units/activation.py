"""Standalone activation units — rebuild of veles.znicz activation.py ::
ActivationForward / ActivationBackward pairs {Tanh, RELU, StrictRELU,
Sigmoid, Log, SinCos, TanhLog, Mul}.

For nets where the activation is decoupled from FC/conv (SURVEY.md §3.1).
Each pair shares a name in the MAPPING registry so StandardWorkflow can
instantiate the backward chain automatically.  ``Mul`` is the elementwise
product of two linked inputs (gating) — formula reconstructed, reference
detail was [MED].
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core.memory import Array
from znicz_tpu.ops import activations
from znicz_tpu.units.nn_units import Forward, GradientDescentBase


class ActivationForward(Forward):
    """Elementwise activation as its own unit."""

    MAPPING: set = set()
    ACTIVATION = activations.LINEAR

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, include_bias=False, **kwargs)

    def _common_init(self, **kwargs) -> None:
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(shape=self.input.shape)
        self.init_array(self.input, self.output)

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        return activations.forward(jnp, self.ACTIVATION, x)

    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = activations.forward(np, self.ACTIVATION,
                                              self.input.mem)

    def xla_init(self) -> None:
        act = self.ACTIVATION
        self._xla_fn = jax.jit(lambda x: activations.forward(jnp, act, x))

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(self._xla_fn(self.input.devmem))


class ActivationBackward(GradientDescentBase):
    """err_input = err_output * act'(input) — has both input and output
    linked (reference: ActivationBackward)."""

    MAPPING: set = set()
    ACTIVATION = activations.LINEAR

    def link_from_forward(self, forward) -> "ActivationBackward":
        self.link_attrs(forward, "input", "output")
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.err_output.shape:
            self.err_input.reset(shape=self.err_output.shape)
        self.init_array(self.err_input, self.err_output)

    def _backward(self, xp, x, y, e):
        return e * activations.derivative_from_input(
            xp, self.ACTIVATION, x, y)

    def numpy_run(self) -> None:
        err_in = self._backward(np, self.input.map_read(),
                                self.output.map_read(),
                                self.err_output.map_read())
        self.err_input.map_invalidate()
        self.err_input.mem = err_in

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(
            lambda x, y, e: self._backward(jnp, x, y, e))

    def xla_run(self) -> None:
        for arr in (self.input, self.output, self.err_output):
            arr.unmap()
        self.err_input.set_devmem(self._xla_fn(
            self.input.devmem, self.output.devmem, self.err_output.devmem))


class ForwardTanh(ActivationForward):
    MAPPING = {"activation_tanh"}
    ACTIVATION = activations.TANH


class BackwardTanh(ActivationBackward):
    MAPPING = {"activation_tanh"}
    ACTIVATION = activations.TANH


class ForwardRELU(ActivationForward):
    MAPPING = {"activation_relu"}
    ACTIVATION = activations.RELU


class BackwardRELU(ActivationBackward):
    MAPPING = {"activation_relu"}
    ACTIVATION = activations.RELU


class ForwardStrictRELU(ActivationForward):
    MAPPING = {"activation_str"}
    ACTIVATION = activations.STRICT_RELU


class BackwardStrictRELU(ActivationBackward):
    MAPPING = {"activation_str"}
    ACTIVATION = activations.STRICT_RELU


class ForwardSigmoid(ActivationForward):
    MAPPING = {"activation_sigmoid"}
    ACTIVATION = activations.SIGMOID


class BackwardSigmoid(ActivationBackward):
    MAPPING = {"activation_sigmoid"}
    ACTIVATION = activations.SIGMOID


class ForwardLog(ActivationForward):
    MAPPING = {"activation_log"}
    ACTIVATION = activations.LOG


class BackwardLog(ActivationBackward):
    MAPPING = {"activation_log"}
    ACTIVATION = activations.LOG


class ForwardSinCos(ActivationForward):
    MAPPING = {"activation_sincos"}
    ACTIVATION = activations.SINCOS


class BackwardSinCos(ActivationBackward):
    MAPPING = {"activation_sincos"}
    ACTIVATION = activations.SINCOS


class ForwardTanhLog(ActivationForward):
    MAPPING = {"activation_tanhlog"}
    ACTIVATION = activations.TANHLOG


class BackwardTanhLog(ActivationBackward):
    MAPPING = {"activation_tanhlog"}
    ACTIVATION = activations.TANHLOG


class ForwardMul(ActivationForward):
    """y = input * input2 (elementwise gate)."""

    MAPPING = {"activation_mul"}

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input2 = Array()

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        # the single-input fused-chain protocol cannot thread input2;
        # refuse rather than silently degrade to identity
        raise NotImplementedError(
            "ForwardMul (two-input gate) is eager-only; keep it outside "
            "the fused segment")

    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = self.input.map_read() * self.input2.map_read()

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda a, b: a * b)

    def xla_run(self) -> None:
        for arr in (self.input, self.input2):
            arr.unmap()
        self.output.set_devmem(self._xla_fn(self.input.devmem,
                                            self.input2.devmem))


class BackwardMul(ActivationBackward):
    """err_input = err_output * input2."""

    MAPPING = {"activation_mul"}

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input2 = Array()

    def link_from_forward(self, forward) -> "BackwardMul":
        self.link_attrs(forward, "input", "output", "input2")
        return self

    def numpy_run(self) -> None:
        self.err_input.map_invalidate()
        self.err_input.mem = self.err_output.map_read() * \
            self.input2.map_read()

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda e, b: e * b)

    def xla_run(self) -> None:
        for arr in (self.err_output, self.input2):
            arr.unmap()
        self.err_input.set_devmem(self._xla_fn(self.err_output.devmem,
                                               self.input2.devmem))
