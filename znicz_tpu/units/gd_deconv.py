"""Deconvolution gradient unit — rebuild of veles.znicz gd_deconv.py ::
GDDeconv.

err_input is the *forward* conv of err_output (adjoint of the transposed
conv); grad_weights the patch GEMM with input/error roles swapped relative
to GDConv (znicz_tpu.ops.deconv.backward).  No bias (matches Deconv).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops import deconv as deconv_ops, sgd
from znicz_tpu.units.nn_units import GradientDescentBase


class GDDeconv(GradientDescentBase):
    """Reference: gd_deconv.py :: GDDeconv."""

    MAPPING = {"deconv"}

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.sliding = (1, 1)
        self.padding = (0, 0, 0, 0)

    def link_from_forward(self, forward) -> "GDDeconv":
        self.link_attrs(forward, "input", "output", "weights")
        self.sliding = forward.sliding
        self.padding = forward.padding
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(shape=self.input.shape)
        self.init_array(self.err_input, self.err_output,
                        self.gradient_weights)

    def _backward(self, xp, x, w, err_out):
        return deconv_ops.backward(
            xp, x, w, err_out, self.sliding, self.padding)

    def _step(self, xp, x, w, err_out, vel_w, batch_size):
        err_in, grad_w = self._backward(xp, x, w, err_out)
        if not self.need_err_input:
            err_in = None
        if self.apply_gradient:
            w, vel_w = sgd.update(xp, w, grad_w, vel_w, self.learning_rate,
                                  self.weights_decay, self.l1_vs_l2,
                                  self.gradient_moment, batch_size)
        return err_in, w, vel_w

    def numpy_run(self) -> None:
        err_in, w, vel_w = self._step(
            np, self.input.mem, self.weights.mem, self.err_output.mem,
            self.gradient_weights.mem,
            self.current_batch_size(self.err_output))
        if err_in is not None:
            self.err_input.map_invalidate()
            self.err_input.mem = err_in
        self.weights.map_invalidate()
        self.weights.mem = w
        self.gradient_weights.map_invalidate()
        self.gradient_weights.mem = vel_w

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root

        if bool(root.common.engine.get("pallas", False)):
            # forward-conv + swapped-roles grad kernels (parity path)
            from znicz_tpu.ops.pallas import deconv2d_backward
            interp = bool(root.common.engine.get("pallas_interpret", False))
            sliding, padding = self.sliding, self.padding

            def pallas_backward(xp, x, w, err_out):
                return deconv2d_backward(x, w, err_out, sliding, padding,
                                         interpret=interp)

            self._backward = pallas_backward
        else:
            # drop a stale instance override from a previous initialize
            # under engine.pallas — the flag must toggle both ways
            self.__dict__.pop("_backward", None)

        def fn(x, w, err_out, vel_w, batch_size):
            return self._step(jnp, x, w, err_out, vel_w, batch_size)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        for arr in (self.input, self.weights, self.err_output,
                    self.gradient_weights):
            arr.unmap()
        err_in, w, vel_w = self._xla_fn(
            self.input.devmem, self.weights.devmem, self.err_output.devmem,
            self.gradient_weights.devmem,
            self.current_batch_size(self.err_output))
        if err_in is not None:
            self.err_input.set_devmem(err_in)
        self.weights.set_devmem(w)
        self.gradient_weights.set_devmem(vel_w)
