"""Growable fully-connected layer — rebuild of veles.znicz
resizable_all2all.py :: ResizableAll2All.

An All2All whose output width can change between runs: ``resize(n)``
reallocates weights/bias preserving the overlapping block (existing
columns keep their trained values; new columns get fresh init)."""

from __future__ import annotations

import numpy as np

from znicz_tpu.units.all2all import All2All


class ResizableAll2All(All2All):
    """Reference: resizable_all2all.py :: ResizableAll2All."""

    MAPPING = {"resizable_all2all"}

    def resize(self, new_output: int) -> None:
        old_w = self.weights.map_read()
        n_in, old_out = old_w.shape if not self.weights_transposed else \
            old_w.shape[::-1]
        self.output_sample_shape = (int(new_output),)
        stddev = self.weights_stddev or 1.0 / np.sqrt(n_in)
        fresh = self._fill((n_in, new_output) if not self.weights_transposed
                           else (new_output, n_in),
                           self.weights_filling, stddev)
        keep = min(old_out, new_output)
        if self.weights_transposed:
            fresh[:keep, :] = old_w[:keep, :]
        else:
            fresh[:, :keep] = old_w[:, :keep]
        self.weights.map_invalidate()
        self.weights.reset(fresh)
        if self.include_bias:
            old_b = self.bias.map_read()
            fresh_b = self._fill((new_output,), self.bias_filling,
                                 self.bias_stddev or 0.01)
            fresh_b[:keep] = old_b[:keep]
            self.bias.map_invalidate()
            self.bias.reset(fresh_b)
        # output re-allocates on next initialize/run
        batch = self.output.shape[0] if self.output else None
        if batch is not None:
            self.output.reset(shape=(batch, new_output))
        if self.initialized:
            self.init_array(self.weights, self.bias, self.output)
            getattr(self, f"{self.backend_suffix}_init",
                    self.numpy_init)()
