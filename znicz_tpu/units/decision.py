"""Decision units — rebuild of veles.znicz decision.py :: DecisionBase,
DecisionGD, DecisionMSE.

Host-side epoch bookkeeping: accumulate the evaluator's per-minibatch
metrics per sample class (TEST/VALID/TRAIN), detect end of epoch, track the
best validation result, flip the ``improved`` / ``epoch_ended`` /
``complete`` Bools that gate the snapshotter/plotters and terminate the
Repeater loop (SURVEY.md §4.1).

Stop conditions (reference semantics): ``max_epochs`` reached, or no
validation improvement within the last ``fail_iterations`` epochs; plus
``target_metric`` — stop as soon as the watched metric reaches a target
(the "train to 99%" contract of BASELINE.md config 2).
"""

from __future__ import annotations

from typing import Optional


import uuid

from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TEST, VALID, TRAIN, CLASS_NAMES

#: one id per process: JSONL consumers disambiguate records when a
#: resumed run re-appends epochs an earlier (crashed) run already wrote
_RUN_ID = uuid.uuid4().hex[:12]


class DecisionBase(Unit):
    """Shared epoch bookkeeping (reference: decision.py :: DecisionBase)."""

    def __init__(self, workflow=None, max_epochs: Optional[int] = None,
                 fail_iterations: int = 100,
                 target_metric: Optional[float] = None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.target_metric = target_metric
        # data-linked from the loader:
        self.minibatch_class = TRAIN
        self.last_minibatch = False
        self.class_lengths = [0, 0, 0]
        #: data-linked to the loader; already incremented when the last train
        #: minibatch is served, so it reads as "epochs completed" here
        self.epoch_number = 0
        # flags the rest of the graph gates on:
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.epoch_ended = Bool(False)
        self.train_ended = Bool(False)
        # per-epoch accumulators / history
        self.epoch_metrics: list = [None, None, None]
        self.best_metric = None
        self.best_epoch = -1
        self.metrics_history: list[dict] = []

    # -- override points ----------------------------------------------------
    def accumulate(self, cls: int) -> None:
        """Fold the evaluator's minibatch metrics into epoch accumulators."""
        raise NotImplementedError

    def finalize_class(self, cls: int) -> float:
        """End of one class pass; return the epoch metric for that class."""
        raise NotImplementedError

    def reset_epoch(self) -> None:
        raise NotImplementedError

    # -- the control-graph callback -----------------------------------------
    def run(self) -> None:
        cls = int(self.minibatch_class)
        self.epoch_ended.set(False)
        self.improved.set(False)
        self.train_ended.set(False)
        self.accumulate(cls)
        if not self.last_minibatch:
            return
        metric = self.finalize_class(cls)
        self.epoch_metrics[cls] = metric
        if cls != TRAIN:
            return
        # ---- end of epoch (train is the last class served) ----
        self.train_ended.set(True)
        self.epoch_ended.set(True)
        # improvement is judged on validation when present, else train
        watch = VALID if self.class_lengths[VALID] > 0 else TRAIN
        watched = self.epoch_metrics[watch]
        if watched is not None and (self.best_metric is None
                                    or watched < self.best_metric):
            self.best_metric = watched
            self.best_epoch = int(self.epoch_number)
            self.improved.set(True)
        self.metrics_history.append({
            "epoch": int(self.epoch_number),
            **{f"metric_{CLASS_NAMES[c]}": self.epoch_metrics[c]
               for c in (TEST, VALID, TRAIN)
               if self.epoch_metrics[c] is not None},
        })
        self._append_metrics_jsonl()
        self.on_epoch_logged()
        if self.max_epochs is not None and \
                int(self.epoch_number) >= self.max_epochs:
            self.complete.set(True)
        if int(self.epoch_number) - self.best_epoch >= self.fail_iterations:
            self.complete.set(True)
        if self.target_metric is not None and watched is not None and \
                watched <= self.target_metric:
            self.complete.set(True)
        self.reset_epoch()

    def on_epoch_logged(self) -> None:
        pass

    def _append_metrics_jsonl(self) -> None:
        """Append the epoch record to ``root.common.metrics_file`` when
        set (SURVEY.md §6.5 "metrics to jsonl" — the machine-readable
        sibling of the console log; one JSON object per line)."""
        from znicz_tpu.core.config import root

        path = root.common.get("metrics_file", None)
        if not path:
            return
        import json

        with open(str(path), "a") as f:
            f.write(json.dumps({"workflow": self.workflow.name
                                if self.workflow else None,
                                "run_id": _RUN_ID,
                                **self.metrics_history[-1]}) + "\n")

    # -- snapshot support ---------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "best_metric": self.best_metric,
            "best_epoch": self.best_epoch,
            "metrics_history": list(self.metrics_history),
            "complete": bool(self.complete),
        }

    def load_state_dict(self, state: dict) -> None:
        self.best_metric = state["best_metric"]
        self.best_epoch = state["best_epoch"]
        self.metrics_history = list(state["metrics_history"])
        self.complete.set(state["complete"])


class DecisionGD(DecisionBase):
    """Classification decision: counts argmax errors (reference: DecisionGD).

    ``epoch_n_err_pt`` — per-class error percentage of the finished epoch;
    ``minibatch_n_err`` is data-linked to EvaluatorSoftmax.n_err.
    """

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.minibatch_n_err = 0        # linked from evaluator ("n_err")
        self.minibatch_size = 0         # linked from loader (current size)
        #: set to the EvaluatorSoftmax unit to collect + reset its confusion
        #: matrix per class pass (reference: Decision owns the per-class
        #: confusion_matrixes; the evaluator only accumulates a minibatch)
        self.evaluator = None
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]
        self.epoch_n_err_pt = [100.0, 100.0, 100.0]
        self.confusion_matrixes: list = [None, None, None]

    def accumulate(self, cls: int) -> None:
        self.epoch_n_err[cls] += int(self.minibatch_n_err)
        self.epoch_samples[cls] += int(self.minibatch_size)

    def finalize_class(self, cls: int) -> float:
        samples = max(self.epoch_samples[cls], 1)
        self.epoch_n_err_pt[cls] = 100.0 * self.epoch_n_err[cls] / samples
        ev = self.evaluator
        if ev is not None and getattr(ev, "confusion_matrix", None) is not None:
            self.confusion_matrixes[cls] = ev.confusion_matrix.copy()
            ev.confusion_matrix[:] = 0
        return float(self.epoch_n_err[cls])

    def reset_epoch(self) -> None:
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]

    def on_epoch_logged(self) -> None:
        parts = [f"epoch {int(self.epoch_number)}:"]
        for c in (TEST, VALID, TRAIN):
            if self.epoch_samples[c]:
                parts.append(f"{CLASS_NAMES[c]} err "
                             f"{self.epoch_n_err_pt[c]:.2f}%")
        if bool(self.improved):
            parts.append("*")
        self.info(" ".join(parts))


class DecisionMSE(DecisionBase):
    """Regression decision: tracks epoch mse (reference: DecisionMSE)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.minibatch_mse = 0.0        # linked from evaluator ("mse")
        self.minibatch_size = 0
        self.epoch_sse = [0.0, 0.0, 0.0]
        self.epoch_samples = [0, 0, 0]

    def accumulate(self, cls: int) -> None:
        # evaluator mse is already normalized by its batch; re-weight to sum
        self.epoch_sse[cls] += float(self.minibatch_mse) * \
            int(self.minibatch_size)
        self.epoch_samples[cls] += int(self.minibatch_size)

    def finalize_class(self, cls: int) -> float:
        return self.epoch_sse[cls] / max(self.epoch_samples[cls], 1)

    def reset_epoch(self) -> None:
        self.epoch_sse = [0.0, 0.0, 0.0]
        self.epoch_samples = [0, 0, 0]

    def on_epoch_logged(self) -> None:
        parts = [f"epoch {int(self.epoch_number)}:"]
        for c in (TEST, VALID, TRAIN):
            if self.epoch_samples[c]:
                mse = self.epoch_sse[c] / self.epoch_samples[c]
                parts.append(f"{CLASS_NAMES[c]} mse {mse:.6f}")
        if bool(self.improved):
            parts.append("*")
        self.info(" ".join(parts))
