"""Weight-diversity diagnostic — rebuild of veles.znicz diversity.py
(``get_similar_kernels`` helpers; SURVEY.md §3.1 "Diversity analysis":
detect near-duplicate conv kernels as a training-health signal).

Semantics: kernels (weight rows / conv filters flattened per output
channel) whose pairwise correlation exceeds ``threshold`` are grouped;
large groups mean the layer wastes capacity on redundant features (bad
init or a collapsed lr schedule).  One XLA GEMM computes the whole
correlation matrix — the reference loops kernel pairs on the host.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core.units import Unit


def similarity_matrix(weights: np.ndarray) -> np.ndarray:
    """(n_kernels, n_kernels) pairwise correlation of kernel vectors.

    ``weights`` is (n_kernels, fan_in) — All2All stores (in, out), conv
    stores HWIO; use :func:`kernels_of` to get this view."""
    w = jnp.asarray(weights, jnp.float32)
    w = w - w.mean(axis=1, keepdims=True)
    norm = jnp.linalg.norm(w, axis=1, keepdims=True)
    w = w / jnp.maximum(norm, 1e-12)
    return np.asarray(w @ w.T)


def kernels_of(forward) -> np.ndarray:
    """Per-output-channel kernel vectors of a forward unit's weights."""
    w = np.asarray(forward.weights.map_read())
    if w.ndim == 4:                     # conv HWIO -> (n_kernels, ky*kx*c)
        return w.reshape(-1, w.shape[3]).T
    return w.T                          # all2all (in, out) -> (out, in)


def get_similar_kernels(weights: np.ndarray,
                        threshold: float = 0.95) -> list[list[int]]:
    """Groups of kernel indices with pairwise correlation > threshold
    (reference: diversity.py :: get_similar_kernels — union-find over the
    thresholded similarity graph)."""
    sim = similarity_matrix(weights)
    n = sim.shape[0]
    parent = list(range(n))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i in range(n):
        for j in range(i + 1, n):
            if sim[i, j] > threshold:
                parent[find(i)] = find(j)
    groups: dict[int, list[int]] = {}
    for i in range(n):
        groups.setdefault(find(i), []).append(i)
    return sorted((g for g in groups.values() if len(g) > 1),
                  key=lambda g: (-len(g), g))


class Diversity(Unit):
    """Epoch-gated diagnostic unit: logs redundant-kernel groups per
    layer (wire after Decision with ``gate_skip = ~decision.epoch_ended``
    like the plotters).  Exposes ``report`` for tests/plotters."""

    def __init__(self, workflow=None, threshold: float = 0.95,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.threshold = float(threshold)
        self.forwards = []
        #: layer index -> list of duplicate groups, refreshed per run()
        self.report: dict[int, list[list[int]]] = {}

    def link_forwards(self, forwards) -> "Diversity":
        self.forwards = list(forwards)
        return self

    def run(self) -> None:
        self.report = {}
        for i, fwd in enumerate(self.forwards):
            if not getattr(fwd, "weights", None):
                continue
            groups = get_similar_kernels(kernels_of(fwd), self.threshold)
            if groups:
                self.report[i] = groups
                dup = sum(len(g) - 1 for g in groups)
                self.warning(
                    f"{fwd.name}: {dup} near-duplicate kernels "
                    f"(threshold {self.threshold}): {groups[:3]}")
