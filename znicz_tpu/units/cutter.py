"""Spatial crop unit pair — rebuild of veles.znicz cutter.py :: Cutter,
GDCutter.

Forward crops a fixed spatial window out of an NHWC batch; the gradient
routes err back by zero-padding it into the input geometry.  Registered as
layer type "cutter" for StandardWorkflow.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.units.nn_units import Forward, GradientDescentBase


class Cutter(Forward):
    """Reference: cutter.py :: Cutter (crop offset ``(y, x)``, size
    ``(h, w)``)."""

    MAPPING = {"cutter"}

    def __init__(self, workflow=None, offset=(0, 0), size=None,
                 **kwargs) -> None:
        super().__init__(workflow, include_bias=False, **kwargs)
        if size is None:
            raise ValueError("Cutter requires size=(h, w)")
        self.offset = tuple(int(v) for v in offset)
        self.size = tuple(int(v) for v in size)

    def _common_init(self, **kwargs) -> None:
        n, h, w, c = self.input.shape
        oy, ox = self.offset
        ch, cw = self.size
        if oy + ch > h or ox + cw > w:
            raise ValueError(f"crop {self.offset}+{self.size} exceeds input "
                             f"{(h, w)}")
        out_shape = (n, ch, cw, c)
        if not self.output or self.output.shape != out_shape:
            self.output.reset(shape=out_shape)
        self.init_array(self.input, self.output)

    def _crop(self, x):
        oy, ox = self.offset
        ch, cw = self.size
        return x[:, oy:oy + ch, ox:ox + cw, :]

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        return self._crop(x)

    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = np.ascontiguousarray(self._crop(self.input.mem))

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(jnp.asarray(self._crop(self.input.devmem)))


class GDCutter(GradientDescentBase):
    """Reference: cutter.py :: GDCutter — zero-pad err into input geometry."""

    MAPPING = {"cutter"}

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.offset = (0, 0)
        self.size = None

    def link_from_forward(self, forward) -> "GDCutter":
        self.link_attrs(forward, "input", "output")
        self.offset = forward.offset
        self.size = forward.size
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(shape=self.input.shape)
        self.init_array(self.err_input, self.err_output)

    def _pad(self, xp, err):
        n, h, w, c = self.input.shape
        oy, ox = self.offset
        ch, cw = self.size
        return xp.pad(err, ((0, 0), (oy, h - oy - ch),
                            (ox, w - ox - cw), (0, 0)))

    def numpy_run(self) -> None:
        self.err_input.map_invalidate()
        self.err_input.mem = self._pad(np, self.err_output.map_read())

    def xla_run(self) -> None:
        self.err_output.unmap()
        self.err_input.set_devmem(self._pad(jnp, self.err_output.devmem))
