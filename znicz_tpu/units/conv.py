"""Convolutional forward units — rebuild of veles.znicz conv.py ::
ConvolutionalBase, Conv, ConvTanh, ConvRELU, ConvStrictRELU.

NHWC activations, HWIO weights (znicz_tpu.ops.conv layout note), arbitrary
``kx/ky``, ``sliding`` stride and 4-tuple ``padding`` — the reference's
geometry, on XLA's native conv (MXU path) instead of the reference's
hand-written im2col GEMM kernels.

Weight init follows the reference: uniform/gaussian via the framework PRNG,
plus the optional ``weights_filling="gabor"`` bank for first conv layers.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.ops import activations, conv as conv_ops
from znicz_tpu.units.nn_units import Forward


def gabor_bank(ky: int, kx: int, c_in: int, n_kernels: int) -> np.ndarray:
    """Deterministic Gabor-filter bank (reference: conv.py gabor filling) —
    orientations x phases cycled across kernels, PRNG-jittered wavelength."""
    gen = prng.get()
    yy, xx = np.meshgrid(np.linspace(-1, 1, ky), np.linspace(-1, 1, kx),
                         indexing="ij")
    bank = np.empty((ky, kx, c_in, n_kernels), np.float32)
    for k in range(n_kernels):
        theta = np.pi * k / max(n_kernels, 1)
        lam = 0.8 + 0.4 * float(gen.uniform(0.0, 1.0, (1,))[0])
        psi = 0.0 if k % 2 == 0 else np.pi / 2
        xr = xx * np.cos(theta) + yy * np.sin(theta)
        yr = -xx * np.sin(theta) + yy * np.cos(theta)
        g = np.exp(-(xr ** 2 + 0.5 * yr ** 2) / 0.3) * \
            np.cos(2 * np.pi * xr / lam + psi)
        bank[:, :, :, k] = g[:, :, None] / max(np.abs(g).max(), 1e-6)
    return bank * 0.1


class Conv(Forward):
    """Linear convolution (reference: conv.py :: Conv)."""

    MAPPING = {"conv"}
    ACTIVATION = activations.LINEAR

    def __init__(self, workflow=None, n_kernels=None, kx=None, ky=None,
                 sliding=(1, 1), padding=(0, 0, 0, 0), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if None in (n_kernels, kx, ky):
            raise ValueError("Conv requires n_kernels, kx, ky")
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        self.sliding = sliding
        self.padding = padding

    # -- shapes -------------------------------------------------------------
    def output_shape_for(self, in_shape):
        n, h, w, _ = in_shape
        ky, kx, sy, sx, pt, pb, pl, pr = conv_ops.normalize_geometry(
            self.kx, self.ky, self.sliding, self.padding)
        return (n, conv_ops.out_size(h, ky, sy, pt, pb),
                conv_ops.out_size(w, kx, sx, pl, pr), self.n_kernels)

    def _common_init(self, **kwargs) -> None:
        in_shape = self.input.shape
        if len(in_shape) != 4:
            raise ValueError(f"Conv wants NHWC input, got {in_shape}")
        c_in = in_shape[3]
        if not self.weights:
            if self.weights_filling == "gabor":
                self.weights.mem = gabor_bank(self.ky, self.kx, c_in,
                                              self.n_kernels)
            else:
                # fan-in scaling, no 0.05 cap — see nn_units.init_weights
                fan_in = self.kx * self.ky * c_in
                stddev = self.weights_stddev or 1.0 / np.sqrt(fan_in)
                self.weights.mem = self._fill(
                    (self.ky, self.kx, c_in, self.n_kernels),
                    self.weights_filling, stddev)
        if self.include_bias and not self.bias:
            self.bias.mem = self._fill((self.n_kernels,), self.bias_filling,
                                       self.bias_stddev or 0.01)
        out_shape = self.output_shape_for(in_shape)
        if not self.output or self.output.shape != out_shape:
            self.output.reset(shape=out_shape)
        self.init_array(self.input, self.output, self.weights, self.bias)

    # -- fused-step protocol ------------------------------------------------
    def param_arrays(self) -> dict:
        out = {"w": self.weights}
        if self.include_bias:
            out["b"] = self.bias
        return out

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        return conv_ops.forward(jnp, x, p["w"], p.get("b"), self.sliding,
                                self.padding, self.ACTIVATION)

    # -- compute ------------------------------------------------------------
    def numpy_run(self) -> None:
        out = conv_ops.forward(np, self.input.mem, self.weights.mem,
                               self.bias.mem if self.include_bias else None,
                               self.sliding, self.padding, self.ACTIVATION)
        self.output.map_invalidate()
        self.output.mem = out

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root
        from znicz_tpu.ops import activations as act_ops

        act, sliding, padding = self.ACTIVATION, self.sliding, self.padding
        if bool(root.common.engine.get("pallas", False)):
            # hand-written implicit-im2col GEMM kernel (parity path)
            from znicz_tpu.ops.pallas import conv2d_im2col
            interp = bool(root.common.engine.get("pallas_interpret", False))

            def fn(x, w, b):
                v = conv2d_im2col(x, w, b, sliding, padding,
                                  interpret=interp)
                return act_ops.forward(jnp, act, v)
        else:
            def fn(x, w, b):
                return conv_ops.forward(jnp, x, w, b, sliding, padding, act)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(self._xla_fn(
            self.input.devmem, self.weights.devmem,
            self.bias.devmem if self.include_bias else None))


class ConvTanh(Conv):
    """Conv + LeCun tanh (reference: ConvTanh)."""
    MAPPING = {"conv_tanh"}
    ACTIVATION = activations.TANH


class ConvRELU(Conv):
    """Conv + soft ReLU log(1+e^x) (reference: ConvRELU)."""
    MAPPING = {"conv_relu"}
    ACTIVATION = activations.RELU


class ConvStrictRELU(Conv):
    """Conv + max(0, x) (reference: ConvStrictRELU)."""
    MAPPING = {"conv_str"}
    ACTIVATION = activations.STRICT_RELU


class ConvSigmoid(Conv):
    """Conv + logistic sigmoid."""
    MAPPING = {"conv_sigmoid"}
    ACTIVATION = activations.SIGMOID
