"""Pooling forward units — rebuild of veles.znicz pooling.py :: Pooling,
OffsetPooling, MaxPooling, MaxAbsPooling, AvgPooling, StochasticPooling,
StochasticAbsPooling.

Max/stochastic variants record the winner's flat input offset per output
element into ``input_offset`` (reference behavior) for the eager backward
scatter; the fused training step instead differentiates through the jnp
forward.  Stochastic variants draw from the framework PRNG (host stream for
numpy, counter-based jax keys on device — znicz_tpu.core.prng) and fall
back to the probability-weighted expectation in ``forward_mode``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.memory import Array
from znicz_tpu.ops import pooling as pool_ops
from znicz_tpu.units.nn_units import Forward


class Pooling(Forward):
    """Geometry base (reference: pooling.py :: Pooling)."""

    MAPPING: set = set()

    def __init__(self, workflow=None, kx=2, ky=2, sliding=None,
                 **kwargs) -> None:
        super().__init__(workflow, include_bias=False, **kwargs)
        self.kx, self.ky = int(kx), int(ky)
        if sliding is None:
            sliding = (self.ky, self.kx)
        self.sliding = (sliding, sliding) if isinstance(sliding, int) \
            else tuple(sliding)

    @property
    def sy(self) -> int:
        return self.sliding[0]

    @property
    def sx(self) -> int:
        return self.sliding[1]

    def output_shape_for(self, in_shape):
        n, h, w, c = in_shape
        return (n, pool_ops.pool_out_size(h, self.ky, self.sy),
                pool_ops.pool_out_size(w, self.kx, self.sx), c)

    def _common_init(self, **kwargs) -> None:
        in_shape = self.input.shape
        if len(in_shape) != 4:
            raise ValueError(f"Pooling wants NHWC input, got {in_shape}")
        out_shape = self.output_shape_for(in_shape)
        if not self.output or self.output.shape != out_shape:
            self.output.reset(shape=out_shape)
        self.init_array(self.input, self.output)


class OffsetPooling(Pooling):
    """Pooling that records winner offsets (reference: OffsetPooling)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input_offset = Array()

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        out_shape = self.output.shape
        if not self.input_offset or self.input_offset.shape != out_shape:
            self.input_offset.reset(shape=out_shape, dtype=np.int32)
        self.init_array(self.input_offset)


class MaxPooling(OffsetPooling):
    """Max pooling (reference: MaxPooling)."""

    MAPPING = {"max_pooling"}
    USE_ABS = False

    def _run(self, xp, x):
        return pool_ops.max_forward(xp, x, self.ky, self.kx, self.sy,
                                    self.sx, use_abs=self.USE_ABS)

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        # reduce_window path: identical values/gradient routing to the
        # offset-recording forward, ~50x faster on TPU (no patch gather)
        fast = pool_ops.maxabs_forward_fast if self.USE_ABS \
            else pool_ops.max_forward_fast
        return fast(x, self.ky, self.kx, self.sy, self.sx)

    def numpy_run(self) -> None:
        y, off = self._run(np, self.input.mem)
        self.output.map_invalidate()
        self.output.mem = y
        self.input_offset.map_invalidate()
        self.input_offset.mem = off

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda x: self._run(jnp, x))

    def xla_run(self) -> None:
        self.input.unmap()
        y, off = self._xla_fn(self.input.devmem)
        self.output.set_devmem(y)
        self.input_offset.set_devmem(off)


class MaxAbsPooling(MaxPooling):
    """Max-|x| pooling emitting the signed winner (reference:
    MaxAbsPooling)."""
    MAPPING = {"maxabs_pooling"}
    USE_ABS = True


class AvgPooling(Pooling):
    """Average pooling (reference: AvgPooling); border windows divide by
    the clipped element count."""

    MAPPING = {"avg_pooling"}

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        return pool_ops.avg_forward_fast(x, self.ky, self.kx, self.sy,
                                        self.sx)

    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = pool_ops.avg_forward(
            np, self.input.mem, self.ky, self.kx, self.sy, self.sx)

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda x: pool_ops.avg_forward(
            jnp, x, self.ky, self.kx, self.sy, self.sx))

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(self._xla_fn(self.input.devmem))


class StochasticPooling(OffsetPooling):
    """Stochastic pooling, winner ~ p(x_i) = x_i+ / sum (reference:
    StochasticPooling; Zeiler & Fergus 2013)."""

    MAPPING = {"stochastic_pooling"}
    USE_ABS = False
    NEEDS_RNG = True

    def _uniform_host(self, shape):
        return prng.get().uniform(0.0, 1.0, shape).astype(np.float32)

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        out_shape = self.output_shape_for(x.shape)
        if train:
            u = jax.random.uniform(rng, out_shape)
            return pool_ops.stochastic_forward_fast(
                x, u, self.ky, self.kx, self.sy, self.sx, self.USE_ABS)
        y, _ = pool_ops.stochastic_forward(
            jnp, x, self.ky, self.kx, self.sy, self.sx, None,
            self.USE_ABS, train=False)
        return y

    def numpy_run(self) -> None:
        train = not self.forward_mode
        u = self._uniform_host(self.output.shape) if train else None
        y, off = pool_ops.stochastic_forward(
            np, self.input.mem, self.ky, self.kx, self.sy, self.sx, u,
            self.USE_ABS, train=train)
        self.output.map_invalidate()
        self.output.mem = y
        if off is not None:
            self.input_offset.map_invalidate()
            self.input_offset.mem = off

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root

        self._pallas = bool(root.common.engine.get("pallas", False))
        self._pallas_interp = bool(
            root.common.engine.get("pallas_interpret", False))
        if self._pallas:
            # in-kernel-PRNG path: patches stream through the Pallas
            # kernel, the uniform is drawn per output cell on device
            from znicz_tpu.ops.pallas import stochastic_pool

            ky, kx, sy, sx = self.ky, self.kx, self.sy, self.sx
            use_abs, interp = self.USE_ABS, self._pallas_interp

            def fn(x, seed, bits):
                patch, valid, _ = pool_ops.patches(
                    jnp, x, ky, kx, sy, sx, pad_value=0.0)
                n, oh, ow, K, c = patch.shape
                vtile = jnp.broadcast_to(valid.reshape(1, oh * ow, K),
                                         (n, oh * ow, K))
                y, tap = stochastic_pool(
                    patch.reshape(n * oh * ow, K, c),
                    vtile.reshape(n * oh * ow, K),
                    seed, use_abs, bits=bits, interpret=interp)
                idx = tap.reshape(n, oh, ow, c)
                off = pool_ops.offsets_of(jnp, idx, x.shape, ky, kx, sy, sx)
                return y.reshape(n, oh, ow, c), off

            self._xla_pallas_fn = jax.jit(fn)
        self._xla_fn = jax.jit(
            lambda x, u, train: pool_ops.stochastic_forward(
                jnp, x, self.ky, self.kx, self.sy, self.sx, u,
                self.USE_ABS, train=train),
            static_argnames=("train",))

    def xla_run(self) -> None:
        self.input.unmap()
        train = not self.forward_mode
        if train and self._pallas:
            seed = int(prng.get().randint(0, 2 ** 31))
            # the interpreter's emulated TPU PRNG yields zeros: inject
            # framework-stream bits there; real TPU draws in-kernel
            bits = None
            if self._pallas_interp:
                n, oh, ow, c = self.output.shape
                bits = jnp.asarray(np.asarray(
                    prng.get().randint(0, 2 ** 32, (n * oh * ow, c)),
                    dtype=np.uint32))
            y, off = self._xla_pallas_fn(self.input.devmem, seed, bits)
            self.output.set_devmem(y)
            self.input_offset.set_devmem(off)
            return
        u = jax.random.uniform(prng.get().key(), self.output.shape) \
            if train else None
        y, off = self._xla_fn(self.input.devmem, u, train)
        self.output.set_devmem(y)
        if off is not None:
            self.input_offset.set_devmem(off)


class StochasticAbsPooling(StochasticPooling):
    """Stochastic pooling over |x| (reference: StochasticAbsPooling)."""
    MAPPING = {"stochastic_abs_pooling"}
    USE_ABS = True
