"""Divergence rollback — rebuild of veles.znicz nn_rollback.py ::
NNRollback.

Epoch-gated watchdog: on validation improvement it stores host copies of
all weights/bias/momenta ("last good"); when training diverges (NaN/inf
metric, or ``fail_iterations`` epochs without improvement) it restores the
last-good state and multiplies every gd learning rate by ``lr_cut``.
"""

from __future__ import annotations

import math

import numpy as np

from znicz_tpu.core.units import Unit


# -- shared param capture (used by NNRollback and resilience.HealthGuard) ----
def param_arrays(workflow):
    """(key, Array) pairs of every host-visible trainable buffer — the
    same inventory the snapshotter walks (weights/bias + momentum)."""
    for i, fwd in enumerate(workflow.forwards):
        for attr in ("weights", "bias"):
            # three-arg getattr: KohonenTrainer has no bias attribute
            if getattr(fwd, attr, None):
                yield f"forward.{i}.{attr}", getattr(fwd, attr)
    for i, gd in enumerate(getattr(workflow, "gds", []) or []):
        for attr in ("gradient_weights", "gradient_bias"):
            if getattr(gd, attr, None):
                yield f"gd.{i}.{attr}", getattr(gd, attr)


def capture_params(workflow) -> dict:
    """Host copy of the current trainable state (device params synced
    back first in fused workflows)."""
    step = getattr(workflow, "step", None)
    if step is not None and getattr(step, "_params", None) is not None:
        step.sync_to_units()
    return {k: np.array(arr.map_read(), copy=True)
            for k, arr in param_arrays(workflow)}


def restore_params(workflow, stored: dict) -> None:
    """Write a :func:`capture_params` copy back (and re-place it on the
    device mesh in fused workflows)."""
    for k, arr in param_arrays(workflow):
        if k in stored:
            arr.map_invalidate()
            arr.mem = stored[k].copy()
    step = getattr(workflow, "step", None)
    if step is not None and getattr(step, "_params", None) is not None:
        step._params = step.gather_params()


class NNRollback(Unit):
    """Reference: nn_rollback.py :: NNRollback."""

    def __init__(self, workflow=None, lr_cut: float = 0.5,
                 fail_iterations: int = 5, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.lr_cut = float(lr_cut)
        self.fail_iterations = int(fail_iterations)
        self.target_workflow = None
        self.decision = None
        self._good: dict[str, np.ndarray] = {}
        self._bad_epochs = 0
        self.rollback_count = 0

    def link_workflow_state(self, workflow) -> "NNRollback":
        self.target_workflow = workflow
        self.decision = workflow.decision
        return self

    # -- state capture (same array inventory as the snapshotter) ------------
    def _store_good(self) -> None:
        self._good = capture_params(self.target_workflow)

    def _restore_good(self) -> None:
        restore_params(self.target_workflow, self._good)

    def _metric_is_finite(self) -> bool:
        for m in self.decision.epoch_metrics:
            if m is not None and not math.isfinite(m):
                return False
        return True

    def run(self) -> None:
        dec = self.decision
        if not bool(dec.epoch_ended):
            return
        if bool(dec.improved) and self._metric_is_finite():
            self._store_good()
            self._bad_epochs = 0
            return
        self._bad_epochs += 1
        if not self._metric_is_finite() or \
                self._bad_epochs >= self.fail_iterations:
            self.force_rollback()

    def force_rollback(self) -> None:
        """Restore last-good state and cut the learning rates now —
        called by ``run`` on epoch-level divergence, and by the
        resilience plane's :class:`~znicz_tpu.resilience.health
        .HealthGuard` (mode="rollback") on a per-step NaN trip."""
        if self._good:
            self._restore_good()
        for gd in getattr(self.target_workflow, "gds", []) or []:
            gd.learning_rate = float(gd.learning_rate) * self.lr_cut
            gd.learning_rate_bias = \
                float(gd.learning_rate_bias) * self.lr_cut
        self._bad_epochs = 0
        self.rollback_count += 1
        self.info(f"rollback #{self.rollback_count}: restored last-good "
                  f"weights, lr cut by {self.lr_cut}")
