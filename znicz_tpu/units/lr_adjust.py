"""Learning-rate schedules — rebuild of veles.znicz lr_adjust.py ::
LearningRateAdjust + policy classes (exp, inv, step, arbitrary).

The unit sits in the control graph (after Decision) and mutates the
``learning_rate`` / ``learning_rate_bias`` of its linked gradient units.
TPU note: the fused training step reads per-layer hyperparams as traced
scalars on every call (znicz_tpu.parallel.step.hyper_params), so schedule
mutations take effect immediately without recompilation.  Exception:
in epoch-scan mode (``root.common.engine.scan_epoch``) a whole class
pass compiles into one dispatch and hyperparams are read once per pass —
per-minibatch (``by_epoch=False``) schedules coarsen to per-pass there;
per-epoch schedules are unaffected.
"""

from __future__ import annotations

from typing import Optional

from znicz_tpu.core.units import Unit


class LRPolicyBase:
    """lr = f(base_lr, iteration) (reference: lr_adjust policy objects)."""

    def __call__(self, base_lr: float, it: int) -> float:
        raise NotImplementedError


class FixedPolicy(LRPolicyBase):
    def __call__(self, base_lr, it):
        return base_lr


class ExpPolicy(LRPolicyBase):
    """lr = base * gamma^it (reference: exp policy)."""

    def __init__(self, gamma: float) -> None:
        self.gamma = gamma

    def __call__(self, base_lr, it):
        return base_lr * self.gamma ** it


class InvPolicy(LRPolicyBase):
    """lr = base * (1 + gamma*it)^-power (reference: inv policy)."""

    def __init__(self, gamma: float, power: float) -> None:
        self.gamma, self.power = gamma, power

    def __call__(self, base_lr, it):
        return base_lr * (1.0 + self.gamma * it) ** (-self.power)


class StepExpPolicy(LRPolicyBase):
    """lr = base * gamma^(it // step) (reference: step_exp policy)."""

    def __init__(self, gamma: float, step: int) -> None:
        self.gamma, self.step = gamma, step

    def __call__(self, base_lr, it):
        return base_lr * self.gamma ** (it // self.step)


class ArbitraryStepPolicy(LRPolicyBase):
    """Explicit [(lr, n_iterations), ...] table; the last entry's lr holds
    forever (reference: arbitrary_step policy)."""

    def __init__(self, table) -> None:
        self.table = [(float(lr), int(n)) for lr, n in table]

    def __call__(self, base_lr, it):
        for lr, n in self.table:
            if it < n:
                return lr
            it -= n
        return self.table[-1][0]


class LearningRateAdjust(Unit):
    """Reference: lr_adjust.py :: LearningRateAdjust.

    ``by_epoch``: step the schedule per epoch (gated on decision
    epoch_ended) instead of per minibatch.
    """

    def __init__(self, workflow=None, lr_policy: Optional[LRPolicyBase] = None,
                 bias_lr_policy: Optional[LRPolicyBase] = None,
                 by_epoch: bool = False, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.lr_policy = lr_policy or FixedPolicy()
        self.bias_lr_policy = bias_lr_policy or self.lr_policy
        self.by_epoch = by_epoch
        self.decision = None           # set when by_epoch
        self._gd_units: list = []      # (gd, base_lr, base_lr_bias)
        self._iteration = 0

    def add_gd_unit(self, gd) -> "LearningRateAdjust":
        self._gd_units.append((gd, float(gd.learning_rate),
                               float(gd.learning_rate_bias)))
        return self

    def run(self) -> None:
        if self.by_epoch and self.decision is not None and \
                not bool(self.decision.epoch_ended):
            return
        for gd, base_lr, base_lr_bias in self._gd_units:
            gd.learning_rate = self.lr_policy(base_lr, self._iteration)
            gd.learning_rate_bias = self.bias_lr_policy(base_lr_bias,
                                                        self._iteration)
        self._iteration += 1
