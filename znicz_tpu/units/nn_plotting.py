"""NN-specific plotters — rebuild of veles.znicz nn_plotting_units.py ::
Weights2D, KohonenHits, KohonenInputMaps, KohonenNeighborMap and
multi_hist.py :: MultiHistogram."""

from __future__ import annotations

import numpy as np

from znicz_tpu.plotting import Plotter


def tile_filters(w: np.ndarray, shape=None) -> np.ndarray:
    """(n_in, n_out) or HWIO conv weights -> a grid image of per-unit
    filters (reference: Weights2D layout logic)."""
    if w.ndim == 4:                         # HWIO conv bank
        ky, kx, c, n = w.shape
        tiles = [w[:, :, :, i].mean(axis=2) for i in range(n)]
    else:
        n_in, n_out = w.shape
        if shape is None:
            side = int(np.sqrt(n_in))
            shape = (side, side) if side * side == n_in else (1, n_in)
        tiles = [w[:, i].reshape(shape) for i in range(n_out)]
    n = len(tiles)
    cols = int(np.ceil(np.sqrt(n)))
    rows = int(np.ceil(n / cols))
    th, tw = tiles[0].shape
    grid = np.zeros((rows * (th + 1) - 1, cols * (tw + 1) - 1), np.float32)
    for i, t in enumerate(tiles):
        r, c = divmod(i, cols)
        lo, hi = t.min(), t.max()
        norm = (t - lo) / (hi - lo) if hi > lo else t * 0
        grid[r * (th + 1):r * (th + 1) + th,
             c * (tw + 1):c * (tw + 1) + tw] = norm
    return grid


class Weights2D(Plotter):
    """Weight-matrix tile image (reference: Weights2D); ``input`` is the
    weights Array of a forward unit."""

    def __init__(self, workflow=None, sample_shape=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = None
        self.sample_shape = sample_shape

    def redraw(self, plt, fig) -> None:
        w = np.asarray(self.input.map_read())
        grid = tile_filters(w, self.sample_shape)
        ax = fig.add_subplot(111)
        ax.imshow(grid, cmap="gray")
        ax.axis("off")


class MultiHistogram(Plotter):
    """Per-layer weight histograms, one subplot each (reference:
    multi_hist.py :: MultiHistogram); ``inputs`` = list of Arrays."""

    def __init__(self, workflow=None, n_bins: int = 40, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.inputs: list = []
        self.n_bins = n_bins

    def redraw(self, plt, fig) -> None:
        n = max(len(self.inputs), 1)
        for i, arr in enumerate(self.inputs):
            ax = fig.add_subplot(1, n, i + 1)
            ax.hist(np.asarray(arr.map_read()).ravel(), bins=self.n_bins)
            ax.set_title(f"layer {i}", fontsize=8)


class KohonenHits(Plotter):
    """SOM winner-count map (reference: KohonenHits); links ``forward`` to
    a KohonenForward unit."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.forward = None

    def redraw(self, plt, fig) -> None:
        f = self.forward
        ax = fig.add_subplot(111)
        im = ax.imshow(f.hits.reshape(f.sy, f.sx), cmap="hot")
        fig.colorbar(im)
        ax.set_title("SOM hits")


class KohonenInputMaps(Plotter):
    """Per-input-dimension SOM weight maps (reference: KohonenInputMaps);
    links ``trainer`` to the KohonenTrainer."""

    def __init__(self, workflow=None, max_maps: int = 9, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.trainer = None
        self.max_maps = max_maps

    def redraw(self, plt, fig) -> None:
        tr = self.trainer
        w = np.asarray(tr.weights.map_read())
        dims = min(w.shape[1], self.max_maps)
        cols = int(np.ceil(np.sqrt(dims)))
        rows = int(np.ceil(dims / cols))
        for d in range(dims):
            ax = fig.add_subplot(rows, cols, d + 1)
            ax.imshow(w[:, d].reshape(tr.sy, tr.sx), cmap="viridis")
            ax.axis("off")


class KohonenNeighborMap(Plotter):
    """U-matrix: mean distance of each SOM neuron to its grid neighbors
    (reference: KohonenNeighborMap)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.trainer = None

    def redraw(self, plt, fig) -> None:
        tr = self.trainer
        w = np.asarray(tr.weights.map_read()).reshape(tr.sy, tr.sx, -1)
        u = np.zeros((tr.sy, tr.sx), np.float32)
        for y in range(tr.sy):
            for x in range(tr.sx):
                dists = []
                for dy, dx in ((0, 1), (0, -1), (1, 0), (-1, 0)):
                    yy, xx = y + dy, x + dx
                    if 0 <= yy < tr.sy and 0 <= xx < tr.sx:
                        dists.append(np.linalg.norm(w[y, x] - w[yy, xx]))
                u[y, x] = np.mean(dists)
        ax = fig.add_subplot(111)
        im = ax.imshow(u, cmap="bone")
        fig.colorbar(im)
        ax.set_title("U-matrix")
