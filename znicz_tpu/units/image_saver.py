"""ImageSaver — rebuild of veles.znicz image_saver.py :: ImageSaver.

Per minibatch, collects the worst-classified (and optionally best)
samples; on epoch end dumps them as PNGs named
``{class}/{epoch}_{true}_{pred}_{score}.png`` (reference naming shape).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import CLASS_NAMES


class ImageSaver(Unit):
    """Reference: image_saver.py :: ImageSaver."""

    def __init__(self, workflow=None, directory: Optional[str] = None,
                 limit: int = 16, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.directory = directory or os.path.join(
            str(root.common.dirs.plots), "image_saver")
        self.limit = int(limit)
        # data links
        self.input = None        # loader minibatch_data Array
        self.output = None       # softmax probabilities Array
        self.labels = None       # loader minibatch_labels Array
        self.minibatch_class = 0
        self.minibatch_size = 0
        self.epoch_number = 0
        #: collected (score, img, true, pred) worst-first
        self._worst: list = []
        self.saved_paths: list[str] = []

    def run(self) -> None:
        y = np.asarray(self.output.map_read())
        # labels may live in a float32 Array (Array's default dtype)
        labels = np.asarray(self.labels.map_read()).astype(np.int64)
        x = np.asarray(self.input.map_read())
        n = int(self.minibatch_size)
        pred = y[:n].argmax(axis=1)
        true_p = y[np.arange(n), labels[:n]]
        for i in range(n):
            if pred[i] != labels[i]:
                self._worst.append((float(true_p[i]), x[i].copy(),
                                    int(labels[i]), int(pred[i])))
        self._worst.sort(key=lambda t: t[0])
        del self._worst[self.limit:]

    def flush(self) -> None:
        """Write collected samples (call on epoch end; gated in graphs)."""
        from PIL import Image
        cls_dir = os.path.join(self.directory,
                               CLASS_NAMES[int(self.minibatch_class)])
        os.makedirs(cls_dir, exist_ok=True)
        self.saved_paths = []
        for score, img, true, pred in self._worst:
            img = np.asarray(img, np.float32)
            if img.ndim == 3 and img.shape[-1] == 1:
                img = img[..., 0]
            lo, hi = img.min(), img.max()
            norm = ((img - lo) / (hi - lo) * 255 if hi > lo
                    else img * 0).astype(np.uint8)
            path = os.path.join(
                cls_dir, f"{int(self.epoch_number)}_{true}_{pred}_"
                f"{score:.3f}.png")
            Image.fromarray(norm).save(path)
            self.saved_paths.append(path)
        self._worst = []
