"""NN base classes — rebuild of veles.znicz nn_units.py :: Forward,
GradientDescentBase, MatchingObject, NNWorkflow.

``Forward`` units own weights/bias and map input -> output;
``GradientDescentBase`` units are their hand-paired duals mapping
err_output -> err_input while updating the shared weights (the reference has
no autograd — SURVEY.md §1).  ``MatchingObject`` keeps the fwd<->gd pairing
registry that ``StandardWorkflow`` uses to instantiate the backward chain
from the forward chain.

TPU notes: weights live as (in, out) for MXU-friendly GEMM (see
znicz_tpu.ops.linear); the per-unit ``xla_run`` paths exist for eager tier-1
execution, while the training hot loop fuses all units into one jitted step
(znicz_tpu.parallel.step).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.memory import Array
from znicz_tpu.core.accelerated_units import AcceleratedUnit, AcceleratedWorkflow
from znicz_tpu.ops import activations


class MatchingObject(type):
    """Metaclass keeping the forward<->gradient pairing registry.

    A class declares ``MAPPING = {"all2all", ...}``; forward classes (those
    descending from Forward) register as providers of those names, gradient
    classes (descending from GradientDescentBase) as their duals.
    Reference: veles.znicz nn_units.py :: MatchingObject.
    """

    forwards: dict[str, type] = {}
    gds: dict[str, type] = {}

    def __init__(cls, name, bases, namespace):
        super().__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if not mapping:
            return
        is_gd = any(getattr(base, "_matching_kind", None) == "gd"
                    or namespace.get("_matching_kind") == "gd"
                    for base in cls.__mro__)
        registry = MatchingObject.gds if is_gd else MatchingObject.forwards
        for key in mapping:
            registry[key] = cls

    @staticmethod
    def gd_for(forward_unit: "Forward") -> type:
        """The gradient class paired with a forward unit's MAPPING name."""
        for key in type(forward_unit).MAPPING:
            gd_cls = MatchingObject.gds.get(key)
            if gd_cls is not None:
                return gd_cls
        raise KeyError(f"no gradient unit registered for {type(forward_unit)}")


class NNLayerBase(AcceleratedUnit, metaclass=MatchingObject):
    """Shared plumbing for forward and gradient units."""

    MAPPING: set = set()


class Forward(NNLayerBase):
    """Base forward unit (reference: nn_units.py :: Forward).

    Attributes (data-linked across the graph):
    - ``input``: Array, linked from the loader or the previous forward;
    - ``output``: Array, allocated here;
    - ``weights`` / ``bias``: Arrays, allocated + initialized here, shared
      with the paired gradient unit via link_attrs.
    """

    _matching_kind = "forward"
    ACTIVATION = activations.LINEAR

    def __init__(self, workflow=None,
                 weights_filling: str = "uniform",
                 weights_stddev: Optional[float] = None,
                 bias_filling: str = "uniform",
                 bias_stddev: Optional[float] = None,
                 include_bias: bool = True,
                 weights_transposed: bool = False,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.weights_filling = weights_filling
        self.weights_stddev = weights_stddev
        self.bias_filling = bias_filling
        self.bias_stddev = bias_stddev
        self.include_bias = include_bias
        self.weights_transposed = weights_transposed
        self.input = Array()
        self.output = Array()
        self.weights = Array()
        self.bias = Array()
        #: inference mode: loader-independent forward pass (reference:
        #: forward_mode — dropout etc. switch off)
        self.forward_mode = False

    # -- weight init (reference: uniform/gaussian via veles.prng) -----------
    def _fill(self, shape, filling: str, stddev: float) -> np.ndarray:
        gen = prng.get()
        if filling == "uniform":
            bound = stddev * np.sqrt(3.0)  # uniform with this stddev
            return gen.uniform(-bound, bound, shape)
        if filling == "gaussian":
            return gen.normal(0.0, stddev, shape)
        if filling == "constant":
            return np.full(shape, stddev, dtype=np.float32)
        raise ValueError(f"unknown filling {filling!r}")

    # -- fused-step protocol (znicz_tpu.parallel.step) ----------------------
    def param_arrays(self) -> dict:
        """Trainable Arrays contributed to the fused step's params pytree;
        paramless units (pooling, dropout, ...) return {}."""
        return {}

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        """Pure jnp forward over a params leaf-dict, traced once into the
        fused training step.  ``rng`` is a per-unit per-step jax PRNG key
        (supplied when the class sets ``NEEDS_RNG``); ``train`` is a
        trace-time flag (dropout/stochastic pooling switch off for eval)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support the fused step")

    #: class flag: xla_apply consumes a PRNG key each step
    NEEDS_RNG = False

    def init_weights(self, n_input: int, n_output: int) -> None:
        if not self.weights:
            # default scale: 1/sqrt(fan_in) (LeCun/Glorot-style).  The
            # reference capped its default at 0.05, which starves deep
            # conv stacks of gradient signal and made them oscillate under
            # momentum — fan-in scaling is the deliberate deviation here
            # (verified: the MNIST conv stack cannot overfit a single
            # minibatch under the capped init, and trains cleanly without
            # the cap).  ``weights_stddev`` still overrides per layer.
            stddev = self.weights_stddev or 1.0 / np.sqrt(n_input)
            shape = ((n_output, n_input) if self.weights_transposed
                     else (n_input, n_output))
            self.weights.mem = self._fill(shape, self.weights_filling, stddev)
        if self.include_bias and not self.bias:
            # small bias init for the same stability reason (was 0.05)
            stddev = self.bias_stddev or 0.01
            self.bias.mem = self._fill((n_output,), self.bias_filling, stddev)

    def init_array(self, *arrays) -> None:
        super().init_array(*arrays)


class GradientDescentBase(NNLayerBase):
    """Base gradient-descent unit (reference: nn_units.py ::
    GradientDescentBase).

    Data links (wired by StandardWorkflow or by hand):
    - ``input``/``output``/``weights``/``bias`` from the paired forward;
    - ``err_output`` from the downstream gd's ``err_input`` (or the
      evaluator's ``err_output`` for the last layer);
    - ``batch_size`` from the loader's current (unpadded) minibatch size.

    Owns ``err_input`` plus the persistent momentum buffers
    ``gradient_weights``/``gradient_bias`` (reference names kept).
    Hyperparameters follow the reference SGD kernel semantics
    (znicz_tpu.ops.sgd).
    """

    _matching_kind = "gd"
    ACTIVATION = activations.LINEAR
    #: evaluator already produced d/d(pre-activation) (softmax+CE case)
    ACTIVATION_APPLIED = True

    def __init__(self, workflow=None,
                 learning_rate: float = 0.01,
                 learning_rate_bias: Optional[float] = None,
                 weights_decay: float = 0.0,
                 weights_decay_bias: float = 0.0,
                 l1_vs_l2: float = 0.0,
                 gradient_moment: float = 0.0,
                 gradient_moment_bias: Optional[float] = None,
                 need_err_input: bool = True,
                 apply_gradient: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.learning_rate = learning_rate
        self.learning_rate_bias = (learning_rate if learning_rate_bias is None
                                   else learning_rate_bias)
        self.weights_decay = weights_decay
        self.weights_decay_bias = weights_decay_bias
        self.l1_vs_l2 = l1_vs_l2
        self.gradient_moment = gradient_moment
        self.gradient_moment_bias = (gradient_moment if gradient_moment_bias
                                     is None else gradient_moment_bias)
        self.need_err_input = need_err_input
        self.apply_gradient = apply_gradient
        #: set by link_from_forward to match the paired forward's layout
        self.weights_transposed = False
        self.err_input = Array()
        self.err_output = Array()
        # empty defaults; paramful gd units overwrite them with data links
        # (link_attrs pops the instance attribute) — paramless ones
        # (pooling, LRN, dropout, activations) just see empty Arrays
        self.weights = Array()
        self.bias = Array()
        self.gradient_weights = Array()
        self.gradient_bias = Array()

    def _common_init(self, **kwargs) -> None:
        if self.weights and not self.gradient_weights:
            self.gradient_weights.mem = np.zeros_like(self.weights.mem)
        if self.bias and not self.gradient_bias:
            self.gradient_bias.mem = np.zeros_like(self.bias.mem)

    def numpy_init(self) -> None:
        # a re-initialize onto the numpy backend must drop any Pallas
        # ``_backward`` override a previous XLA initialize installed
        # under engine.pallas (gd/gd_conv/gd_deconv) — the numpy oracle
        # path must never run jax kernels
        self.__dict__.pop("_backward", None)

    def link_from_forward(self, forward: Forward) -> "GradientDescentBase":
        """Wire the standard data links from the paired forward unit."""
        self.link_attrs(forward, "input", "output", "weights", "bias")
        self.weights_transposed = forward.weights_transposed
        return self


class NNWorkflow(AcceleratedWorkflow):
    """Workflow with the conventional NN slots (reference: nn_units.py ::
    NNWorkflow): repeater, loader, forwards[], evaluator, decision, gds[]."""

    def __init__(self, workflow=None, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.loader = None
        self.forwards: list[Forward] = []
        self.evaluator = None
        self.decision = None
        self.gds: list[GradientDescentBase] = []
