"""RBM building blocks — rebuild of veles.znicz rbm_units.py ::
Binarization, IterationCounter, BatchWeights, GradientsCalculator,
WeightsUpdater (contrastive-divergence components of the RBM sample).

The CD-1 chain the reference's rbm sample wires from these blocks:
v0 -> (All2AllSigmoid) h0_prob -> Binarization h0 -> reconstruct v1_prob
-> h1_prob;  BatchWeights of (v0, h0_prob) and (v1_prob, h1_prob) give the
positive/negative statistics, GradientsCalculator their difference,
WeightsUpdater the momentum SGD step on the shared weights/biases.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.memory import Array
from znicz_tpu.core.accelerated_units import AcceleratedUnit
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import Unit


class Binarization(AcceleratedUnit):
    """Bernoulli-sample binary states from probabilities (reference:
    rbm_units.py :: Binarization); draws ride the framework PRNG."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = Array()
        self.output = Array()

    def _common_init(self, **kwargs) -> None:
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(shape=self.input.shape)
        self.init_array(self.input, self.output)

    def numpy_run(self) -> None:
        p = self.input.map_read()
        u = prng.get().uniform(0.0, 1.0, p.shape)
        self.output.map_invalidate()
        self.output.mem = (u < p).astype(np.float32)

    def xla_run(self) -> None:
        self.input.unmap()
        u = jax.random.uniform(prng.get().key(), self.input.shape)
        self.output.set_devmem(
            (u < self.input.devmem).astype(jnp.float32))


class IterationCounter(Unit):
    """Counts firings; ``complete`` flips at ``max_iterations``
    (reference: rbm_units.py :: IterationCounter)."""

    def __init__(self, workflow=None, max_iterations: int = 0,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.max_iterations = int(max_iterations)
        self.iteration = 0
        self.complete = Bool(False)

    def reset(self) -> None:
        self.iteration = 0
        self.complete.set(False)

    def run(self) -> None:
        self.iteration += 1
        if self.max_iterations and self.iteration >= self.max_iterations:
            self.complete.set(True)


class BatchWeights(AcceleratedUnit):
    """Associations of a (visible, hidden) pair: ``vh = vᵀh``, plus bias
    sums (reference: rbm_units.py :: BatchWeights)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.v = Array()
        self.h = Array()
        self.vh = Array()
        self.v_sum = Array()
        self.h_sum = Array()

    def _common_init(self, **kwargs) -> None:
        nv, nh = self.v.shape[1], self.h.shape[1]
        if not self.vh or self.vh.shape != (nv, nh):
            self.vh.reset(shape=(nv, nh))
            self.v_sum.reset(shape=(nv,))
            self.h_sum.reset(shape=(nh,))
        self.init_array(self.v, self.h, self.vh, self.v_sum, self.h_sum)

    @staticmethod
    def compute(xp, v, h):
        return v.T @ h, v.sum(axis=0), h.sum(axis=0)

    def numpy_run(self) -> None:
        vh, vs, hs = self.compute(np, self.v.map_read(), self.h.map_read())
        for arr, val in ((self.vh, vh), (self.v_sum, vs), (self.h_sum, hs)):
            arr.map_invalidate()
            arr.mem = val

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda v, h: self.compute(jnp, v, h))

    def xla_run(self) -> None:
        self.v.unmap()
        self.h.unmap()
        vh, vs, hs = self._xla_fn(self.v.devmem, self.h.devmem)
        self.vh.set_devmem(vh)
        self.v_sum.set_devmem(vs)
        self.h_sum.set_devmem(hs)


class GradientsCalculator(AcceleratedUnit):
    """CD gradient = (positive - negative) statistics / batch_size
    (reference: rbm_units.py :: GradientsCalculator)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.pos = None   # BatchWeights unit (data-linked)
        self.neg = None
        self.grad_weights = Array()
        self.grad_vbias = Array()
        self.grad_hbias = Array()

    def _common_init(self, **kwargs) -> None:
        if self.pos is None or self.neg is None:
            raise ValueError("GradientsCalculator needs pos/neg BatchWeights")
        if not self.grad_weights:
            self.grad_weights.reset(shape=self.pos.vh.shape)
            self.grad_vbias.reset(shape=self.pos.v_sum.shape)
            self.grad_hbias.reset(shape=self.pos.h_sum.shape)
        self.init_array(self.grad_weights, self.grad_vbias, self.grad_hbias)

    def numpy_run(self) -> None:
        bs = float(self.current_batch_size(self.pos.v))
        for out, p, n in ((self.grad_weights, self.pos.vh, self.neg.vh),
                          (self.grad_vbias, self.pos.v_sum, self.neg.v_sum),
                          (self.grad_hbias, self.pos.h_sum, self.neg.h_sum)):
            out.map_invalidate()
            out.mem = (p.map_read() - n.map_read()) / bs


class WeightsUpdater(AcceleratedUnit):
    """Momentum SGD step on the RBM parameters (reference: rbm_units.py ::
    WeightsUpdater).  ``weights`` is (nv, nh); the paired All2AllSigmoid
    units share it (v->h uses it directly, h->v transposed)."""

    def __init__(self, workflow=None, learning_rate: float = 0.1,
                 gradient_moment: float = 0.5, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.learning_rate = float(learning_rate)
        self.gradient_moment = float(gradient_moment)
        self.gradients = None    # GradientsCalculator (data-linked)
        self.weights = Array()
        self.vbias = Array()
        self.hbias = Array()
        self._vel = None

    def _common_init(self, **kwargs) -> None:
        if self._vel is None:
            self._vel = [np.zeros(a.shape, np.float32)
                         for a in (self.weights, self.vbias, self.hbias)]
        self.init_array(self.weights, self.vbias, self.hbias)

    def numpy_run(self) -> None:
        g = self.gradients
        for arr, grad, vel in zip(
                (self.weights, self.vbias, self.hbias),
                (g.grad_weights, g.grad_vbias, g.grad_hbias), self._vel):
            vel *= self.gradient_moment
            vel += self.learning_rate * grad.map_read()
            arr.map_invalidate()
            arr.mem = arr.map_read() + vel
