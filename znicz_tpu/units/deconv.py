"""Deconvolution forward unit — rebuild of veles.znicz deconv.py :: Deconv.

Transposed conv for autoencoders: input has the paired Conv's output shape
``(n, oh, ow, n_kernels)``, output its input shape ``(n, h, w, c)``.
Two usage modes (both in the reference's AE samples):

- ``link_conv_attrs(conv)``: tie geometry AND weights to an existing Conv
  (classic tied-weight autoencoder; eager shape only — the fused step
  requires each forward to own its params);
- standalone: pass ``n_kernels/kx/ky/n_channels`` and the unit owns its
  weights (StandardWorkflow's "deconv" layer type).

No bias (reference: Deconv carries none).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops import deconv as deconv_ops
from znicz_tpu.units.nn_units import Forward


class Deconv(Forward):
    """Reference: deconv.py :: Deconv."""

    MAPPING = {"deconv"}

    def __init__(self, workflow=None, n_kernels=None, kx=None, ky=None,
                 n_channels=None, sliding=(1, 1), padding=(0, 0, 0, 0),
                 **kwargs) -> None:
        super().__init__(workflow, include_bias=False, **kwargs)
        if None in (n_kernels, kx, ky):
            raise ValueError("Deconv requires n_kernels, kx, ky")
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        #: output channel count (required unless weights are tied)
        self.n_channels = None if n_channels is None else int(n_channels)
        self.sliding = sliding
        self.padding = padding
        self._tied = False

    def link_conv_attrs(self, conv) -> "Deconv":
        """Tie geometry + weights to the paired Conv (reference helper)."""
        self.n_kernels = conv.n_kernels
        self.kx, self.ky = conv.kx, conv.ky
        self.sliding = conv.sliding
        self.padding = conv.padding
        self.link_attrs(conv, "weights")
        self._tied = True
        return self

    def output_shape_for(self, in_shape):
        return deconv_ops.output_shape_for(
            in_shape, self.weights.shape, self.sliding, self.padding)

    def _common_init(self, **kwargs) -> None:
        in_shape = self.input.shape
        if len(in_shape) != 4:
            raise ValueError(f"Deconv wants NHWC input, got {in_shape}")
        if in_shape[3] != self.n_kernels:
            raise ValueError(f"Deconv input channels {in_shape[3]} != "
                             f"n_kernels {self.n_kernels}")
        if not self.weights:
            if self.n_channels is None:
                raise ValueError("standalone Deconv requires n_channels")
            fan_in = self.kx * self.ky * self.n_kernels
            stddev = self.weights_stddev or 1.0 / np.sqrt(fan_in)
            self.weights.mem = self._fill(
                (self.ky, self.kx, self.n_channels, self.n_kernels),
                self.weights_filling, stddev)
        out_shape = self.output_shape_for(in_shape)
        if not self.output or self.output.shape != out_shape:
            self.output.reset(shape=out_shape)
        self.init_array(self.input, self.output, self.weights)

    # -- fused-step protocol ------------------------------------------------
    def param_arrays(self) -> dict:
        if self._tied:
            raise RuntimeError("tied-weight Deconv is eager-only; give the "
                               "deconv its own weights for the fused step")
        return {"w": self.weights}

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        out_shape = self.output_shape_for(x.shape)
        return deconv_ops.forward(jnp, x, p["w"], self.sliding, self.padding,
                                  out_shape)

    # -- compute ------------------------------------------------------------
    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = deconv_ops.forward(
            np, self.input.mem, self.weights.mem, self.sliding, self.padding,
            self.output.shape)

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root

        sliding, padding, out_shape = \
            self.sliding, self.padding, self.output.shape
        if bool(root.common.engine.get("pallas", False)):
            # hand-written scatter-as-gather transposed conv (parity path)
            from znicz_tpu.ops.pallas import deconv2d
            interp = bool(root.common.engine.get("pallas_interpret", False))

            def fn(x, w):
                return deconv2d(x, w, sliding, padding, out_shape,
                                interpret=interp)
        else:
            def fn(x, w):
                return deconv_ops.forward(jnp, x, w, sliding, padding,
                                          out_shape)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(self._xla_fn(self.input.devmem,
                                            self.weights.devmem))
