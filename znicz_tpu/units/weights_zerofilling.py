"""Structured weight masking — rebuild of veles.znicz
weights_zerofilling.py :: ZeroFiller.

Holds a 0/1 ``mask`` per attached forward unit and re-applies
``weights *= mask`` every run (the reference used it to zero chosen weight
blocks each iteration — structured-sparsity experiments).  With the fused
step, call ``apply()`` after ``sync_to_units()`` or attach in eager mode.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.core.units import Unit


class ZeroFiller(Unit):
    """Reference: weights_zerofilling.py :: ZeroFiller."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self._targets: list = []  # (forward_unit, mask ndarray)

    def add_target(self, forward, mask: np.ndarray) -> "ZeroFiller":
        mask = np.asarray(mask, np.float32)
        if forward.weights and \
                tuple(mask.shape) != tuple(forward.weights.shape):
            raise ValueError(f"mask shape {mask.shape} != weights "
                             f"{forward.weights.shape}")
        self._targets.append((forward, mask))
        return self

    def apply(self) -> None:
        for fwd, mask in self._targets:
            w = fwd.weights.map_read()
            fwd.weights.map_invalidate()
            fwd.weights.mem = w * mask

    def run(self) -> None:
        self.apply()
