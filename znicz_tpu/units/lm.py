"""Transformer language-model step unit — wires the SPMD transformer
stack (znicz_tpu.parallel.transformer: sharded blocks, ring/flash
attention, mixed precision) into the unit graph with the same control
contract as FusedTrainStep: Repeater -> Loader -> step -> Decision.

Beyond-parity: the reference predates transformers (SURVEY.md §3.4 row
"SP/CP: NO — pre-transformer framework"); this unit is what makes the
beyond-parity stack a *workflow citizen* — epochs, validation passes,
Decision stopping, snapshot/resume — instead of a standalone demo.

XLA-only by design (like ``optimizer="adam"`` is fused-only): a numpy
transformer oracle would re-implement the whole stack for no oracle
value — parity for the math is pinned in test_transformer_spmd.py
against autograd.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.accelerated_units import AcceleratedUnit
from znicz_tpu.loader.base import TRAIN


class TransformerLMStep(AcceleratedUnit):
    """One train-or-eval step per served (tokens, labels) minibatch.

    Publishes ``minibatch_mse`` (mean CE loss per token — the DecisionMSE
    contract: a lower-is-better per-sample metric) and mirrors the fused
    step's donation/dispatch discipline: params live on device, the loss
    read is the only d2h sync per minibatch.
    """

    def __init__(self, workflow=None, loader=None, n_layers: int = 2,
                 d: int = 32, heads: int = 2, ff: Optional[int] = None,
                 lr: float = 0.1, mesh=None,
                 loss_chunks: Optional[int] = None,
                 head_sharded: bool = False,
                 n_experts: Optional[int] = None,
                 moe_aux_weight: float = 0.0,
                 moe_top_k: int = 1,
                 moe_zloss_weight: float = 0.0,
                 anatomy: Optional[bool] = None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.loader = loader
        self.n_layers = int(n_layers)
        self.d = int(d)
        self.heads = int(heads)
        self.ff = int(ff) if ff is not None else 4 * self.d
        self.lr = float(lr)
        self.mesh = mesh
        #: CE loss chunk count — set when vocab ≫ d so the (tokens,
        #: vocab) logits never materialize (docs/TUNING.md)
        self.loss_chunks = loss_chunks
        #: vocab-shard the LM head over the mesh's model axis (Megatron
        #: parallel cross-entropy; vocab must divide by tp)
        self.head_sharded = head_sharded
        #: MoE FFN blocks: expert count (sharded over the model axis),
        #: load-balance aux weight (training loss only), and routing k
        self.n_experts = n_experts
        self.moe_aux_weight = float(moe_aux_weight)
        self.moe_top_k = int(moe_top_k)
        self.moe_zloss_weight = float(moe_zloss_weight)
        if n_experts is None and (self.moe_aux_weight != 0.0 or
                                  self.moe_zloss_weight != 0.0 or
                                  self.moe_top_k != 1):
            raise ValueError(
                "moe_aux_weight/moe_zloss_weight/moe_top_k have no "
                "effect without "
                "n_experts — a dense model would train silently")
        #: step-anatomy split-dispatch mode (ISSUE 20): the train step
        #: runs as per-phase programs with host stamps feeding
        #: znicz_anatomy_*{plane="transformer"} — explicit-psum
        #: reduction semantics, see make_train_step(anatomy=True).
        #: None -> root.common.engine.step_anatomy (False).
        self.anatomy = anatomy
        self.vocab_size: Optional[int] = None
        # decision links (DecisionMSE contract)
        self.minibatch_mse = 0.0
        self.minibatch_size = 0
        self._params = None
        self._step = None
        self._eval = None

    # -- lifecycle ----------------------------------------------------------
    def numpy_init(self) -> None:
        raise NotImplementedError(
            "TransformerLMStep is XLA-only (run with -d tpu/auto); the "
            "transformer stack has no numpy oracle by design")

    def xla_init(self) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from znicz_tpu.parallel import transformer as tfm
        from znicz_tpu.parallel.mesh import make_mesh

        if self.loader is None:
            raise ValueError("TransformerLMStep needs loader=")
        self.vocab_size = int(self.loader.vocab_size)
        if self.mesh is None:
            self.mesh = make_mesh({"data": 1, "seq": 1, "model": 1})
        if self._params is None:
            self._params = tfm.init_params(
                prng.get(), self.n_layers, self.d, self.heads, self.ff,
                self.vocab_size, n_experts=self.n_experts)
        self._params = self._place_params(self._params)
        from znicz_tpu.core.config import root

        if self.anatomy is None:
            self.anatomy = bool(root.common.engine.get("step_anatomy",
                                                       False))
        # masked=True: the loader's padded tail rows (base.py static-shape
        # policy) contribute neither loss nor gradients
        self._step, _ = tfm.make_train_step(
            self.mesh, self.n_layers, self.d, self.heads, self.ff,
            self.vocab_size, lr=self.lr, masked=True,
            loss_chunks=self.loss_chunks, head_sharded=self.head_sharded,
            n_experts=self.n_experts,
            moe_aux_weight=self.moe_aux_weight,
            moe_top_k=self.moe_top_k,
            moe_zloss_weight=self.moe_zloss_weight,
            anatomy=bool(self.anatomy))
        self._eval = tfm.make_eval_loss(
            self.mesh, self.n_layers, self.d, self.heads, self.ff,
            self.vocab_size, masked=True, loss_chunks=self.loss_chunks,
            head_sharded=self.head_sharded, n_experts=self.n_experts,
            moe_top_k=self.moe_top_k)
        #: minibatch placement: batch over data, time over seq
        self._batch_sharding = NamedSharding(self.mesh, P("data", "seq"))
        self._mask_sharding = NamedSharding(self.mesh, P("data"))
        #: reused mask row — the hot loop allocates nothing per step
        self._arange = np.arange(self.loader.max_minibatch_size)

    def _stage_batch(self, tokens, labels, count: int):
        """ONE fused ``device_put``: tokens, labels and the padding mask
        ride a single staged tuple transfer instead of three separate
        H2D trips (shared by xla_run and the input-pipeline stager)."""
        import jax

        return jax.device_put(
            (tokens, labels, self._arange < count),
            (self._batch_sharding, self._batch_sharding,
             self._mask_sharding))

    def make_stager(self):
        """Producer-side staging for the input pipeline
        (znicz_tpu.pipeline): the worker issues the next batch's fused
        tuple put while the current step computes; ring-slot handoff via
        the shared ring_safe_stager (copy on the aliasing CPU backend,
        H2D fence on accelerators)."""
        import jax

        from znicz_tpu.pipeline.prefetcher import ring_safe_stager

        safe_put = ring_safe_stager(lambda t, l, m: jax.device_put(
            (t, l, m), (self._batch_sharding, self._batch_sharding,
                        self._mask_sharding)))

        def stage(rec, arrays):
            tokens, labels = arrays["data"], arrays["labels"]
            staged = safe_put(tokens, labels, self._arange < rec["size"])
            nbytes = tokens.nbytes + labels.nbytes + \
                self._arange.size  # one byte per bool mask element
            return {"lm": staged}, nbytes
        return stage

    def _place_params(self, params):
        """Mesh placement by param_specs — the ONE layout used by init
        and restore alike."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from znicz_tpu.parallel import transformer as tfm

        specs = tfm.param_specs(self.n_layers, self.head_sharded,
                                moe=bool(self.n_experts))
        return jax.device_put(
            params, jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P)))

    # -- compute ------------------------------------------------------------
    def numpy_run(self) -> None:
        self.numpy_init()

    def xla_run(self) -> None:
        import jax

        loader = self.loader
        count = int(loader.minibatch_size)
        staged = loader.take_staged() \
            if getattr(loader, "pipeline", None) is not None else None
        if staged is not None:
            # pipelined feeding: the prefetch worker already issued the
            # fused tuple put, overlapped with the previous step
            tokens, labels, mask = staged["lm"]
        elif self.anatomy:
            import time

            from znicz_tpu.observe import probe
            t0 = time.perf_counter()
            tokens, labels, mask = self._stage_batch(
                loader.minibatch_data.mem, loader.minibatch_labels.mem,
                count)
            probe.anatomy_phase("transformer", "stage",
                                time.perf_counter() - t0, t0=t0)
        else:
            tokens, labels, mask = self._stage_batch(
                loader.minibatch_data.mem, loader.minibatch_labels.mem,
                count)
        if int(self.loader.minibatch_class) == TRAIN:
            self._params, loss = self._step(self._params, tokens, labels,
                                            mask)
        else:
            loss = self._eval(self._params, tokens, labels, mask)
        self.minibatch_mse = float(jax.device_get(loss))
        self.minibatch_size = count

    # -- serving handoff (ISSUE 10) -----------------------------------------
    def export_lm(self, path: str,
                  draft_layers: int | None = None) -> str:
        """Package the trained params as a generative serving artifact
        (``utils/export.py::export_lm``): weights + architecture +
        the loader's charmap, bootable by ``python -m znicz_tpu
        generate`` into the KV-cache decode plane.  The SAME params
        that trained serve — the unified train/serve contract the serve
        plane is built on.

        ``draft_layers=k`` also ships a layer-truncated DRAFT model
        (first k blocks + the shared embedding/head) for speculative
        decoding (ISSUE 12) — the zero-extra-training proposer whose
        logits track the target's."""
        import jax

        from znicz_tpu.utils.export import export_lm

        if self._params is None:
            raise ValueError("export_lm needs an initialized workflow "
                             "(params live on device after xla_init)")
        if self.n_experts:
            raise ValueError("export_lm cannot package an MoE stack "
                             "(KV-cache decode serves dense FFN only)")
        params = jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                              self._params)
        draft = None
        if draft_layers:
            from znicz_tpu.serve.paged import truncate_draft
            draft = truncate_draft(params, draft_layers)
        charmap = list(getattr(self.loader, "vocab", []) or []) or None
        wf = getattr(self, "workflow", None)
        return export_lm(params, path, heads=self.heads, charmap=charmap,
                         name=getattr(wf, "name", None) or "char_lm",
                         draft_params=draft)

    # -- snapshot support ---------------------------------------------------
    def state_dict(self) -> dict:
        import jax

        if self._params is None:
            return {}
        return {"params": jax.tree.map(
            lambda a: np.asarray(jax.device_get(a)), self._params)}

    def load_state_dict(self, state: dict) -> None:
        if "params" not in state:
            return
        params = state["params"]
        # architecture validation — the generic snapshot restore checks
        # tree STRUCTURE; shape semantics are this unit's contract:
        restored_vocab = int(params["emb"].shape[0])
        if len(params["blocks"]) != self.n_layers or \
                int(params["emb"].shape[1]) != self.d or \
                tuple(params["head"].shape) != (self.d, restored_vocab):
            raise ValueError(
                f"snapshot params (d={params['emb'].shape[1]}, "
                f"{len(params['blocks'])} blocks) do not match this "
                f"workflow (d={self.d}, {self.n_layers} blocks)")
        # the FFN flavor is architecture too: a dense snapshot cannot
        # restore into an MoE workflow (or vice versa), and the expert
        # count must match — the params pytree would otherwise win
        # silently over the configured architecture
        blk0 = params["blocks"][0]
        snap_experts = int(blk0["ew1"].shape[0]) if "ew1" in blk0 else None
        if snap_experts != (self.n_experts or None):
            raise ValueError(
                f"snapshot FFN flavor (n_experts={snap_experts}) does "
                f"not match this workflow (n_experts={self.n_experts})")
        # vocab must match what the loader SERVES NOW — after a restore
        # the loader has adopted the snapshot vocab (CharSequenceLoader
        # snapshots it), so a mismatch means a genuinely different corpus
        live_vocab = int(self.loader.vocab_size) \
            if self.loader is not None else self.vocab_size
        if live_vocab and restored_vocab != live_vocab:
            raise ValueError(
                f"snapshot params carry vocab {restored_vocab} but the "
                f"loader serves vocab {live_vocab} — the corpus does not "
                f"match the snapshot")
        self.vocab_size = restored_vocab
        if self._step is not None:
            # already initialized: only re-place the arrays onto the
            # mesh — the compiled step/eval stay valid
            params = self._place_params(params)
        self._params = params
