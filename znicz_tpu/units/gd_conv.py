"""Convolutional gradient units — rebuild of veles.znicz gd_conv.py ::
GradientDescentConv, GDTanhConv, GDRELUConv, GDStrictRELUConv.

The reference's hardest kernels (col2im overlap-scatter with atomics —
SURVEY.md §3.2) map to ``jax.vjp`` of the XLA conv: the compiler emits the
transposed conv + patch-GEMM pair natively.  The numpy path is the explicit
im2col/col2im oracle (znicz_tpu.ops.conv).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops import activations, conv as conv_ops, sgd
from znicz_tpu.units.nn_units import GradientDescentBase


class GradientDescentConv(GradientDescentBase):
    """Gradient for Conv (reference: gd_conv.py :: GradientDescentConv)."""

    MAPPING = {"conv"}
    ACTIVATION = activations.LINEAR

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        # geometry is data-linked from the paired forward (link_conv_attrs)
        self.sliding = (1, 1)
        self.padding = (0, 0, 0, 0)

    def link_from_forward(self, forward) -> "GradientDescentConv":
        super().link_from_forward(forward)
        self.sliding = forward.sliding
        self.padding = forward.padding
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(shape=self.input.shape)
        self.init_array(self.err_input, self.err_output,
                        self.gradient_weights, self.gradient_bias)

    def _backward(self, xp, x, y, w, err_out):
        return conv_ops.backward(
            xp, x, y, w, err_out, self.sliding, self.padding,
            self.ACTIVATION, activation_applied=True)

    def _step(self, xp, x, y, w, b, err_out, vel_w, vel_b, batch_size):
        err_in, grad_w, grad_b = self._backward(xp, x, y, w, err_out)
        if not self.need_err_input:
            err_in = None
        if self.apply_gradient:
            w, vel_w = sgd.update(xp, w, grad_w, vel_w, self.learning_rate,
                                  self.weights_decay, self.l1_vs_l2,
                                  self.gradient_moment, batch_size)
            if b is not None:
                b, vel_b = sgd.update(xp, b, grad_b, vel_b,
                                      self.learning_rate_bias,
                                      self.weights_decay_bias, self.l1_vs_l2,
                                      self.gradient_moment_bias, batch_size)
        return err_in, w, b, vel_w, vel_b

    def numpy_run(self) -> None:
        has_bias = bool(self.bias)
        err_in, w, b, vel_w, vel_b = self._step(
            np, self.input.mem, self.output.mem, self.weights.mem,
            self.bias.mem if has_bias else None, self.err_output.mem,
            self.gradient_weights.mem,
            self.gradient_bias.mem if has_bias else None,
            self.current_batch_size(self.err_output))
        if err_in is not None:
            self.err_input.map_invalidate()
            self.err_input.mem = err_in
        self.weights.map_invalidate()
        self.weights.mem = w
        self.gradient_weights.map_invalidate()
        self.gradient_weights.mem = vel_w
        if has_bias:
            self.bias.map_invalidate()
            self.bias.mem = b
            self.gradient_bias.map_invalidate()
            self.gradient_bias.mem = vel_b

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root

        if bool(root.common.engine.get("pallas", False)):
            # hand-written col2im-as-gather + transposed-tap-GEMM pair
            # (parity path; XLA's vjp conv is the default)
            from znicz_tpu.ops.pallas import conv2d_backward
            interp = bool(root.common.engine.get("pallas_interpret", False))
            act, sliding, padding = \
                self.ACTIVATION, self.sliding, self.padding

            def pallas_backward(xp, x, y, w, err_out):
                err_v = activations.backward(jnp, act, y, err_out)
                return conv2d_backward(x, w, err_v, sliding, padding,
                                       interpret=interp)

            self._backward = pallas_backward
        else:
            # drop a stale instance override from a previous initialize
            # under engine.pallas — the flag must toggle both ways
            self.__dict__.pop("_backward", None)

        def fn(x, y, w, b, err_out, vel_w, vel_b, batch_size):
            return self._step(jnp, x, y, w, b, err_out, vel_w, vel_b,
                              batch_size)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        has_bias = bool(self.bias)
        for arr in (self.input, self.output, self.weights, self.err_output,
                    self.gradient_weights):
            arr.unmap()
        err_in, w, b, vel_w, vel_b = self._xla_fn(
            self.input.devmem, self.output.devmem, self.weights.devmem,
            self.bias.devmem if has_bias else None, self.err_output.devmem,
            self.gradient_weights.devmem,
            self.gradient_bias.devmem if has_bias else None,
            self.current_batch_size(self.err_output))
        if err_in is not None:
            self.err_input.set_devmem(err_in)
        self.weights.set_devmem(w)
        self.gradient_weights.set_devmem(vel_w)
        if has_bias:
            self.bias.set_devmem(b)
            self.gradient_bias.set_devmem(vel_b)


class GDTanhConv(GradientDescentConv):
    """Gradient for ConvTanh (reference: GDTanhConv)."""
    MAPPING = {"conv_tanh"}
    ACTIVATION = activations.TANH


class GDRELUConv(GradientDescentConv):
    """Gradient for ConvRELU (reference: GDRELUConv)."""
    MAPPING = {"conv_relu"}
    ACTIVATION = activations.RELU


class GDStrictRELUConv(GradientDescentConv):
    """Gradient for ConvStrictRELU (reference: GDStrictRELUConv)."""
    MAPPING = {"conv_str"}
    ACTIVATION = activations.STRICT_RELU


class GDSigmoidConv(GradientDescentConv):
    """Gradient for ConvSigmoid."""
    MAPPING = {"conv_sigmoid"}
    ACTIVATION = activations.SIGMOID
