"""Fully-connected forward units — rebuild of veles.znicz all2all.py ::
All2All, All2AllTanh, All2AllRELU, All2AllStrictRELU, All2AllSigmoid,
All2AllSoftmax.

y = act(x·W + b) over ``znicz_tpu.ops.linear``; the Softmax variant also
emits ``max_idx`` per row for EvaluatorSoftmax (reference behavior).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core.memory import Array
from znicz_tpu.ops import activations, linear
from znicz_tpu.units.nn_units import Forward


class All2All(Forward):
    """Linear fully-connected layer (reference: all2all.py :: All2All)."""

    MAPPING = {"all2all"}
    ACTIVATION = activations.LINEAR

    def __init__(self, workflow=None, output_sample_shape=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if output_sample_shape is None:
            raise ValueError("All2All requires output_sample_shape")
        self.output_sample_shape = (
            (output_sample_shape,) if isinstance(output_sample_shape, int)
            else tuple(output_sample_shape))

    # -- shapes -------------------------------------------------------------
    @property
    def n_input(self) -> int:
        return int(np.prod(self.input.shape[1:]))

    @property
    def n_output(self) -> int:
        return int(np.prod(self.output_sample_shape))

    def _common_init(self, **kwargs) -> None:
        batch = self.input.shape[0]
        self.init_weights(self.n_input, self.n_output)
        if not self.output or self.output.shape[0] != batch:
            self.output.reset(shape=(batch,) + self.output_sample_shape)
        self.init_array(self.input, self.output, self.weights, self.bias)

    # -- weights view (honoring weights_transposed on the stored layout) ----
    def _w(self, xp):
        w = self.weights.mem if xp is np else self.weights.devmem
        return w.T if self.weights_transposed else w

    def _b(self, xp):
        if not self.include_bias:
            return None
        return self.bias.mem if xp is np else self.bias.devmem

    # -- fused-step protocol (znicz_tpu.parallel.step) ----------------------
    def param_arrays(self) -> dict:
        """Trainable Arrays contributed to the fused step's params pytree."""
        out = {"w": self.weights}
        if self.include_bias:
            out["b"] = self.bias
        return out

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        """Pure jnp forward over a params leaf-dict (traced once into the
        fused training step)."""
        return activations.forward(jnp, self.ACTIVATION,
                                   self.xla_apply_linear(p, x))

    def xla_apply_linear(self, p: dict, x):
        """Pre-activation part only (the fused softmax+CE path composes
        log_softmax into the loss for numerical stability)."""
        w = p["w"].T if self.weights_transposed else p["w"]
        return linear.forward(jnp, x, w, p.get("b"), activations.LINEAR)

    # -- compute ------------------------------------------------------------
    def numpy_run(self) -> None:
        out = linear.forward(np, self.input.mem, self._w(np), self._b(np),
                             self.ACTIVATION)
        self.output.map_invalidate()
        self.output.mem = out.reshape((-1,) + self.output_sample_shape)

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root

        act = self.ACTIVATION
        shape = (-1,) + self.output_sample_shape
        if bool(root.common.engine.get("pallas", False)):
            # blocked-GEMM kernel with fused bias+activation (parity
            # path — the reference's all2all/forward kernel)
            from znicz_tpu.ops.pallas import gemm
            interp = bool(root.common.engine.get("pallas_interpret", False))

            def fn(x, w, b):
                return gemm.fc_forward(x, w, b, act,
                                       interpret=interp).reshape(shape)
        else:
            def fn(x, w, b):
                return linear.forward(jnp, x, w, b, act).reshape(shape)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(self._xla_fn(
            self.input.devmem, self._w(jnp), self._b(jnp)))


class All2AllTanh(All2All):
    """FC + LeCun-scaled tanh (reference: All2AllTanh)."""
    MAPPING = {"all2all_tanh"}
    ACTIVATION = activations.TANH


class All2AllRELU(All2All):
    """FC + soft ReLU log(1+e^x) (reference: All2AllRELU)."""
    MAPPING = {"all2all_relu"}
    ACTIVATION = activations.RELU


class All2AllStrictRELU(All2All):
    """FC + max(0, x) (reference: All2AllStrictRELU)."""
    MAPPING = {"all2all_str"}
    ACTIVATION = activations.STRICT_RELU


class All2AllSigmoid(All2All):
    """FC + logistic sigmoid (reference: All2AllSigmoid)."""
    MAPPING = {"all2all_sigmoid"}
    ACTIVATION = activations.SIGMOID


class All2AllSoftmax(All2All):
    """FC + softmax, emitting per-row argmax into ``max_idx``
    (reference: All2AllSoftmax with apply_exp kernel)."""

    MAPPING = {"softmax"}
    ACTIVATION = "softmax"

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.max_idx = Array()

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        return jax.nn.softmax(self.xla_apply_linear(p, x), axis=1)

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.max_idx or self.max_idx.shape[0] != self.output.shape[0]:
            self.max_idx.reset(shape=(self.output.shape[0],), dtype=np.int32)
        self.init_array(self.max_idx)

    def numpy_run(self) -> None:
        y, idx = linear.softmax_forward(np, self.input.mem, self._w(np),
                                        self._b(np))
        self.output.map_invalidate()
        self.output.mem = y.reshape((-1,) + self.output_sample_shape)
        self.max_idx.map_invalidate()
        self.max_idx.mem = idx.astype(np.int32)

    def xla_init(self) -> None:
        def fn(x, w, b):
            y, idx = linear.softmax_forward(jnp, x, w, b)
            return y, idx.astype(jnp.int32)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        self.input.unmap()
        y, idx = self._xla_fn(self.input.devmem, self._w(jnp), self._b(jnp))
        self.output.set_devmem(y)
        self.max_idx.set_devmem(idx)
