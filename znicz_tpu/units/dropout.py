"""Dropout units — rebuild of veles.znicz dropout.py :: DropoutForward,
DropoutBackward.

Forward draws a Bernoulli mask (keep prob ``1 - dropout_ratio``) from the
framework PRNG and scales kept activations by ``1/(1-p)`` (reference
semantics: the mask Array holds 0 or 1/(1-p) and the backward reuses it).
Disabled in ``forward_mode`` (inference) — identity.  The reference's
device xorshift128+ mask generator maps to counter-based ``jax.random``
keys (znicz_tpu.core.prng :: RandomGenerator.key).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.memory import Array
from znicz_tpu.ops.dropout import make_mask
from znicz_tpu.units.nn_units import Forward, GradientDescentBase


class DropoutForward(Forward):
    """Reference: DropoutForward (attribute ``dropout_ratio`` = drop prob)."""

    MAPPING = {"dropout"}
    NEEDS_RNG = True

    def __init__(self, workflow=None, dropout_ratio=0.5, **kwargs) -> None:
        super().__init__(workflow, include_bias=False, **kwargs)
        self.dropout_ratio = float(dropout_ratio)
        self.mask = Array()

    def _common_init(self, **kwargs) -> None:
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(shape=self.input.shape)
        if not self.mask or self.mask.shape != self.input.shape:
            self.mask.reset(shape=self.input.shape)
        self.init_array(self.input, self.output, self.mask)

    def _make_mask_np(self, shape):
        u = prng.get().uniform(0.0, 1.0, shape)
        return make_mask(np, u, self.dropout_ratio, np.float32)

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        if not train or self.dropout_ratio == 0.0:
            return x
        return x * make_mask(jnp, jax.random.uniform(rng, x.shape),
                             self.dropout_ratio, x.dtype)

    def numpy_run(self) -> None:
        x = self.input.mem
        self.output.map_invalidate()
        if self.forward_mode or self.dropout_ratio == 0.0:
            self.output.mem = x
            return
        mask = self._make_mask_np(x.shape)
        self.mask.map_invalidate()
        self.mask.mem = mask
        self.output.mem = x * mask

    def xla_init(self) -> None:
        ratio = self.dropout_ratio

        def fn(x, key):
            mask = make_mask(jnp, jax.random.uniform(key, x.shape), ratio,
                             x.dtype)
            return x * mask, mask

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        self.input.unmap()
        if self.forward_mode or self.dropout_ratio == 0.0:
            self.output.set_devmem(self.input.devmem)
            return
        y, mask = self._xla_fn(self.input.devmem, prng.get().key())
        self.output.set_devmem(y)
        self.mask.set_devmem(mask)


class DropoutBackward(GradientDescentBase):
    """Reference: DropoutBackward — err * mask (mask already holds the
    1/(1-p) scale)."""

    MAPPING = {"dropout"}

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.mask = Array()  # linked from the forward

    def link_from_forward(self, forward) -> "DropoutBackward":
        self.link_attrs(forward, "input", "output", "mask")
        self.forward_unit = forward
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.err_output.shape:
            self.err_input.reset(shape=self.err_output.shape)
        self.init_array(self.err_input, self.err_output)

    def numpy_run(self) -> None:
        e = self.err_output.map_read()
        self.err_input.map_invalidate()
        if not self.mask:
            self.err_input.mem = e
            return
        self.err_input.mem = e * self.mask.map_read()

    def xla_run(self) -> None:
        if not self.mask:
            self.err_output.unmap()
            self.err_input.set_devmem(self.err_output.devmem)
            return
        for arr in (self.err_output, self.mask):
            arr.unmap()
        self.err_input.set_devmem(self.err_output.devmem * self.mask.devmem)
