"""Pooling gradient units — rebuild of veles.znicz gd_pooling.py ::
GDPooling, GDMaxPooling, GDMaxAbsPooling, GDAvgPooling (+ the stochastic
variants share the offset-scatter backward).

Max/stochastic: scatter err through the offsets the forward recorded;
avg: spread err uniformly over each (clipped) window.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core.memory import Array
from znicz_tpu.ops import pooling as pool_ops
from znicz_tpu.units.nn_units import GradientDescentBase


class GDPooling(GradientDescentBase):
    """Geometry base (reference: gd_pooling.py :: GDPooling)."""

    MAPPING: set = set()

    def __init__(self, workflow=None, kx=2, ky=2, sliding=None,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.kx, self.ky = int(kx), int(ky)
        if sliding is None:
            sliding = (self.ky, self.kx)
        self.sliding = (sliding, sliding) if isinstance(sliding, int) \
            else tuple(sliding)

    @property
    def sy(self) -> int:
        return self.sliding[0]

    @property
    def sx(self) -> int:
        return self.sliding[1]

    def link_from_forward(self, forward) -> "GDPooling":
        self.link_attrs(forward, "input", "output")
        self.kx, self.ky = forward.kx, forward.ky
        self.sliding = forward.sliding
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(shape=self.input.shape)
        self.init_array(self.err_input, self.err_output)


class GDMaxPooling(GDPooling):
    """Backward through recorded winner offsets (reference:
    GDMaxPooling)."""

    MAPPING = {"max_pooling"}

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input_offset = Array()  # linked from the forward

    def link_from_forward(self, forward) -> "GDMaxPooling":
        super().link_from_forward(forward)
        self.link_attrs(forward, "input_offset")
        return self

    def numpy_run(self) -> None:
        err_in = pool_ops.scatter_backward(
            np, self.err_output.map_read(), self.input_offset.map_read(),
            self.input.shape)
        self.err_input.map_invalidate()
        self.err_input.mem = err_in

    def xla_init(self) -> None:
        in_shape = tuple(self.input.shape)
        self._xla_fn = jax.jit(
            lambda e, off: pool_ops.scatter_backward(jnp, e, off, in_shape))

    def xla_run(self) -> None:
        for arr in (self.err_output, self.input_offset):
            arr.unmap()
        self.err_input.set_devmem(self._xla_fn(
            self.err_output.devmem, self.input_offset.devmem))


class GDMaxAbsPooling(GDMaxPooling):
    """Reference: GDMaxAbsPooling — same scatter."""
    MAPPING = {"maxabs_pooling"}


class GDStochasticPooling(GDMaxPooling):
    """Stochastic pooling backward = scatter to the sampled winner."""
    MAPPING = {"stochastic_pooling"}


class GDStochasticAbsPooling(GDMaxPooling):
    MAPPING = {"stochastic_abs_pooling"}


class GDAvgPooling(GDPooling):
    """Uniform spread backward (reference: GDAvgPooling)."""

    MAPPING = {"avg_pooling"}

    def numpy_run(self) -> None:
        err_in = pool_ops.avg_backward(
            np, self.err_output.map_read(), self.input.shape,
            self.ky, self.kx, self.sy, self.sx)
        self.err_input.map_invalidate()
        self.err_input.mem = err_in

    def xla_init(self) -> None:
        in_shape = tuple(self.input.shape)
        self._xla_fn = jax.jit(
            lambda e: pool_ops.avg_backward(jnp, e, in_shape, self.ky,
                                            self.kx, self.sy, self.sx))

    def xla_run(self) -> None:
        self.err_output.unmap()
        self.err_input.set_devmem(self._xla_fn(self.err_output.devmem))
