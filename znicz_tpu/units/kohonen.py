"""Kohonen SOM units — rebuild of veles.znicz kohonen.py :: KohonenBase,
KohonenForward, KohonenTrainer (+ the sample's decision logic).

Unsupervised winner-take-all with Gaussian neighborhood decay; no gradient
pair (SURVEY.md §3.1).  ``KohonenTrainer`` owns the ``(sy*sx, n_input)``
weights and performs the batched update (znicz_tpu.ops.kohonen);
``KohonenForward`` emits winner indices (and hit counts) using the shared
weights.  ``KohonenDecision`` stops on max_epochs or when the epoch weight
delta stabilizes.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.memory import Array
from znicz_tpu.core.accelerated_units import AcceleratedUnit
from znicz_tpu.ops import kohonen as k_ops
from znicz_tpu.units.decision import DecisionBase


def _som_batch_step(x, w, coords, alpha, radius, bs, *, pallas: bool,
                    interpret: bool):
    """THE one SOM batch-update rule — shared by the per-minibatch jit
    and the epoch scan so the two modes cannot drift."""
    if pallas:
        from znicz_tpu.ops.pallas import som_step
        new_w, idx = som_step(x, w, coords, alpha, radius, bs,
                              interpret=interpret)
        return new_w, idx.astype(jnp.int32)
    mask = jnp.arange(x.shape[0]) < bs
    new_w, idx = k_ops.update(jnp, x, w, coords, alpha, radius, mask)
    return new_w, idx.astype(jnp.int32)


_som_batch_step_jit = jax.jit(_som_batch_step,
                              static_argnames=("pallas", "interpret"))


@jax.jit
def _winners_jit(x, w):
    """Winner indices per sample — module-level (ISSUE 7 satellite):
    the previous per-``xla_init`` ``jax.jit(lambda ...)`` gave every
    KohonenForward build a fresh empty trace cache, so repeated builds
    in one process (supervised restarts, warm-up-then-time benches,
    forge reloads) re-traced and re-looked-up a program jit already
    had.  One module-level jitted function memoizes per (shape, dtype)
    for the life of the process — the same fix ``_epoch_scan`` records
    for the scan path — and the persistent compilation cache
    (znicz_tpu.compilecache) carries the compile across processes."""
    return k_ops.winners(jnp, x, w).astype(jnp.int32)


@partial(jax.jit, static_argnames=("pallas", "interpret"))
def _epoch_scan(dataset, w, coords, idxs, ms, alpha, radius, *,
                pallas: bool, interpret: bool):
    """One compiled class pass over the pinned dataset PLUS the decision
    metric ``|ΔW|/|W|`` in the same dispatch — the per-epoch host round
    trip is then a single scalar fetch.  Module-level (not a per-workflow
    closure) so jit's in-process cache carries across workflow builds:
    a warm-up build genuinely warms the timed build (the closure version
    re-traced per build, and on hardware the re-trace + persistent-cache
    reload dominated the whole measured SOM run — docs/BENCH_LOG.md)."""
    def body(wc, inp):
        idx, m = inp
        new_w, _ = _som_batch_step(dataset[idx], wc, coords, alpha,
                                   radius, m.sum(), pallas=pallas,
                                   interpret=interpret)
        return new_w, None

    new_w, _ = jax.lax.scan(body, w, (idxs, ms))
    delta = jnp.abs(new_w - w).sum() / jnp.maximum(
        jnp.abs(w).sum(), 1e-12)
    return new_w, delta


class KohonenBase(AcceleratedUnit):
    """Shared geometry (reference: kohonen.py :: KohonenBase)."""

    def __init__(self, workflow=None, shape=(8, 8), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.sy, self.sx = int(shape[0]), int(shape[1])
        self.input = Array()
        self.weights = Array()

    @property
    def n_neurons(self) -> int:
        return self.sy * self.sx

    def _flat_input(self, mem):
        return mem.reshape(mem.shape[0], -1)


class KohonenTrainer(KohonenBase):
    """Reference: kohonen.py :: KohonenTrainer.

    ``gradient_decay``/``radius_decay``: per-epoch multiplicative decay of
    the learning rate and neighborhood radius (reference semantics of the
    time-decaying schedules)."""

    def __init__(self, workflow=None, shape=(8, 8), alpha: float = 0.5,
                 alpha_min: float = 0.01, gradient_decay: float = 0.95,
                 radius: float = None, radius_min: float = 0.5,
                 radius_decay: float = 0.95, **kwargs) -> None:
        super().__init__(workflow, shape=shape, **kwargs)
        self.alpha0 = float(alpha)
        self.alpha_min = float(alpha_min)
        self.gradient_decay = float(gradient_decay)
        self.radius0 = float(radius if radius is not None
                             else max(self.sy, self.sx) / 2.0)
        self.radius_min = float(radius_min)
        self.radius_decay = float(radius_decay)
        self.epoch_number = 0            # data-linked from the loader
        self.epoch_ended = False         # data-linked from the loader
        self.winners = Array()
        self._coords_np = None
        #: optional loader reference enabling epoch-scan mode: ONE
        #: compiled lax.scan dispatch per class pass over the HBM-pinned
        #: dataset, instead of one dispatch per minibatch (the same
        #: design as FusedTrainStep epoch scanning; per-minibatch
        #: dispatch latency dominates SOM steps).  Resolved from
        #: ``root.common.engine.scan_epoch`` at xla_init when None.
        self.loader = None
        self.scan_epoch = None
        self._scan_fn = None
        self._dataset_dev = None
        self._coords_dev = None
        self._scan_in_flight = False  # current class pass scan-dispatched
        #: device scalar |ΔW|/|W| of the last scan-dispatched pass —
        #: KohonenDecision fetches it (ONE d2h fence per epoch) instead
        #: of reading full weights twice
        self.scan_delta_dev = None
        #: weights as of the START of the current epoch (consumed by
        #: KohonenDecision's |ΔW| metric on the PER-MINIBATCH path —
        #: its own capture point runs after this unit, which would miss
        #: the first minibatch's movement).  Scan mode never populates
        #: it: the delta rides the dispatch as ``scan_delta_dev``
        self.epoch_start_weights = None
        self._snap_epoch = None

    @property
    def _schedule_epoch(self) -> int:
        """The epoch the CURRENT minibatch belongs to.  The loader
        increments ``epoch_number`` while serving the last minibatch of
        an epoch (before this unit runs on it), so the raw counter would
        decay the schedule one minibatch early each epoch."""
        e = int(self.epoch_number)
        if bool(getattr(self, "epoch_ended", False)):
            e = max(e - 1, 0)
        return e

    # current schedule values (read by tests/plotters)
    @property
    def alpha(self) -> float:
        return max(self.alpha0 * self.gradient_decay ** self._schedule_epoch,
                   self.alpha_min)

    @property
    def radius(self) -> float:
        return max(self.radius0 * self.radius_decay ** self._schedule_epoch,
                   self.radius_min)

    def _common_init(self, **kwargs) -> None:
        dim = int(np.prod(self.input.shape[1:]))
        if not self.weights:
            self.weights.mem = prng.get().normal(
                0.0, 0.1, (self.n_neurons, dim))
        if not self.winners or len(self.winners) != self.input.shape[0]:
            self.winners.reset(shape=(self.input.shape[0],), dtype=np.int32)
        self._coords_np = np.asarray(k_ops.grid_coords(np, self.sy, self.sx))
        self.init_array(self.input, self.weights, self.winners)

    def _maybe_snapshot_epoch_start(self) -> None:
        e = self._schedule_epoch
        if self._snap_epoch != e:
            self.epoch_start_weights = np.asarray(
                self.weights.map_read()).copy()
            self._snap_epoch = e

    def numpy_run(self) -> None:
        self._maybe_snapshot_epoch_start()
        x = self._flat_input(self.input.mem)
        mask = self._mask(x.shape[0])
        new_w, idx = k_ops.update(np, x, self.weights.mem, self._coords_np,
                                  self.alpha, self.radius, mask)
        self.weights.map_invalidate()
        self.weights.mem = new_w
        self.winners.map_invalidate()
        self.winners.mem = idx.astype(np.int32)

    def _mask(self, n):
        bs = self.current_batch_size(self.input)
        if bs >= n:
            return None
        return (np.arange(n) < bs)

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root

        coords = jnp.asarray(self._coords_np)
        self._coords_dev = coords
        # pallas=True selects the fused distance+argmin+update kernel:
        # weights read and written once per batch step
        self._use_pallas = bool(root.common.engine.get("pallas", False))
        self._interp = bool(root.common.engine.get("pallas_interpret",
                                                   False))
        self._xla_fn = partial(_som_batch_step_jit,
                               pallas=self._use_pallas,
                               interpret=self._interp)
        self._maybe_enable_scan()

    def _maybe_enable_scan(self) -> None:
        """Pin the loader's full-batch dataset on device and compile the
        per-class-pass scan (one dispatch per pass; class-plan padding
        sits at the tail, so the per-step ``bs`` mask stays valid)."""
        from znicz_tpu.core.config import root

        if self.scan_epoch is None:
            self.scan_epoch = bool(root.common.engine.get("scan_epoch",
                                                          False))
        loader = self.loader
        data_arr = getattr(loader, "original_data", None)
        if not self.scan_epoch or loader is None or not data_arr:
            return
        if getattr(loader, "augmenting", False):
            # per-serve augmentation is data-dependent: the pinned-scan
            # shortcut would silently train on the raw uncropped dataset
            # (same guard as FusedTrainStep._pin_dataset)
            return
        data = np.asarray(data_arr.mem, np.float32)
        data = data.reshape(data.shape[0], -1)
        limit = int(root.common.engine.get(
            "dataset_on_device_max_bytes", 1 << 30))
        if data.nbytes > limit:
            return
        self._dataset_dev = jnp.asarray(data)
        self._scan_fn = partial(_epoch_scan, pallas=self._use_pallas,
                                interpret=self._interp)
        loader.capture_class_plan = True
        # NOTE: the loader keeps filling minibatch_data — KohonenForward
        # (winner maps / hits plotters) and the mid-pass-resume fallback
        # below read it; SOM minibatches are small, so the per-step host
        # fill is not the bottleneck the scan removes (dispatch latency)

    def xla_run(self) -> None:
        if self._scan_fn is not None and \
                (int(self.loader.minibatch_offset) == 0 or
                 self._scan_in_flight):
            # epoch-scan mode: dispatch the WHOLE class pass at its first
            # minibatch; later minibatches of the pass are no-ops (the
            # control loop still walks them — the loader serves cheaply).
            # ``winners`` is not updated per minibatch here; winner maps
            # come from KohonenForward as in the demo graph.
            if int(self.loader.minibatch_offset) == 0:
                from znicz_tpu.loader.base import plan_device_arrays
                idxs, ms = plan_device_arrays(self.loader.class_plan())
                self.weights.unmap()
                new_w, delta = self._scan_fn(
                    self._dataset_dev, self.weights.devmem,
                    self._coords_dev, idxs, ms, self.alpha, self.radius)
                self.weights.set_devmem(new_w)
                self.scan_delta_dev = delta      # fetched by the decision
                self._scan_in_flight = True
            if self.loader.last_minibatch:
                self._scan_in_flight = False
            return
        # per-minibatch path: also the fallback for a class pass entered
        # MID-WAY (restored loader state after resume — same defense as
        # FusedTrainStep.run)
        self._maybe_snapshot_epoch_start()
        self.input.unmap()
        self.weights.unmap()
        x = self.input.devmem
        new_w, idx = self._xla_fn(
            x.reshape(x.shape[0], -1), self.weights.devmem,
            self._coords_dev, self.alpha, self.radius,
            self.current_batch_size(self.input))
        self.weights.set_devmem(new_w)
        self.winners.set_devmem(idx)


class KohonenForward(KohonenBase):
    """Reference: kohonen.py :: KohonenForward — winner index per sample
    (+ hit counts for the SOM plotters); weights linked from the trainer."""

    def __init__(self, workflow=None, shape=(8, 8), compute_hits: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, shape=shape, **kwargs)
        self.output = Array()
        self.compute_hits = compute_hits
        self.hits = None

    def _common_init(self, **kwargs) -> None:
        if not self.output or len(self.output) != self.input.shape[0]:
            self.output.reset(shape=(self.input.shape[0],), dtype=np.int32)
        if self.compute_hits and self.hits is None:
            self.hits = np.zeros(self.n_neurons, np.int64)
        self.init_array(self.input, self.weights, self.output)

    def numpy_run(self) -> None:
        x = self._flat_input(self.input.mem)
        idx = k_ops.winners(np, x, self.weights.mem)
        self.output.map_invalidate()
        self.output.mem = idx.astype(np.int32)
        if self.compute_hits:
            bs = self.current_batch_size(self.input)
            self.hits += np.bincount(idx[:bs], minlength=self.n_neurons)

    def xla_init(self) -> None:
        self._xla_fn = _winners_jit

    def xla_run(self) -> None:
        self.input.unmap()
        self.weights.unmap()
        x = self.input.devmem
        idx = self._xla_fn(x.reshape(x.shape[0], -1), self.weights.devmem)
        self.output.set_devmem(idx)
        if self.compute_hits:
            bs = self.current_batch_size(self.input)
            self.hits += np.bincount(np.asarray(idx)[:bs],
                                     minlength=self.n_neurons)


class KohonenDecision(DecisionBase):
    """Epoch bookkeeping for SOM training: metric is the epoch's weight
    movement ``|ΔW|/|W|``; stops on max_epochs or when movement falls
    below ``min_delta`` (reference sample's stop logic)."""

    def __init__(self, workflow=None, min_delta: float = 1e-4,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.min_delta = float(min_delta)
        self.trainer = None
        self._epoch_start_w = None
        self.weights_delta = 0.0

    def accumulate(self, cls: int) -> None:
        if getattr(self.trainer, "scan_delta_dev", None) is not None:
            return            # metric rides the scan dispatch on device
        if self._epoch_start_w is None:
            pre = getattr(self.trainer, "epoch_start_weights", None)
            self._epoch_start_w = pre.copy() if pre is not None \
                else self.trainer.weights.map_read().copy()

    def finalize_class(self, cls: int) -> float:
        delta_dev = getattr(self.trainer, "scan_delta_dev", None)
        if delta_dev is not None:
            # scan mode: ONE scalar d2h is the whole per-epoch fence
            self.weights_delta = float(jax.device_get(delta_dev))
            self.trainer.scan_delta_dev = None
            return self.weights_delta
        w = self.trainer.weights.map_read()
        denom = max(float(np.abs(self._epoch_start_w).sum()), 1e-12)
        self.weights_delta = float(
            np.abs(w - self._epoch_start_w).sum()) / denom
        return self.weights_delta

    def reset_epoch(self) -> None:
        self._epoch_start_w = None

    def run(self) -> None:
        super().run()
        if bool(self.epoch_ended) and self.weights_delta < self.min_delta:
            self.complete.set(True)

    def on_epoch_logged(self) -> None:
        self.info(f"epoch {int(self.epoch_number)}: weights delta "
                  f"{self.weights_delta:.6f}")
