"""Input normalization unit — rebuild of veles.znicz
mean_disp_normalizer.py :: MeanDispNormalizer.

``output = (input - mean) * rdisp`` on device; ``mean`` and ``rdisp``
(reciprocal dispersion) are dataset statistics computed by the loader
pipeline (the reference's ImageNet workflows feed the precomputed
mean/dispersion tensors).  ``fit()`` computes them from a sample batch when
the pipeline does not supply them.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core.memory import Array
from znicz_tpu.core.accelerated_units import AcceleratedUnit


class MeanDispNormalizer(AcceleratedUnit):
    """Reference: mean_disp_normalizer.py :: MeanDispNormalizer."""

    def __init__(self, workflow=None, epsilon: float = 1e-6,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.input = Array()
        self.mean = Array()    # linked from the loader pipeline, or fit()
        self.rdisp = Array()
        self.output = Array()
        self.epsilon = float(epsilon)

    def fit(self, samples: np.ndarray) -> None:
        """Compute mean/rdisp over a representative batch (axis 0)."""
        samples = np.asarray(samples, np.float32)
        self.mean.mem = samples.mean(axis=0)
        disp = samples.max(axis=0) - samples.min(axis=0)
        self.rdisp.mem = (1.0 / np.maximum(disp, self.epsilon)).astype(
            np.float32)

    def _common_init(self, **kwargs) -> None:
        if not self.mean or not self.rdisp:
            raise ValueError("MeanDispNormalizer needs mean/rdisp (link "
                             "them or call fit())")
        if self.mean.shape != self.input.shape[1:]:
            raise ValueError(f"mean shape {self.mean.shape} != sample shape "
                             f"{self.input.shape[1:]}")
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(shape=self.input.shape)
        self.init_array(self.input, self.mean, self.rdisp, self.output)

    @staticmethod
    def compute(xp, x, mean, rdisp):
        return (x - mean) * rdisp

    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = self.compute(np, self.input.mem, self.mean.mem,
                                       self.rdisp.mem)

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda x, m, r: self.compute(jnp, x, m, r))

    def xla_run(self) -> None:
        for arr in (self.input, self.mean, self.rdisp):
            arr.unmap()
        self.output.set_devmem(self._xla_fn(
            self.input.devmem, self.mean.devmem, self.rdisp.devmem))
