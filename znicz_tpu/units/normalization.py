"""Local response normalization units — rebuild of veles.znicz
normalization.py :: LRNormalizerForward, LRNormalizerBackward.

AlexNet cross-map LRN with the reference's hyperparameters
(alpha/beta/k/n) and the exact-derivative backward (znicz_tpu.ops.lrn).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops import lrn as lrn_ops
from znicz_tpu.units.nn_units import Forward, GradientDescentBase


class LRNormalizerForward(Forward):
    """Reference: LRNormalizerForward (alpha=1e-4, beta=0.75, k=2, n=5)."""

    MAPPING = {"norm"}

    def __init__(self, workflow=None, alpha=1e-4, beta=0.75, k=2.0, n=5,
                 **kwargs) -> None:
        super().__init__(workflow, include_bias=False, **kwargs)
        self.alpha, self.beta, self.k, self.n = alpha, beta, float(k), int(n)

    def _common_init(self, **kwargs) -> None:
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(shape=self.input.shape)
        self.init_array(self.input, self.output)

    def xla_apply(self, p: dict, x, *, rng=None, train=True):
        # normalization stays f32 under mixed precision (bf16 squares
        # round away the alpha-scaled window sums).  Rematerialized: LRN
        # sits on the largest activations in the nets that use it
        # (AlexNet conv1/conv2), and without checkpoint AD keeps f32
        # residuals of those alive across the whole backward pass —
        # recomputing the window sums is ~10 VPU ops vs. hundreds of MB
        # of HBM traffic per step.
        def lrn(t):
            y = lrn_ops.forward(jnp, t.astype(jnp.float32), self.alpha,
                                self.beta, self.k, self.n)
            return y.astype(t.dtype)

        return jax.checkpoint(lrn)(x)

    def numpy_run(self) -> None:
        self.output.map_invalidate()
        self.output.mem = lrn_ops.forward(
            np, self.input.mem, self.alpha, self.beta, self.k, self.n)

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda x: lrn_ops.forward(
            jnp, x, self.alpha, self.beta, self.k, self.n))

    def xla_run(self) -> None:
        self.input.unmap()
        self.output.set_devmem(self._xla_fn(self.input.devmem))


class LRNormalizerBackward(GradientDescentBase):
    """Reference: LRNormalizerBackward — exact derivative."""

    MAPPING = {"norm"}

    def __init__(self, workflow=None, alpha=1e-4, beta=0.75, k=2.0, n=5,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.alpha, self.beta, self.k, self.n = alpha, beta, float(k), int(n)

    def link_from_forward(self, forward) -> "LRNormalizerBackward":
        self.link_attrs(forward, "input", "output")
        self.alpha, self.beta = forward.alpha, forward.beta
        self.k, self.n = forward.k, forward.n
        return self

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(shape=self.input.shape)
        self.init_array(self.err_input, self.err_output)

    def numpy_run(self) -> None:
        err_in = lrn_ops.backward(
            np, self.input.map_read(), self.err_output.map_read(),
            self.alpha, self.beta, self.k, self.n)
        self.err_input.map_invalidate()
        self.err_input.mem = err_in

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(lambda x, e: lrn_ops.backward(
            jnp, x, e, self.alpha, self.beta, self.k, self.n))

    def xla_run(self) -> None:
        for arr in (self.input, self.err_output):
            arr.unmap()
        self.err_input.set_devmem(self._xla_fn(
            self.input.devmem, self.err_output.devmem))
