"""NN unit library — rebuild of the veles.znicz unit tree (SURVEY.md §3.1).

Forward/gradient unit pairs over the pure ops in ``znicz_tpu.ops``; every
unit has a ``numpy`` oracle path and an ``xla`` TPU path (the reference's
numpy/ocl/cuda triple collapsed to numpy/xla).

Importing this package imports every unit module so the MatchingObject
fwd<->gd registry is fully populated (StandardWorkflow's layer-type lookup
depends on it).
"""

from znicz_tpu.units import (activation, all2all, conv, cutter,  # noqa: F401
                             deconv, dropout, gd, gd_conv, gd_deconv,
                             gd_pooling, kohonen, lr_adjust,
                             mean_disp_normalizer, nn_rollback,
                             normalization, pooling, rbm,
                             resizable_all2all, weights_zerofilling)
