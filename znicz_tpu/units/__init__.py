"""NN unit library — rebuild of the veles.znicz unit tree (SURVEY.md §3.1).

Forward/gradient unit pairs over the pure ops in ``znicz_tpu.ops``; every
unit has a ``numpy`` oracle path and an ``xla`` TPU path (the reference's
numpy/ocl/cuda triple collapsed to numpy/xla).
"""
