"""Fully-connected gradient units — rebuild of veles.znicz gd.py ::
GradientDescent, GDTanh, GDRELU, GDStrictRELU, GDSigmoid, GDSoftmax.

err_output -> err_input via Wᵀ GEMM; ∇W via xᵀ GEMM; fused SGD update with
learning_rate / weights_decay (L2·L1 mix) / gradient_moment — the same
fusion the reference's err_h_update + weights_update + bias_update kernels
perform (SURVEY.md §3.2).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops import activations, linear, sgd
from znicz_tpu.units.nn_units import GradientDescentBase


class GradientDescent(GradientDescentBase):
    """Gradient for All2All (reference: gd.py :: GradientDescent)."""

    MAPPING = {"all2all"}
    ACTIVATION = activations.LINEAR
    ACTIVATION_APPLIED = True

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(shape=self.input.shape)
        self.init_array(self.err_input, self.err_output,
                        self.gradient_weights, self.gradient_bias)

    # -- the pure update (shared between backends and the fused step) -------
    def _step(self, xp, x, y, w, b, err_out, vel_w, vel_b, batch_size):
        """Returns (err_input, w_new, b_new, vel_w_new, vel_b_new).

        ``w``/``vel_w`` stay in the *stored* layout; when the paired forward
        uses ``weights_transposed`` the GEMMs see the natural (in, out) view
        and the gradient is transposed back before the update."""
        w_natural = w.T if self.weights_transposed else w
        err_in, grad_w, grad_b = self._backward(xp, x, y, w_natural,
                                                err_out)
        if self.weights_transposed:
            grad_w = grad_w.T
        if not self.need_err_input:
            err_in = None
        if self.apply_gradient:
            w, vel_w = sgd.update(xp, w, grad_w, vel_w, self.learning_rate,
                                  self.weights_decay, self.l1_vs_l2,
                                  self.gradient_moment, batch_size)
            if b is not None:
                b, vel_b = sgd.update(xp, b, grad_b, vel_b,
                                      self.learning_rate_bias,
                                      self.weights_decay_bias, self.l1_vs_l2,
                                      self.gradient_moment_bias, batch_size)
        return err_in, w, b, vel_w, vel_b

    def numpy_run(self) -> None:
        has_bias = bool(self.bias)
        err_in, w, b, vel_w, vel_b = self._step(
            np, self.input.mem, self.output.mem, self.weights.mem,
            self.bias.mem if has_bias else None,
            linear.flatten_batch(np, self.err_output.mem),
            self.gradient_weights.mem,
            self.gradient_bias.mem if has_bias else None,
            self.current_batch_size(self.err_output))
        if err_in is not None:
            self.err_input.map_invalidate()
            self.err_input.mem = err_in
        self.weights.map_invalidate()
        self.weights.mem = w
        self.gradient_weights.map_invalidate()
        self.gradient_weights.mem = vel_w
        if has_bias:
            self.bias.map_invalidate()
            self.bias.mem = b
            self.gradient_bias.map_invalidate()
            self.gradient_bias.mem = vel_b

    def _backward(self, xp, x, y, w_natural, err_out):
        return linear.backward(xp, x, y, w_natural, err_out,
                               self.ACTIVATION, self.ACTIVATION_APPLIED)

    def xla_init(self) -> None:
        from znicz_tpu.core.config import root
        from znicz_tpu.ops.pallas.gemm import FUSED_ACTIVATIONS

        if bool(root.common.engine.get("pallas", False)) and \
                self.ACTIVATION in FUSED_ACTIVATIONS:
            # the reference's err_h_update/weights_update/bias_update
            # trio as blocked Pallas GEMMs (parity path)
            from znicz_tpu.ops.pallas.gemm import fc_backward
            interp = bool(root.common.engine.get("pallas_interpret", False))
            act, applied = self.ACTIVATION, self.ACTIVATION_APPLIED

            def pallas_backward(xp, x, y, w_natural, err_out):
                return fc_backward(x, y, w_natural, err_out, act, applied,
                                   interpret=interp)

            self._backward = pallas_backward
        else:
            # drop a stale instance override from a previous initialize
            # under engine.pallas — the flag must toggle both ways
            self.__dict__.pop("_backward", None)

        def fn(x, y, w, b, err_out, vel_w, vel_b, batch_size):
            return self._step(jnp, x, y, w, b,
                              linear.flatten_batch(jnp, err_out),
                              vel_w, vel_b, batch_size)

        self._xla_fn = jax.jit(fn)

    def xla_run(self) -> None:
        has_bias = bool(self.bias)
        for arr in (self.input, self.output, self.weights, self.err_output,
                    self.gradient_weights):
            arr.unmap()
        err_in, w, b, vel_w, vel_b = self._xla_fn(
            self.input.devmem, self.output.devmem, self.weights.devmem,
            self.bias.devmem if has_bias else None,
            self.err_output.devmem, self.gradient_weights.devmem,
            self.gradient_bias.devmem if has_bias else None,
            self.current_batch_size(self.err_output))
        if err_in is not None:
            self.err_input.set_devmem(err_in)
        self.weights.set_devmem(w)
        self.gradient_weights.set_devmem(vel_w)
        if has_bias:
            self.bias.set_devmem(b)
            self.gradient_bias.set_devmem(vel_b)


class GDTanh(GradientDescent):
    """Gradient for All2AllTanh (reference: gd.py :: GDTanh)."""
    MAPPING = {"all2all_tanh"}
    ACTIVATION = activations.TANH


class GDRELU(GradientDescent):
    """Gradient for All2AllRELU (reference: gd.py :: GDRELU)."""
    MAPPING = {"all2all_relu"}
    ACTIVATION = activations.RELU


class GDStrictRELU(GradientDescent):
    """Gradient for All2AllStrictRELU (reference: gd.py :: GDStrictRELU)."""
    MAPPING = {"all2all_str"}
    ACTIVATION = activations.STRICT_RELU


class GDSigmoid(GradientDescent):
    """Gradient for All2AllSigmoid."""
    MAPPING = {"all2all_sigmoid"}
    ACTIVATION = activations.SIGMOID


class GDSoftmax(GradientDescent):
    """Gradient for All2AllSoftmax (reference: gd.py :: GDSoftmax).

    EvaluatorSoftmax's err_output is already d(cross-entropy)/d(logits)
    (y - target), so no activation derivative is applied here.
    """
    MAPPING = {"softmax"}
    ACTIVATION = "softmax"
    ACTIVATION_APPLIED = False
