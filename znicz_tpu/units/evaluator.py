"""Evaluators — rebuild of veles.znicz evaluator.py :: EvaluatorBase,
EvaluatorSoftmax, EvaluatorMSE.

Turn the last forward's output + labels/targets into ``err_output`` for the
backward chain plus host-side metrics (``n_err``, confusion matrix, mse).

Static-shape note (SURVEY.md §8 "dynamic epoch-tail batches"): the loader
pads tail minibatches to the fixed minibatch size; evaluators mask rows
beyond ``batch_size`` so padded samples contribute neither gradient nor
metrics — the reference relied on the same per-sample masking semantics.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.core.memory import Array
from znicz_tpu.core.accelerated_units import AcceleratedUnit


class EvaluatorBase(AcceleratedUnit):
    """Common evaluator state (reference: evaluator.py :: EvaluatorBase)."""

    def __init__(self, workflow=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.output = Array()      # linked from last forward
        self.err_output = Array()  # allocated here
        #: inference mode: compute metrics only, no err_output needed
        self.forward_mode = False

    def _common_init(self, **kwargs) -> None:
        if not self.err_output or self.err_output.shape != self.output.shape:
            self.err_output.reset(shape=self.output.shape)
        self.init_array(self.output, self.err_output)


class EvaluatorSoftmax(EvaluatorBase):
    """Softmax + cross-entropy evaluator (reference: EvaluatorSoftmax).

    Consumes softmax probabilities ``output`` and integer ``labels``;
    produces ``err_output = y - onehot(labels)`` (d CE/d logits), and
    metrics: ``n_err`` (argmax mismatches), ``confusion_matrix``,
    ``max_err_output_sum`` (largest |err| row-sum, a divergence canary).

    ``class_weights`` (length n_classes) scales each sample's err_output
    row by the weight of its TRUE class — the reference's class-imbalance
    compensation (EvaluatorSoftmax honors class weights; underrepresented
    classes contribute proportionally more gradient).  ``n_err`` stays an
    unweighted integer count, reference semantics.
    """

    def __init__(self, workflow=None, compute_confusion_matrix: bool = True,
                 class_weights=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.labels = Array()   # linked from loader (minibatch_labels)
        self.max_idx = Array()  # linked from All2AllSoftmax
        self.compute_confusion_matrix = compute_confusion_matrix
        self.class_weights = None if class_weights is None else \
            np.asarray(class_weights, np.float32)
        self.n_err = 0
        self.confusion_matrix = None
        self.max_err_output_sum = 0.0

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        n_classes = self.output.shape[1]
        if self.class_weights is not None and \
                len(self.class_weights) != n_classes:
            # XLA's clamped gather would otherwise train silently with
            # the wrong weighting on a length mismatch
            raise ValueError(
                f"class_weights has {len(self.class_weights)} entries "
                f"for {n_classes} classes")
        if self.compute_confusion_matrix:
            self.confusion_matrix = np.zeros((n_classes, n_classes), np.int64)

    @staticmethod
    def _compute(xp, y, labels, max_idx, batch_size, class_weights=None):
        """Pure path shared by both backends; returns (err, n_err, sums)."""
        n, c = y.shape
        valid = (xp.arange(n) < batch_size)
        onehot = (labels[:, None] == xp.arange(c)[None, :]).astype(y.dtype)
        err = (y - onehot) * valid[:, None].astype(y.dtype)
        if class_weights is not None:
            err = err * class_weights[labels][:, None].astype(y.dtype)
        n_err = xp.sum((max_idx != labels) & valid)
        max_err_sum = xp.abs(err).sum(axis=1).max()
        return err, n_err, max_err_sum

    def numpy_run(self) -> None:
        y = self.output.map_read()
        labels = self.labels.map_read()
        max_idx = self.max_idx.map_read() if self.max_idx else \
            y.argmax(axis=1)
        bs = self.current_batch_size(self.output)
        err, n_err, max_err_sum = self._compute(np, y, labels, max_idx, bs,
                                                self.class_weights)
        self.err_output.map_invalidate()
        self.err_output.mem = err
        self.n_err = int(n_err)
        self.max_err_output_sum = float(max_err_sum)
        if self.compute_confusion_matrix:
            np.add.at(self.confusion_matrix,
                      (max_idx[:bs], labels[:bs]), 1)

    def xla_init(self) -> None:
        cw = None if self.class_weights is None else \
            jnp.asarray(self.class_weights)
        self._xla_fn = jax.jit(
            lambda y, labels, max_idx, bs:
            self._compute(jnp, y, labels, max_idx, bs, cw))

    def xla_run(self) -> None:
        for arr in (self.output, self.labels):
            arr.unmap()
        max_idx = self.max_idx.devmem if self.max_idx else \
            jnp.argmax(self.output.devmem, axis=1)
        bs = self.current_batch_size(self.output)
        err, n_err, max_err_sum = self._xla_fn(
            self.output.devmem, self.labels.devmem, max_idx, bs)
        self.err_output.set_devmem(err)
        # metrics are host-side scalars (Decision consumes them in Python)
        self.n_err = int(n_err)
        self.max_err_output_sum = float(max_err_sum)
        if self.compute_confusion_matrix:
            idx = np.asarray(max_idx)[:bs]
            lab = self.labels.map_read()[:bs]
            np.add.at(self.confusion_matrix, (idx, lab), 1)


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator (reference: EvaluatorMSE).

    err_output = output - target (masked); metrics: per-sample ``mse``
    vector over the valid rows, batch ``rmse``.  When ``labels`` AND
    ``class_targets`` are linked (the approximator samples: class_targets
    holds one prototype vector per class), ``n_err`` additionally counts
    nearest-target misclassifications — argmin over ||output - proto_c||
    vs the integer label; otherwise ``n_err`` mirrors mse (what the MSE
    Decision tracks).
    """

    def __init__(self, workflow=None, root_mse: bool = True, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.target = Array()  # linked from loader (minibatch_targets)
        self.labels = Array()         # optional: integer class labels
        self.class_targets = Array()  # optional: (n_classes, *target_shape)
        self.root_mse = root_mse
        self.mse = 0.0
        self.rmse = 0.0
        self.n_err = 0

    @staticmethod
    def _compute(xp, y, target, batch_size):
        n = y.shape[0]
        valid = (xp.arange(n) < batch_size).astype(y.dtype)
        diff = (y.reshape(n, -1) - target.reshape(n, -1)) * valid[:, None]
        err = diff.reshape(y.shape)
        sample_mse = (diff * diff).mean(axis=1)
        mse = sample_mse.sum() / batch_size
        return err, mse

    @staticmethod
    def nearest_prototype(xp, y, protos):
        """argmin_c ||y_i - protos[c]||^2 per row — the single distance/
        argmin definition shared by the eager paths and the fused step."""
        flat = y.reshape(y.shape[0], -1)
        pf = protos.reshape(protos.shape[0], -1)
        d = ((flat[:, None, :] - pf[None, :, :]) ** 2).sum(axis=2)
        return d.argmin(axis=1)

    @staticmethod
    def _nearest_target_errors(xp, y, protos, labels, batch_size):
        """Count nearest-prototype mispredictions over the valid rows
        (reference: nearest-target classification)."""
        pred = EvaluatorMSE.nearest_prototype(xp, y, protos)
        valid = xp.arange(y.shape[0]) < batch_size
        return ((pred != labels) & valid).sum()

    @property
    def _classifies(self) -> bool:
        return bool(self.labels) and bool(self.class_targets)

    def _common_init(self, **kwargs) -> None:
        super()._common_init(**kwargs)
        if self._classifies:
            # the linked label/prototype arrays need device buffers for
            # the xla_run path (the loader only initializes its own
            # minibatch arrays)
            self.init_array(self.labels, self.class_targets)

    def numpy_run(self) -> None:
        y = self.output.map_read()
        target = self.target.map_read()
        bs = self.current_batch_size(self.output)
        err, mse = self._compute(np, y, target, bs)
        self.err_output.map_invalidate()
        self.err_output.mem = err
        self.mse = float(mse)
        self.rmse = float(np.sqrt(self.mse))
        if self._classifies:
            self.n_err = int(self._nearest_target_errors(
                np, y, self.class_targets.map_read(),
                self.labels.map_read(), bs))
        else:
            self.n_err = self.mse  # Decision tracks mse for MSE workflows

    def xla_init(self) -> None:
        self._xla_fn = jax.jit(
            lambda y, t, bs: self._compute(jnp, y, t, bs))
        self._xla_nt_fn = jax.jit(
            lambda y, p, labels, bs:
            self._nearest_target_errors(jnp, y, p, labels, bs))

    def xla_run(self) -> None:
        for arr in (self.output, self.target):
            arr.unmap()
        bs = self.current_batch_size(self.output)
        err, mse = self._xla_fn(self.output.devmem, self.target.devmem, bs)
        self.err_output.set_devmem(err)
        self.mse = float(mse)
        self.rmse = float(np.sqrt(self.mse))
        if self._classifies:
            self.labels.unmap()
            self.class_targets.unmap()
            self.n_err = int(self._xla_nt_fn(
                self.output.devmem, self.class_targets.devmem,
                self.labels.devmem, bs))
        else:
            self.n_err = self.mse
