"""BatchPrefetcher — bounded background prefetch + overlapped H2D staging.

One daemon thread ("znicz-prefetch") runs the loader's serve core
(:meth:`Loader._next_record` → :meth:`Loader.fill_batch` →
:meth:`Loader._complete_record` — shuffle included, so prng order is
byte-identical to the synchronous path) and an optional step-provided
*stager* (e.g. :meth:`FusedTrainStep.make_stager`: ``jax.device_put`` with
the step's input shardings), pushing :class:`StagedBatch` items into a
depth-N bounded queue.  The consumer (``Loader.xla_run`` on the
control-walk thread) pops batches, replays their control metadata onto the
loader's published attributes and hands the staged device arrays to the
step — so host decode of batch k+1..k+depth and its H2D transfer both
overlap the device compute of batch k under XLA's async dispatch stream.

Determinism contract (pinned by tests/test_pipeline_prefetch.py):

- the producer OWNS the serve loop — the per-epoch reshuffle draws from
  the global prng in exactly the synchronous order, just on the worker
  thread; nothing else consumes the host prng during a fused run;
- published loader attributes (``minibatch_*``, ``epoch_number``,
  ``epoch_ended``) are written ONLY by the consumer thread, from the
  captured record — downstream units never observe producer-ahead state;
- **epoch-boundary barrier**: after queueing a batch whose serve crossed
  an epoch boundary, the worker parks until the consumer has consumed
  that batch AND asked for the next one.  The snapshotter (and therefore
  the supervisor's resume) only observes loader/prng state at epoch
  boundaries, where the barrier guarantees it is exactly the sync-mode
  state — this is what keeps snapshots and chaos kill-and-resume
  bit-identical with prefetching on.

Failure semantics: any exception on the worker (including an armed
``pipeline.fetch`` chaos fault, resilience/faults.py) is re-raised on the
consumer at the next :meth:`next_batch` once the queue drains — the
supervisor then sees an ordinary crashed step and restarts; loader
``RetryPolicy`` wrappers (image decode, pickle reads) run inside
``fill_batch`` on the worker and keep retrying exactly as before.
``Workflow.run`` stops registered pipelines on any crash, and snapshot
restore calls :meth:`resync` so a restored cursor never mixes with
batches prefetched from the pre-restore state.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from znicz_tpu.observe import probe
from znicz_tpu.observe import registry as _metrics
from znicz_tpu.observe import trace as _trace
from znicz_tpu.resilience.faults import fault_hook

# shared-registry mirror of PipelineStats (ISSUE 5): the instance stats
# below stay the per-pipeline single-writer truth (tests pin snapshot());
# these process-wide series are what GET /metrics scrapes — stall seconds
# aggregate across pipelines, the fill gauge tracks the live queue
_M_PRODUCED = _metrics.counter("znicz_pipeline_batches_produced_total",
                               "batches the prefetch workers queued")
_M_CONSUMED = _metrics.counter("znicz_pipeline_batches_consumed_total",
                               "prefetched batches the consumers popped")
_M_SERVE = _metrics.counter("znicz_pipeline_serve_seconds_total",
                            "host serve+fill seconds on prefetch workers")
_M_STAGE = _metrics.counter("znicz_pipeline_stage_seconds_total",
                            "device_put staging seconds on workers")
_M_PROD_STALL = _metrics.counter(
    "znicz_pipeline_producer_starved_seconds_total",
    "workers waited for a free queue slot")
_M_CONS_STALL = _metrics.counter(
    "znicz_pipeline_consumer_starved_seconds_total",
    "consumers waited on an empty queue")
_M_BARRIER = _metrics.counter(
    "znicz_pipeline_barrier_seconds_total",
    "epoch-boundary determinism parks on workers")
_M_FILL = _metrics.gauge("znicz_pipeline_queue_fill",
                         "prefetch queue occupancy after the last event")


class PrefetcherStopped(RuntimeError):
    """``next_batch`` after ``stop()`` — the pipeline is shut down."""


def ring_safe_stager(put: Callable) -> Callable:
    """Wrap a device-placement callable so ring-slot handoff is safe —
    THE one place the detach-or-fence invariant lives (shared by
    FusedTrainStep.make_stager and TransformerLMStep.make_stager):

    - on the CPU backend ``device_put`` zero-copy ALIASES host memory
      while dispatch stays async, so the host arrays are detached with a
      worker-side copy before the put;
    - on accelerators the staged result is fenced
      (``block_until_ready``) so the H2D transfer has completed — the
      ring slot is then free for reuse.

    Either way the cost rides the producer thread, never the consumer.
    ``put(*host_arrays)`` must return the staged array pytree."""
    import jax

    cpu_backend = jax.devices()[0].platform == "cpu"

    def stage(*host_arrays):
        if cpu_backend:
            host_arrays = tuple(np.array(a) for a in host_arrays)
        staged = put(*host_arrays)
        if not cpu_backend:
            jax.block_until_ready(staged)
        return staged

    return stage


class StagedBatch:
    """One prefetched minibatch: the loader control record, the filled
    host arrays (None when the loader serves indices only), and the
    stager's device arrays (None without a stager)."""

    __slots__ = ("record", "arrays", "staged")

    def __init__(self, record: dict, arrays: Optional[dict],
                 staged: Optional[dict]) -> None:
        self.record = record
        self.arrays = arrays
        self.staged = staged


class PipelineStats:
    """Per-stage accounting.  Single-writer discipline: the worker owns
    ``produced``/``serve_s``/``stage_s``/``producer_starved_s``/
    ``barrier_s``/``bytes_staged``/``max_fill``; the consumer owns
    ``consumed``/``consumer_starved_s`` — no locks on the hot path."""

    def __init__(self, depth: int) -> None:
        self.depth = depth
        self.produced = 0            # batches the worker queued
        self.consumed = 0            # batches the consumer popped
        self.bytes_staged = 0        # host bytes shipped through the stager
        self.max_fill = 0            # high-water queue occupancy observed
        self.serve_s = 0.0           # host serve+fill time (worker)
        self.stage_s = 0.0           # device_put staging time (worker)
        self.producer_starved_s = 0.0  # worker waited for a free slot
        self.consumer_starved_s = 0.0  # consumer waited on an empty queue
        self.barrier_s = 0.0         # epoch-boundary determinism park

    def bound(self) -> str:
        """Dominant stall: ``consumer-starved`` (producer is the
        bottleneck), ``producer-starved`` (compute is — the pipeline keeps
        up), or ``transfer-bound`` (staging dominates the worker)."""
        stalls = {"producer-starved": self.producer_starved_s,
                  "consumer-starved": self.consumer_starved_s,
                  "transfer-bound": self.stage_s}
        if max(stalls.values()) <= 0.0:
            return "balanced"
        return max(stalls, key=stalls.get)

    def snapshot(self) -> dict:
        return {
            "depth": self.depth,
            "produced": self.produced,
            "consumed": self.consumed,
            "bytes_staged": self.bytes_staged,
            "max_fill": self.max_fill,
            "serve_s": round(self.serve_s, 4),
            "stage_s": round(self.stage_s, 4),
            "producer_starved_s": round(self.producer_starved_s, 4),
            "consumer_starved_s": round(self.consumer_starved_s, 4),
            "barrier_s": round(self.barrier_s, 4),
            "bound": self.bound(),
        }


class BatchPrefetcher:
    """Depth-bounded producer of :class:`StagedBatch` items over a Loader.

    ``stager(record, arrays) -> (staged_dict, nbytes)`` runs on the worker
    thread right after the host fill — its ``jax.device_put`` calls are
    the overlapped H2D leg.  ``stager=None`` still overlaps the host fill
    (the consumer uploads as the sync path does).
    """

    THREAD_NAME = "znicz-prefetch"

    def __init__(self, loader, stager: Optional[Callable] = None,
                 depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.loader = loader
        self.depth = int(depth)
        self._stager = stager
        #: a stager detaches ring slots before handoff (ring_safe_stager
        #: copy/fence); without one, batches reach the consumer as raw
        #: host buffers that async dispatch may alias — fill_batch then
        #: serves FRESH buffers (sync-path ownership) instead of rotating
        self.detaches_slots = stager is not None
        self.stats = PipelineStats(self.depth)
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._barrier_sem = threading.Semaphore(0)
        self._pending_release = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # -- producer ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("prefetcher already started")
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name=self.THREAD_NAME)
        self._thread.start()

    def _worker(self) -> None:
        loader = self.loader
        try:
            while not self._stop.is_set():
                # chaos hook: crash/hang/oserror inside the REAL worker
                # loop (site "pipeline.fetch") — the consumer re-raises
                fault_hook("pipeline.fetch", loader=loader,
                           batch=self.stats.produced)
                t0 = time.perf_counter()
                rec = loader._next_record()
                arrays = None
                if not loader.serve_indices_only:
                    arrays = loader.fill_batch(rec["indices"], rec["size"])
                loader._complete_record(rec)
                serve_dt = time.perf_counter() - t0
                self.stats.serve_s += serve_dt
                observed = probe.enabled()
                if observed:
                    _M_SERVE.inc(serve_dt)
                staged = None
                if self._stager is not None:
                    t0 = time.perf_counter()
                    staged, nbytes = self._stager(rec, arrays)
                    stage_dt = time.perf_counter() - t0
                    self.stats.stage_s += stage_dt
                    self.stats.bytes_staged += int(nbytes)
                    if observed:
                        _M_STAGE.inc(stage_dt)
                        probe.staged_bytes(int(nbytes))
                        # anatomy plane (ISSUE 20): the H2D staging leg
                        # as a phase of the input pipeline's step
                        probe.anatomy_phase("pipeline", "stage",
                                            stage_dt, t0=t0)
                batch = StagedBatch(rec, arrays, staged)
                t0 = time.perf_counter()
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.05)
                        break
                    except queue.Full:
                        continue
                else:
                    return
                stall_dt = time.perf_counter() - t0
                self.stats.producer_starved_s += stall_dt
                self.stats.produced += 1
                fill = self._queue.qsize()
                if fill > self.stats.max_fill:
                    self.stats.max_fill = fill
                if observed:
                    _M_PROD_STALL.inc(stall_dt)
                    _M_PRODUCED.inc()
                    _M_FILL.set(fill)
                if rec["epoch_ended"]:
                    # determinism barrier: hold the post-boundary state
                    # (reshuffled order, advanced epoch) frozen until the
                    # consumer-side snapshotter has had its window
                    t0 = time.perf_counter()
                    self._barrier_sem.acquire()
                    barrier_dt = time.perf_counter() - t0
                    self.stats.barrier_s += barrier_dt
                    if observed:
                        _M_BARRIER.inc(barrier_dt)
        except BaseException as exc:  # noqa: BLE001 — re-raised on consumer
            self._error = exc
            # the error is parked until the consumer drains the queue —
            # drop an instant NOW so a flight artifact dumped between
            # the worker dying and the consumer noticing still carries
            # the real failure point
            if probe.enabled():
                _trace.instant("pipeline.error",
                               error=type(exc).__name__,
                               batch=self.stats.produced)

    # -- consumer ------------------------------------------------------------
    def next_batch(self) -> StagedBatch:
        """Pop the next prefetched batch (starts the worker lazily);
        re-raises a worker failure once the queue drains."""
        if self._thread is None:
            self.start()
        if self._pending_release:
            # the consume AFTER the epoch-boundary batch: the snapshot
            # window is over, release the parked worker into the new epoch
            self._pending_release = False
            self._barrier_sem.release()
        t0 = time.perf_counter()
        while True:
            if self._stop.is_set():
                raise PrefetcherStopped("prefetcher was stopped")
            try:
                batch = self._queue.get(timeout=0.05)
                break
            except queue.Empty:
                if self._error is not None:
                    raise self._error
        stall_dt = time.perf_counter() - t0
        self.stats.consumer_starved_s += stall_dt
        self.stats.consumed += 1
        if probe.enabled():
            _M_CONS_STALL.inc(stall_dt)
            _M_CONSUMED.inc()
            # anatomy plane (ISSUE 20): consumer-side input wait — the
            # time the step sat blocked on an empty prefetch ring
            probe.anatomy_phase("pipeline", "input_wait", stall_dt,
                                t0=t0)
        if batch.record["epoch_ended"]:
            self._pending_release = True
        return batch

    # -- lifecycle -----------------------------------------------------------
    def stop(self) -> bool:
        """Shut down: unpark + join the worker, drop queued batches.
        Returns True when the worker is confirmed dead (False = it was
        still alive after the join grace — abandoned, not re-armable)."""
        self._stop.set()
        self._barrier_sem.release()          # unpark a barrier wait
        t = self._thread
        if t is not None and t.is_alive() and \
                t is not threading.current_thread():
            t.join(timeout=10.0)
        while True:                          # release ring-buffer refs
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        return t is None or not t.is_alive()

    def resync(self) -> None:
        """Drain and re-arm after the loader's cursor was replaced
        (snapshot restore): queued batches belong to the pre-restore
        state and are discarded; the next ``next_batch`` restarts the
        worker from the restored position."""
        if not self.stop():
            # a wedged worker would wake against the replaced stop event
            # and race a fresh one over the loader's cursor + the global
            # prng — refuse to re-arm; the supervisor treats the failed
            # restore as one more crashed attempt
            raise RuntimeError(
                "prefetch worker still alive after stop(); cannot re-arm "
                "the pipeline over a live producer")
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self.depth)
        self._barrier_sem = threading.Semaphore(0)
        self._pending_release = False
        self._error = None
        self._thread = None

    def stats_snapshot(self) -> dict:
        """``WebStatus.register_pipeline`` payload."""
        return self.stats.snapshot()


def attach_prefetcher(loader, stager: Optional[Callable] = None,
                      depth: int = 2) -> BatchPrefetcher:
    """Attach a prefetch pipeline to ``loader``: its ``run`` now consumes
    staged batches while the worker produces ahead.  Registers with the
    owning workflow (``Workflow.pipelines``) for timing_table/stop
    integration; returns the prefetcher."""
    if getattr(loader, "pipeline", None) is not None:
        raise ValueError(f"loader {loader.name!r} already has a pipeline")
    pf = BatchPrefetcher(loader, stager=stager, depth=depth)
    loader.pipeline = pf
    workflow = getattr(loader, "workflow", None)
    if workflow is not None and hasattr(workflow, "pipelines"):
        workflow.pipelines.append(pf)
    return pf
