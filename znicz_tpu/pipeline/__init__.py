"""Async input pipeline: prefetching loaders with overlapped host→device
staging (docs/PIPELINE.md).  Not to be confused with
``znicz_tpu.parallel.pipeline`` (GPipe-style model pipeline parallelism)."""

from znicz_tpu.pipeline.prefetcher import (BatchPrefetcher, PipelineStats,
                                           PrefetcherStopped, StagedBatch,
                                           attach_prefetcher,
                                           ring_safe_stager)

__all__ = ["BatchPrefetcher", "PipelineStats", "PrefetcherStopped",
           "StagedBatch", "attach_prefetcher", "ring_safe_stager"]
