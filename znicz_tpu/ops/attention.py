"""Multi-head attention ops (TPU-native extension; no reference
counterpart — veles.znicz predates transformers, SURVEY.md §6.7 — but the
rebuild treats long-context as first-class).

Dense reference implementation here; the sequence-parallel ring variant
(identical math, K/V blocks rotated over the ``seq`` mesh axis) lives in
znicz_tpu.parallel.ring_attention and is pinned equal to this one by
tests/test_parallel_axes.py.

Layouts: activations ``(batch, time, d_model)``; heads split last dim.
"""

from __future__ import annotations

import numpy as np


def split_heads(xp, x, n_heads: int):
    b, t, d = x.shape
    return x.reshape(b, t, n_heads, d // n_heads)


def merge_heads(xp, x):
    b, t, h, dh = x.shape
    return x.reshape(b, t, h * dh)


def softmax(xp, x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    e = xp.exp(x - m)
    return e / e.sum(axis=axis, keepdims=True)


def masked_scores(xp, q, k, causal: bool, q_offset=0, k_offset=0):
    """Scaled q·kᵀ scores ``(b, h, tq, tk)`` with optional causal masking;
    ``*_offset`` give global positions when q/k are sequence blocks — the
    ONE definition of the mask convention, shared by dense attention and
    the ring variant (znicz_tpu.parallel.ring_attention).

    The product accumulates in f32 even for bf16 inputs (matmul inputs
    stay bf16 on the MXU; only the accumulator widens — the same rule as
    the Pallas flash kernel, so the auto-selected paths agree)."""
    dh = q.shape[-1]
    try:
        s = xp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=xp.float32)
    except TypeError:      # numpy has no accumulator-dtype control
        s = xp.einsum("bqhd,bkhd->bhqk", q, k)
    s = s / np.sqrt(dh).astype(s.dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = xp.arange(tq)[:, None] + q_offset
        kpos = xp.arange(tk)[None, :] + k_offset
        s = xp.where((kpos > qpos)[None, None, :, :],
                     xp.asarray(-1e30, dtype=s.dtype), s)
    return s


def attention(xp, q, k, v, causal: bool = False):
    """Scaled-dot-product attention over per-head tensors
    ``(b, t, h, dh)``."""
    p = softmax(xp, masked_scores(xp, q, k, causal))
    # probabilities ride the MXU at the value dtype (flash-kernel rule)
    return xp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def mha_forward(xp, x, params: dict, n_heads: int, causal: bool = False,
                attention_fn=None):
    """Full MHA block: qkv projections -> attention -> output projection.
    ``params``: wq/wk/wv/wo ``(d, d)`` (+ optional bq/bk/bv/bo).
    ``attention_fn(q, k, v, causal)`` overrides the core (the ring variant
    passes its sequence-parallel kernel) — ONE definition of the
    projection/param convention for all MHA assemblies."""
    def proj(w_key, b_key):
        y = x @ params[w_key]
        if params.get(b_key) is not None:
            y = y + params[b_key]
        return split_heads(xp, y, n_heads)

    q = proj("wq", "bq")
    k = proj("wk", "bk")
    v = proj("wv", "bv")
    if attention_fn is None:
        o = attention(xp, q, k, v, causal=causal)
    else:
        o = attention_fn(q, k, v, causal=causal)
    y = merge_heads(xp, o) @ params["wo"]
    if params.get("bo") is not None:
        y = y + params["bo"]
    return y
