"""Fused SGD weight update — rebuild of the reference's weights_update /
bias_update kernels (gradient_descent.{cl,cu}, SURVEY.md §3.2).

The reference fuses, in one kernel: gradient normalization by batch size,
L2/L1 weight decay (``weights_decay`` with ``l1_vs_l2`` mixing), momentum
(``gradient_moment`` into the persistent gradient buffer), and the in-place
weight apply.  Kept as one fusable function here — XLA fuses it into a
couple of elementwise HBM passes; the Pallas version
(znicz_tpu.ops.pallas.sgd) makes the single-pass fusion explicit.

Update rule (reference semantics):

    g     = grad_sum / batch_size
            + weights_decay * ((1 - l1_vs_l2) * w + l1_vs_l2 * sign(w))
    vel   = gradient_moment * vel + learning_rate * g
    w_new = w - vel
"""

from __future__ import annotations


def update(xp, w, grad_sum, vel, learning_rate: float, weights_decay: float,
           l1_vs_l2: float, gradient_moment: float, batch_size):
    """One fused SGD step.  Returns ``(w_new, vel_new)``.

    ``vel`` is the persistent momentum buffer (reference:
    ``gradient_weights`` Array with the moment folded in); pass zeros for
    the first step.  ``batch_size`` may be a traced scalar (masked tail
    minibatches divide by the *real* sample count).

    Dtype contract: math runs in ``w``'s dtype (f32 masters); ``vel``
    may be stored narrow (state_dtype bf16) — it is widened for the
    update and ``vel_new`` is returned in ``vel``'s own dtype, so the
    weight apply always uses the full-precision velocity.
    """
    vel_dtype = vel.dtype
    if vel_dtype != w.dtype:
        vel = vel.astype(w.dtype)
    g = grad_sum / batch_size
    # branchless: hyperparams may be traced scalars inside the fused step
    # (LR schedules mutate them without recompiling); the static-zero check
    # only skips work when called eagerly with plain floats
    if not (isinstance(weights_decay, (int, float)) and weights_decay == 0):
        g = g + weights_decay * ((1.0 - l1_vs_l2) * w +
                                 l1_vs_l2 * xp.sign(w))
    vel_new = gradient_moment * vel + learning_rate * g
    w_new = w - vel_new
    if vel_dtype != w.dtype:
        vel_new = vel_new.astype(vel_dtype)
    return w_new, vel_new
