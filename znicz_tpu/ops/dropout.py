"""Dropout mask op — rebuild of the reference's dropout.{cl,cu} mask-gen
kernel (SURVEY.md §3.2).  One definition shared by every execution path
(numpy oracle, eager xla, fused step) so the mask semantics cannot diverge.
"""

from __future__ import annotations


def make_mask(xp, u, ratio: float, dtype):
    """Bernoulli keep-mask from uniforms ``u`` in [0,1): kept entries hold
    ``1/(1-ratio)`` (inverted-dropout scale, reference semantics), dropped
    entries 0."""
    keep = 1.0 - ratio
    return (u >= ratio).astype(dtype) / keep
