"""Fully-connected (All2All) forward/backward — rebuild of the reference's
all2all + gradient_descent GEMM kernels (matrix_multiplication.{cl,cu},
SURVEY.md §3.2).

Layout note (TPU-first design decision): weights are stored **(in, out)** so
the forward GEMM is ``x @ W`` with no transpose — the MXU-friendly layout.
The reference stores (out, in) and runs x·Wᵀ; the ``weights_transposed``
unit flag is honored at the unit level by transposing on load/save, not in
the hot loop.
"""

from __future__ import annotations

from znicz_tpu.ops import activations


def flatten_batch(xp, x):
    """(B, ...) -> (B, features) — the reference reshapes implicitly."""
    return x.reshape(x.shape[0], -1)


def forward(xp, x, weights, bias, activation: str = activations.LINEAR):
    """y = act(x·W + b).  ``bias`` may be None (include_bias=False)."""
    v = flatten_batch(xp, x) @ weights
    if bias is not None:
        v = v + bias
    return activations.forward(xp, activation, v)


def softmax_forward(xp, x, weights, bias):
    """All2AllSoftmax forward: row-max-subtracted exp-normalize.

    Returns ``(y, max_idx)`` — the reference's softmax kernel also emits the
    argmax per row for the evaluator (SURVEY.md §3.1 All2AllSoftmax).
    """
    v = flatten_batch(xp, x) @ weights
    if bias is not None:
        v = v + bias
    m = v.max(axis=1, keepdims=True)
    e = xp.exp(v - m)
    y = e / e.sum(axis=1, keepdims=True)
    return y, v.argmax(axis=1)


def backward(xp, x, y, weights, err_output, activation: str,
             activation_applied: bool = True):
    """Full backward for one FC layer.

    Returns ``(err_input, grad_weights, grad_bias)`` with gradients
    **summed over the batch** (normalization by batch size happens in the
    SGD update, reference semantics).

    ``activation_applied=False`` means err_output is already d/d(pre-act)
    — the GDSoftmax case, where EvaluatorSoftmax produced y - target.
    """
    x_flat = flatten_batch(xp, x)
    if activation_applied:
        err_v = activations.backward(xp, activation, y, err_output)
    else:
        err_v = err_output
    err_input = (err_v @ weights.T).reshape(x.shape)
    grad_weights = x_flat.T @ err_v
    grad_bias = err_v.sum(axis=0)
    return err_input, grad_weights, grad_bias
