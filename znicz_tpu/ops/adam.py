"""Fused AdamW weight update — TPU-native extension beyond the
reference's SGD+momentum (gradient_descent.{cl,cu} has no adaptive
optimizer; SURVEY.md §3.2).  Same fusion contract as ops/sgd.py: one
function XLA collapses into a couple of elementwise HBM passes inside
the fused train step.

Update rule (decoupled weight decay, Loshchilov & Hutter):

    g     = grad_sum / batch_size
    m'    = b1*m + (1-b1)*g
    v'    = b2*v + (1-b2)*g^2
    mhat  = m' / (1 - b1^t);  vhat = v' / (1 - b2^t)
    w'    = w - lr * (mhat / (sqrt(vhat) + eps) + weight_decay * w)

``t`` is the POST-increment step count (the caller advances it once per
step and passes the advanced value, so the first step uses t=1).
"""

from __future__ import annotations


def update(xp, w, grad_sum, m, v, t, learning_rate, weight_decay,
           beta1, beta2, eps, batch_size):
    """One AdamW step -> ``(w_new, m_new, v_new)``.

    All hyperparams may be traced scalars; ``t`` is a (traced) f32 step
    count ALREADY advanced for this step.  ``batch_size`` may be traced
    (masked tail minibatches divide by the real sample count).
    """
    g = grad_sum / batch_size
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * (g * g)
    mhat = m_new / (1.0 - beta1 ** t)
    vhat = v_new / (1.0 - beta2 ** t)
    step = mhat / (xp.sqrt(vhat) + eps) + weight_decay * w
    return w - learning_rate * step, m_new, v_new
