"""Shared scaffolding for fused elementwise optimizer kernels (SGD,
AdamW): 2-D view, row tiling under a VMEM budget, SMEM hyperparameter
pack, vma-aware out specs, and in-place aliasing.

Returns ``None`` when no tile fits VMEM (pathologically wide rows) — the
caller falls back to its jnp implementation, which XLA fuses well enough
that correctness never depends on the Pallas path."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: conservative VMEM working-set budget (bytes) for in+out tiles
VMEM_BUDGET = 12 * 1024 * 1024


def out_struct(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-mesh-axes: under
    shard_map with vma checking, pallas_call outputs must declare which
    mesh axes they vary over — same set as the operands.  Degrades to a
    plain struct on pre-vma jax.  THE one copy of this policy (used by
    the optimizer kernels here and the flash-attention kernel)."""
    typeof = getattr(jax, "typeof", None)    # vma-era jax only
    vma = getattr(typeof(like), "vma", None) if typeof else None
    if vma is not None:
        # an EMPTY frozenset means replicated — still required under
        # check_vma; only a missing attribute (pre-vma jax) may omit it
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _pick_tile(rows: int, cols: int, n_buffers: int,
               min_tile: int = 1) -> int:
    """Largest workable row tile: whole-array when it fits (one grid
    step), else the biggest power-of-two divisor of ``rows`` that fits,
    else 0 (= no tile fits; caller must fall back).  ``min_tile`` guards
    Mosaic's sublane tiling: 16-bit refs need (16, 128)-divisible blocks
    unless the block spans the whole array."""
    def fits(t: int) -> bool:
        return t * cols * 4 * n_buffers <= VMEM_BUDGET

    if fits(rows):
        return rows
    for t in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if t >= min_tile and rows % t == 0 and fits(t):
            return t
    return 0


def tiled_update(kernel, hyper_scalars, arrays, aliases: dict,
                 n_out: int, *, interpret: bool = False):
    """Run ``kernel(h_ref, *in_refs, *out_refs)`` tiled over same-shaped
    ``arrays`` (first array defines shape/dtype).  ``aliases`` maps
    operand index (1-based: 0 is the SMEM hyper pack) -> output index for
    in-place updates.  Returns a tuple of ``n_out`` arrays reshaped to
    the input shape, or ``None`` if no tile fits VMEM."""
    orig_shape = arrays[0].shape
    a2 = [a.reshape(-1, orig_shape[-1]) if a.ndim != 2 else a
          for a in arrays]
    rows, cols = a2[0].shape
    # 16-bit buffers (narrow optimizer state) tile at (16, 128) sublanes
    min_tile = 16 if any(jnp.dtype(a.dtype).itemsize < 4 for a in a2) \
        else 1
    tile = _pick_tile(rows, cols, len(arrays) + n_out, min_tile)
    if tile == 0:
        return None
    hyper = jnp.stack([jnp.asarray(h, jnp.float32)
                       for h in hyper_scalars])
    spec = pl.BlockSpec((tile, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    # each output inherits shape/dtype/vma from the operand it aliases
    # (narrow velocity stays narrow); non-aliased outputs mirror arrays[0]
    src = {out_i: a2[in_i - 1] for in_i, out_i in aliases.items()}
    outs = tuple(
        out_struct(a2[0].shape, src.get(i, a2[0]).dtype,
                   src.get(i, a2[0]))
        for i in range(n_out))
    results = pl.pallas_call(
        kernel,
        grid=(rows // tile,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                 [spec] * len(arrays),
        out_specs=(spec,) * n_out,
        out_shape=outs,
        input_output_aliases=dict(aliases),
        interpret=interpret,
    )(hyper, *a2)
    if n_out == 1:
        results = (results,)
    return tuple(r.reshape(orig_shape) for r in results)
