"""LRN forward/backward as Pallas kernels — rebuild of the reference's
normalization.{cl,cu} (SURVEY.md §3.2: "cross-map sliding sums fwd;
exact-derivative bwd").

One VMEM pass each: the channel window sum is a static unrolled
shift-accumulate over the lane dimension (n is small — 5 in AlexNet), so
forward fuses square + window + pow + multiply without touching HBM
between, and backward likewise fuses the adjoint window.
Semantics identical to znicz_tpu.ops.lrn (the jnp oracle the tests pin).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _window(x, n: int, adjoint: bool):
    """Sliding channel-window sum via static shifts (lane-dim rolls)."""
    half = n // 2
    lo = (n - 1 - half) if adjoint else half
    c = x.shape[-1]
    acc = x
    for off in range(1, lo + 1):          # contributions from the left
        shifted = jnp.pad(x, ((0, 0), (off, 0)))[:, :c]
        acc = acc + shifted
    for off in range(1, n - lo):          # contributions from the right
        shifted = jnp.pad(x, ((0, 0), (0, off)))[:, off:]
        acc = acc + shifted
    return acc


def _fwd_kernel(n: int, alpha: float, beta: float, k: float,
                x_ref, y_ref):
    from znicz_tpu.ops.lrn import _pow_neg_beta

    x = x_ref[:]
    d = k + alpha * _window(x * x, n, adjoint=False)
    y_ref[:] = x * _pow_neg_beta(jnp, d, beta)


def _bwd_kernel(n: int, alpha: float, beta: float, k: float,
                x_ref, e_ref, out_ref):
    from znicz_tpu.ops.lrn import _pow_neg_beta

    x = x_ref[:]
    e = e_ref[:]
    d = k + alpha * _window(x * x, n, adjoint=False)
    dnb = _pow_neg_beta(jnp, d, beta)
    t = e * x * (dnb / d)
    out_ref[:] = e * dnb - 2.0 * alpha * beta * x * _window(
        t, n, adjoint=True)


def _flat2(x):
    return x.reshape(-1, x.shape[-1])


def lrn_forward(x, alpha: float, beta: float, k: float, n: int, *,
                interpret: bool = False):
    x2 = _flat2(x)
    from functools import partial
    y = pl.pallas_call(
        partial(_fwd_kernel, n, alpha, beta, k),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=interpret,
    )(x2)
    return y.reshape(x.shape)


def lrn_backward(x, err_output, alpha: float, beta: float, k: float, n: int,
                 *, interpret: bool = False):
    x2, e2 = _flat2(x), _flat2(err_output)
    from functools import partial
    out = pl.pallas_call(
        partial(_bwd_kernel, n, alpha, beta, k),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=interpret,
    )(x2, e2)
    return out.reshape(x.shape)
