"""Flash attention as a Pallas kernel — the hot-op kernel for the
long-context stack (TPU-native extension; the reference predates
transformers, SURVEY.md §6.7, but its identity — a hand-written kernel
for every op family's hot path — is matched here for attention).

Row-block formulation: the grid walks ``(batch*heads, q_blocks)``; each
step holds one q block plus the full K/V for that head in VMEM and
computes its softmax row exactly — the ``(t, t)`` score matrix never
touches HBM (XLA's dense path materializes it twice per layer per step:
~1 GB/layer at b=8, h=8, t=2048, f32).  The saved residual is the
logsumexp row ``lse`` (one f32 per query), from which the backward kernel
reconstructs the probabilities: ``p = exp(s·scale - lse)``.

VMEM budget per grid step is O(block_q·t + t·dh) ≈ 1.5 MB at t=2048 —
fine through t≈8k.  Beyond that the sequence axis should be sharded (ring
attention, znicz_tpu/parallel/ring_attention.py); the two compose: the
ring rotates K/V blocks over ICI while each local block uses dense math,
so per-shard t stays in this kernel's range.

Backward follows the standard flash recipe in one grid pass: dq per
q block; dk/dv accumulated across q blocks into a revisited output block
(Pallas TPU grids execute sequentially, so accumulation over the minor
grid axis is sound).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _mask_scores(s, causal: bool, iq: int, block_q: int):
    """Apply the causal mask to one q block's score rows ``(bq, t)``."""
    if not causal:
        return s
    bq, t = s.shape
    qpos = jax.lax.broadcasted_iota(jnp.int32, (bq, t), 0) + iq * block_q
    kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, t), 1)
    return jnp.where(kpos > qpos, jnp.float32(-1e30), s)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                sm_scale: float, block_q: int):
    iq = pl.program_id(1)
    q = q_ref[0]                                       # (bq, dh)
    k = k_ref[0]                                       # (t, dh)
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    s = _mask_scores(s, causal, iq, block_q)
    m = s.max(axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(axis=1, keepdims=True)
    # p rides the MXU at the input dtype (bf16 in production); the
    # accumulator and the 1/l normalization stay f32
    o = jnp.dot(p.astype(v.dtype), v,
                preferred_element_type=jnp.float32) / l
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)                        # (bq, 1)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, *, causal: bool, sm_scale: float,
                block_q: int):
    iq = pl.program_id(1)

    @pl.when(iq == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    q = q_ref[0]                                       # (bq, dh)
    k = k_ref[0]                                       # (t, dh)
    v = v_ref[0]
    lse = lse_ref[0]                                   # (bq, 1)
    delta = delta_ref[0]                               # (bq, 1)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    s = _mask_scores(s, causal, iq, block_q)
    p = jnp.exp(s - lse)                               # (bq, t)
    # dv += pᵀ @ do (p cast to the MXU input dtype; accumulate f32)
    dv_ref[0] += jax.lax.dot_general(
        p.astype(v.dtype), do_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    # ds = p ⊙ (do @ vᵀ − Δ), already includes the softmax jacobian
    dp = jax.lax.dot_general(do_ref[0], v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    dsc = ds.astype(q.dtype)
    dq_ref[0] = jnp.dot(dsc, k,
                        preferred_element_type=jnp.float32
                        ).astype(dq_ref.dtype)
    # dk += dsᵀ @ q
    dk_ref[0] += jax.lax.dot_general(
        dsc, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _pick_block_q(t: int) -> int:
    # 128 rows already fill the MXU's systolic dimension; larger q blocks
    # only grow the (block_q, t) score temporaries that dominate the
    # BACKWARD kernel's VMEM working set
    return 128 if t % 128 == 0 else 0


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal: bool, interpret: bool):
    o, _ = _flash_fwd(q, k, v, causal, interpret)
    return o


from znicz_tpu.ops.pallas._elementwise import out_struct as _out_struct


def _call_fwd(q, k, v, causal, interpret):
    bh, t, dh = q.shape
    block_q = _pick_block_q(t)
    kern = partial(_fwd_kernel, causal=causal,
                   sm_scale=1.0 / float(np.sqrt(dh)), block_q=block_q)
    blk = lambda shape: pl.BlockSpec(                  # noqa: E731
        shape, lambda i, j: (i,) + (0,) * (len(shape) - 1),
        memory_space=pltpu.VMEM)
    qspec = pl.BlockSpec((1, block_q, dh), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=(bh, t // block_q),
        in_specs=[qspec, blk((1, t, dh)), blk((1, t, dh))],
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            # lse rides as (bh, t, 1): a 2-D (1, block_q) block is not a
            # legal Mosaic tile (penultimate dim 1 is neither 8-divisible
            # nor the full bh axis) — the trailing singleton makes the
            # last-two block dims (block_q, 1) == (8k-divisible, full dim)
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct((bh, t, dh), q.dtype, q),
            _out_struct((bh, t, 1), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v)


def _flash_fwd(q, k, v, causal, interpret):
    o, lse = _call_fwd(q, k, v, causal, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, interpret, res, do, dlse=None):
    q, k, v, o, lse = res
    bh, t, dh = q.shape
    block_q = _pick_block_q(t)
    # Δ = rowsum(do ⊙ o) — the lse-side term of the softmax jacobian;
    # shaped (bh, t, 1) like lse for the same Mosaic-tiling reason.
    # When the caller also differentiates through lse (the ring×flash
    # merge), its cotangent folds into the SAME kernel:
    #   ds = p·(dp − Δ)·scale  and  ∂lse/∂s = p·scale
    #   ⇒ ds_total = p·(dp − (Δ − dlse))·scale
    # so Δ' = Δ − dlse and the backward kernel is reused unchanged.
    delta = (do.astype(jnp.float32) *
             o.astype(jnp.float32)).sum(-1, keepdims=True)
    if dlse is not None:
        delta = delta - dlse
    kern = partial(_bwd_kernel, causal=causal,
                   sm_scale=1.0 / float(np.sqrt(dh)), block_q=block_q)
    full = lambda shape: pl.BlockSpec(                 # noqa: E731
        shape, lambda i, j: (i,) + (0,) * (len(shape) - 1),
        memory_space=pltpu.VMEM)
    qblk3 = lambda: pl.BlockSpec((1, block_q, dh),     # noqa: E731
                                 lambda i, j: (i, j, 0),
                                 memory_space=pltpu.VMEM)
    qblk2 = lambda: pl.BlockSpec((1, block_q, 1),      # noqa: E731
                                 lambda i, j: (i, j, 0),
                                 memory_space=pltpu.VMEM)
    dq, dk, dv = pl.pallas_call(
        kern,
        grid=(bh, t // block_q),
        in_specs=[qblk3(), full((1, t, dh)), full((1, t, dh)),
                  qblk3(), qblk2(), qblk2()],
        # dk/dv revisit the same (bh)-indexed block across the q axis —
        # sequential grid makes the += accumulation exact
        out_specs=[qblk3(), full((1, t, dh)), full((1, t, dh))],
        out_shape=[
            _out_struct((bh, t, dh), q.dtype, q),
            _out_struct((bh, t, dh), jnp.float32, q),
            _out_struct((bh, t, dh), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_lse(q, k, v, causal: bool = False,
                        interpret: bool = False):
    """Flash attention over FOLDED per-head tensors ``(b·h, t, dh)``
    returning ``(o, lse)`` with BOTH outputs differentiable — the
    building block for blockwise composition (ring attention merges
    per-block results by lse weight, so lse carries real cotangents).
    Same kernels as :func:`flash_attention`; the lse cotangent folds
    into the backward's Δ term (see :func:`_flash_bwd`)."""
    return _call_fwd(q, k, v, causal, interpret)


def _flash_lse_fwd(q, k, v, causal, interpret):
    o, lse = _call_fwd(q, k, v, causal, interpret)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd(causal, interpret, res, cts):
    do, dlse = cts
    return _flash_bwd(causal, interpret, res, do, dlse)


flash_attention_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def supported(t: int, dh: int) -> bool:
    """Shapes this kernel handles: q-blockable time axis, lane-sized head
    dim, and a VMEM budget that must cover the BACKWARD kernel (the one
    actually run under value_and_grad): full K/V plus f32 dk/dv
    accumulator blocks plus the three (block_q, t) f32 score temporaries
    (p, dp, ds)."""
    bq = _pick_block_q(t)
    if bq == 0 or dh % 64 != 0:
        return False
    vmem = 4 * t * dh * 4 + 3 * bq * t * 4
    return vmem <= 10 * 1024 * 1024


def flash_attention(q, k, v, causal: bool = False, *,
                    interpret: bool = False):
    """Fused attention over per-head tensors ``(b, t, h, dh)`` — same
    contract as ops.attention.attention (``softmax(q·kᵀ/√dh)·v``),
    differentiable via the flash backward kernels."""
    b, t, h, dh = q.shape
    if not supported(t, dh):
        raise ValueError(
            f"flash_attention needs t divisible by a 128/256/512 q-block, "
            f"dh a multiple of 64, and K/V within the VMEM budget; got "
            f"t={t}, dh={dh} — gate call sites on "
            f"ops.pallas.attention.supported() or use the dense path")
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, -1)  # noqa: E731
    o = _flash(fold(q), fold(k), fold(v), causal, interpret)
    return o.reshape(b, h, t, dh).transpose(0, 2, 1, 3)
