"""Paged flash-decode as a Pallas kernel — single-query attention over
the block-paged KV arena (ISSUE 12), beside the training-side flash
(attention.py) and ring kernels.

Decode attention is one query row per slot against every cached row the
slot has written: memory-bound, gather-heavy, and the only attention
shape the generative plane dispatches in steady state.  The XLA path
(``PagedKVDecoder._paged_attend``) first materializes the gathered
``(B, T_view, H, Dh)`` K/V copies in HBM and then reads them again for
the scores; this kernel fuses the two — the grid walks
``(slot, head, page)`` and each step DMAs ONE arena page straight into
VMEM via the page table (a *scalar-prefetch* operand: block index maps
read it before the kernel body runs, the Pallas paged-attention idiom),
scoring it against the resident query with an f32 online-softmax
accumulator (m/l/acc scratch, carried across the sequential page axis
— the same recipe ``ring_attention`` and the contiguous decoder use, so
numerics agree with the jnp reference to f32 rounding).

Masking: key row ``r`` (global position ``p·page + r``) participates
iff ``p·page + r < length`` for the slot — rows past the slot's write
frontier, scratch-page padding entries, and empty batch slots
(``length == 0`` never happens live; admission guarantees ``>= 1``) all
fall out of the same comparison, with the serve plane's shared -1e30
mask constant.

Interpret-mode fallback: like every kernel in this package the
``interpret=True`` flag runs the identical kernel on the Pallas
interpreter, so the CPU test suite executes the real kernel logic
(tests/test_paged.py pins it against the jnp reference within the
established 2e-5 band).  Compiled TPU dispatch wants lane-sized heads —
gate call sites on :func:`supported` (or pass interpret) exactly like
``ops.pallas.attention``.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, page: int, sm_scale: float):
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)                 # (1, Dh)
    k = k_ref[0, :, 0].astype(jnp.float32)           # (page, Dh)
    v = v_ref[0, :, 0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32
                            ) * sm_scale             # (1, page)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (1, page), 1) + p * page
    s = jnp.where(kpos >= len_ref[b], jnp.float32(-1e30), s)
    m_prev = m_ref[...]                              # (1, 1)
    m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    pexp = jnp.exp(s - m_new)                        # (1, page)
    l_ref[...] = l_ref[...] * alpha + pexp.sum(axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
        pexp, v, preferred_element_type=jnp.float32)
    m_ref[...] = m_new

    @pl.when(p == n_pages - 1)
    def _emit():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...])[0]


def supported(page: int, head_dim: int) -> bool:
    """Shapes the COMPILED kernel tiles cleanly: sublane-sized pages and
    lane-sized head dims.  Interpret mode has no such constraint — the
    paged decoder picks interpret automatically off-TPU."""
    return page % 8 == 0 and head_dim % 128 == 0


def paged_flash_decode(q, k_pages, v_pages, page_table, lengths, *,
                       interpret: bool = False):
    """Fused single-query paged attention.

    ``q (B, H, Dh)``; ``k_pages/v_pages (N, page, H, Dh)`` — one arena
    layer; ``page_table (B, P)`` int32 arena page ids (padding entries
    point at the scratch page and are masked by ``lengths``);
    ``lengths (B,)`` int32 valid rows per slot (``pos + 1`` at decode
    time).  Returns ``o (B, H, Dh)`` float32.
    """
    B, H, Dh = q.shape
    N, page = k_pages.shape[0], k_pages.shape[1]
    P = page_table.shape[1]
    if not interpret and not supported(page, Dh):
        raise ValueError(
            f"compiled paged_flash_decode needs page % 8 == 0 and "
            f"head_dim % 128 == 0; got page={page}, head_dim={Dh} — "
            f"gate call sites on ops.pallas.decode.supported() or run "
            f"interpret")
    kern = partial(_kernel, page=page,
                   sm_scale=1.0 / float(np.sqrt(Dh)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, P),
        in_specs=[
            pl.BlockSpec((1, 1, Dh),
                         lambda b, h, p, pt, ln: (b, h, 0)),
            # THE paged gather: the block index rides the prefetched
            # page table, so each grid step DMAs exactly the page the
            # slot mapped at view position p
            pl.BlockSpec((1, page, 1, Dh),
                         lambda b, h, p, pt, ln: (pt[b, p], 0, h, 0)),
            pl.BlockSpec((1, page, 1, Dh),
                         lambda b, h, p, pt, ln: (pt[b, p], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, Dh),
                               lambda b, h, p, pt, ln: (b, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),         # running max
            pltpu.VMEM((1, 1), jnp.float32),         # running denom
            pltpu.VMEM((1, Dh), jnp.float32),        # o accumulator
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(page_table, jnp.int32), jnp.asarray(lengths, jnp.int32),
      q, k_pages, v_pages)


def reference(q, k_pages, v_pages, page_table, lengths):
    """The jnp oracle the kernel is pinned against: gather the page
    view, mask rows past each slot's length, dense softmax in f32."""
    B, H, Dh = q.shape
    page = k_pages.shape[1]
    t_view = page_table.shape[1] * page
    kc = k_pages[page_table].reshape(B, t_view, H, Dh)
    vc = v_pages[page_table].reshape(B, t_view, H, Dh)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / np.sqrt(Dh)
    dead = jnp.arange(t_view)[None, :] >= \
        jnp.asarray(lengths, jnp.int32)[:, None]
    s = jnp.where(dead[:, None, :], jnp.float32(-1e30), s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, vc.astype(jnp.float32))
