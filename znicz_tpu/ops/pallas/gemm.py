"""Blocked MXU GEMM with fused bias + activation — the rebuild of the
reference's shared tiled-GEMM include (matrix_multiplication.{cl,cu},
SURVEY.md §3.2: "#include'd by all2all + gd + conv kernels") and the FC
forward/backward kernels built on it (all2all/forward.*,
gradient_descent/err_h_update + weights_update + bias_update).

Classic revisited-accumulator blocking: grid (m, n, k) with the
contraction innermost, one f32 VMEM accumulator per (m, n) tile, bias
add + activation fused into the final k step (the reference fuses them
into the same kernel).  Inputs are zero-padded to block multiples
outside the kernel (the forward conv kernel's jnp.pad discipline) and
the output sliced back.

Policy note (ops/pallas/__init__.py): XLA's native dot is the default
everywhere; these are the selectable parity path
(``root.common.engine.pallas``) and the tier-1 cross-check target.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from znicz_tpu.ops import activations

#: activations the fused kernel applies in-block (the reference macro
#: set; the exotic standalone-unit extras stay on the XLA path)
FUSED_ACTIVATIONS = (activations.LINEAR, activations.TANH,
                     activations.RELU, activations.STRICT_RELU,
                     activations.SIGMOID)


def _rup(x: int, m: int) -> int:
    return -(-x // m) * m


def _matmul_kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *,
                   n_k: int, activation: str):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _emit():
        acc = acc_ref[...] + b_ref[...]
        o_ref[...] = activations.forward(
            jnp, activation, acc).astype(o_ref.dtype)


def matmul(x, w, bias=None, activation: str = activations.LINEAR, *,
           interpret: bool = False):
    """``act(x @ w + bias)`` on (M, K) x (K, N) operands."""
    if activation not in FUSED_ACTIVATIONS:
        raise ValueError(f"activation {activation!r} is not in the fused "
                         f"kernel set {FUSED_ACTIVATIONS}")
    M, K = x.shape
    _, N = w.shape
    bm = min(512, _rup(M, 8))
    bn = min(512, _rup(N, 128))
    bk = min(512, _rup(K, 128))
    Mp, Np, Kp = _rup(M, bm), _rup(N, bn), _rup(K, bk)
    xp_ = jnp.pad(x, ((0, Mp - M), (0, Kp - K)))
    wp = jnp.pad(w, ((0, Kp - K), (0, Np - N)))
    b = jnp.zeros((N,), x.dtype) if bias is None else bias
    bp = jnp.pad(b, (0, Np - N)).reshape(1, Np)
    n_k = Kp // bk
    out = pl.pallas_call(
        partial(_matmul_kernel, n_k=n_k, activation=activation),
        grid=(Mp // bm, Np // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(xp_, wp, bp)
    return out[:M, :N]


def _act_bwd_kernel(y_ref, e_ref, o_ref, *, activation: str):
    o_ref[...] = activations.backward(jnp, activation, y_ref[...],
                                      e_ref[...]).astype(o_ref.dtype)


def _act_backward(y, err, activation: str, *, interpret: bool):
    """err_v = err * act'(y), one elementwise pass (the start of the
    reference's err_h_update kernel), row-tiled so wide layers stay
    inside VMEM (a whole-array block would ask for M*N*4 bytes x 3
    buffers at once)."""
    if activation == activations.LINEAR:
        return err
    M, N = y.shape
    Mp, Np = _rup(M, 8), _rup(N, 128)
    bm = Mp
    while bm > 8 and bm * Np * 4 * 3 > 12 * 1024 * 1024:
        bm //= 2
    bm = _rup(bm, 8)
    Mp = _rup(Mp, bm)
    yp = jnp.pad(y, ((0, Mp - M), (0, Np - N)))
    ep = jnp.pad(err, ((0, Mp - M), (0, Np - N)))
    spec = pl.BlockSpec((bm, Np), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        partial(_act_bwd_kernel, activation=activation),
        grid=(Mp // bm,),
        in_specs=[spec, spec], out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((Mp, Np), err.dtype),
        interpret=interpret,
    )(yp, ep)
    return out[:M, :N]


def fc_forward(x, w, bias=None, activation: str = activations.LINEAR, *,
               interpret: bool = False):
    """All2All forward: flatten-batch GEMM + fused bias/activation
    (semantics of ops.linear.forward)."""
    return matmul(x.reshape(x.shape[0], -1), w, bias, activation,
                  interpret=interpret)


def fc_backward(x, y, w, err_output,
                activation: str = activations.LINEAR,
                activation_applied: bool = True, *,
                interpret: bool = False):
    """All2All backward: ``(err_input, grad_w, grad_b)`` with gradients
    summed over the batch (semantics of ops.linear.backward) — the
    reference's err_h_update / weights_update / bias_update trio as
    three blocked GEMMs over the same kernel."""
    x_flat = x.reshape(x.shape[0], -1)
    if activation_applied:
        err_v = _act_backward(y.reshape(y.shape[0], -1),
                              err_output.reshape(err_output.shape[0], -1),
                              activation, interpret=interpret)
    else:
        err_v = err_output.reshape(err_output.shape[0], -1)
    err_input = matmul(err_v, w.T, interpret=interpret).reshape(x.shape)
    grad_w = matmul(x_flat.T, err_v, interpret=interpret)
    grad_b = err_v.sum(axis=0)
    return err_input, grad_w, grad_b
