"""Stochastic pooling with in-kernel PRNG — Pallas rebuild of the
reference's stochastic pooling kernels, whose defining feature is the
device-resident xorshift draw per output cell (SURVEY.md §3.2 names this
a Pallas deliverable precisely because the PRNG semantics are the point).

The window-patch tensor (built by the caller, same layout as
ops.pooling.patches) streams through VMEM; the kernel draws one uniform
per (output cell, channel) from the TPU core PRNG, builds the in-window
CDF with a static tap loop, and selects the winner by comparison — no
gather.  Inverse-CDF semantics are identical to
ops.pooling.stochastic_forward: strict ``cdf < u * total`` compare, so a
zero-mass window selects tap 0 (always in bounds).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _select(patch, valid, u, use_abs):
    """patch (M, K, C), valid (M, K, 1), u (M, C) in [0,1) ->
    (y, idx) each (M, C)."""
    K = patch.shape[1]
    p = jnp.abs(patch) if use_abs else jnp.maximum(patch, 0.0)
    p = p * valid
    total = p.sum(axis=1)                       # (M, C)
    target = u * total
    # static tap loop: running cdf + strict-compare rank = inverse CDF
    cdf = jnp.zeros_like(total)
    idx = jnp.zeros(total.shape, jnp.int32)
    for k in range(K):
        cdf = cdf + p[:, k, :]
        idx = idx + (cdf < target).astype(jnp.int32)
    idx = jnp.minimum(idx, K - 1)
    y = jnp.zeros_like(total)
    for k in range(K):
        y = y + jnp.where(idx == k, patch[:, k, :], 0.0)
    return y, idx


def _uniform(bits):
    """uint32 -> f32 uniform in [0, 1) via the top 24 bits (Mosaic has no
    uint32->f32 cast; the shifted value fits int32, whose cast exists)."""
    return (bits >> 8).astype(jnp.int32).astype(jnp.float32) * (2.0 ** -24)


def _kernel_prng(seed_ref, patch_ref, valid_ref, y_ref, idx_ref, *,
                 use_abs):
    pltpu.prng_seed(seed_ref[0])
    bits = pltpu.bitcast(
        pltpu.prng_random_bits((patch_ref.shape[0], patch_ref.shape[2])),
        jnp.uint32)
    y_ref[:], idx_ref[:] = _select(patch_ref[:], valid_ref[:],
                                   _uniform(bits), use_abs)


def _kernel_bits(patch_ref, valid_ref, bits_ref, y_ref, idx_ref, *,
                 use_abs):
    y_ref[:], idx_ref[:] = _select(patch_ref[:], valid_ref[:],
                                   _uniform(bits_ref[:]), use_abs)


def stochastic_pool(patch, valid, seed, use_abs: bool = False, *,
                    bits=None, interpret: bool = False):
    """-> (y, winner_tap): patch ``(M, K, C)`` (M = n*oh*ow flattened
    output cells, K = ky*kx taps), valid ``(M, K)`` per-cell in-bounds
    mask (border windows clip per position).

    ``seed`` is an int32 scalar (counter-PRNG determinism contract as
    pallas/dropout.py); ``bits`` injects uint32 randoms of shape (M, C)
    for the CPU interpreter, whose emulated TPU PRNG yields zeros."""
    M, K, C = patch.shape
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    valid3 = valid.reshape(M, K, 1).astype(patch.dtype)
    out_shape = (jax.ShapeDtypeStruct((M, C), patch.dtype),
                 jax.ShapeDtypeStruct((M, C), jnp.int32))
    if bits is None:
        return pl.pallas_call(
            partial(_kernel_prng, use_abs=use_abs),
            in_specs=[smem, vmem, vmem], out_specs=(vmem, vmem),
            out_shape=out_shape, interpret=interpret,
        )(jnp.asarray([seed], jnp.int32), patch, valid3)
    return pl.pallas_call(
        partial(_kernel_bits, use_abs=use_abs),
        in_specs=[vmem, vmem, vmem], out_specs=(vmem, vmem),
        out_shape=out_shape, interpret=interpret,
    )(patch, valid3, bits)
