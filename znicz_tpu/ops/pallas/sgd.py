"""Fused SGD update as one Pallas kernel — the explicit single-HBM-pass
version of znicz_tpu.ops.sgd.update (reference: the weights_update /
bias_update kernels fused normalization + decay + momentum + apply in one
launch, gradient_descent.{cl,cu} — SURVEY.md §3.2).

Weights/grad/velocity stream HBM -> VMEM tile by tile; hyperparameters
ride SMEM as scalars; outputs alias the weight/velocity inputs (true
in-place update, no extra HBM traffic).  Shapes whose rows cannot tile
into VMEM fall back to the jnp implementation."""

from __future__ import annotations

import jax.numpy as jnp

from znicz_tpu.ops import sgd as sgd_ops
from znicz_tpu.ops.pallas._elementwise import tiled_update


def _kernel(h_ref, w_ref, g_ref, v_ref, w_out, v_out):
    lr, wd, l1, mom, bs = (h_ref[0], h_ref[1], h_ref[2], h_ref[3], h_ref[4])
    w = w_ref[:]
    g = g_ref[:] / bs
    g = g + wd * ((1.0 - l1) * w + l1 * jnp.sign(w))
    # velocity may be stored narrow (state_dtype bf16): f32 math inside
    # the tile, one narrow store — the single HBM pass is the point
    vel = mom * v_ref[:].astype(w.dtype) + lr * g
    w_out[:] = w - vel
    v_out[:] = vel.astype(v_out.dtype)


def fused_sgd_update(w, grad, vel, learning_rate, weights_decay, l1_vs_l2,
                     gradient_moment, batch_size, *, interpret: bool = False):
    """(w, vel) -> (w', vel') with ops.sgd.update semantics, one pass.

    Arrays of any rank (tiled over a 2-D view); hyperparams may be traced
    scalars.  ``interpret=True`` runs the Mosaic interpreter (CPU tests).
    """
    result = tiled_update(
        _kernel,
        [learning_rate, weights_decay, l1_vs_l2, gradient_moment,
         batch_size],
        (w, grad, vel), aliases={1: 0, 3: 1}, n_out=2,
        interpret=interpret)
    if result is None:
        # ops.sgd.update preserves vel's storage dtype itself
        return sgd_ops.update(jnp, w, grad, vel, learning_rate,
                              weights_decay, l1_vs_l2, gradient_moment,
                              batch_size)
    return result
