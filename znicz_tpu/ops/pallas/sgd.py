"""Fused SGD update as one Pallas kernel — the explicit single-HBM-pass
version of znicz_tpu.ops.sgd.update (reference: the weights_update /
bias_update kernels fused normalization + decay + momentum + apply in one
launch, gradient_descent.{cl,cu} — SURVEY.md §3.2).

Weights/grad/velocity stream HBM -> VMEM tile by tile; hyperparameters
ride SMEM as scalars; outputs alias the weight/velocity inputs (true
in-place update, no extra HBM traffic).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(h_ref, w_ref, g_ref, v_ref, w_out, v_out):
    lr, wd, l1, mom, bs = (h_ref[0], h_ref[1], h_ref[2], h_ref[3], h_ref[4])
    w = w_ref[:]
    g = g_ref[:] / bs
    g = g + wd * ((1.0 - l1) * w + l1 * jnp.sign(w))
    vel = mom * v_ref[:] + lr * g
    w_out[:] = w - vel
    v_out[:] = vel


def fused_sgd_update(w, grad, vel, learning_rate, weights_decay, l1_vs_l2,
                     gradient_moment, batch_size, *, interpret: bool = False):
    """(w, vel) -> (w', vel') with ops.sgd.update semantics, one pass.

    Arrays of any rank (tiled over a 2-D view); hyperparams may be traced
    scalars.  ``interpret=True`` runs the Mosaic interpreter (CPU tests).
    """
    orig_shape = w.shape
    w2 = w.reshape(-1, orig_shape[-1]) if w.ndim != 2 else w
    g2 = grad.reshape(w2.shape)
    v2 = vel.reshape(w2.shape)
    hyper = jnp.stack([
        jnp.asarray(learning_rate, jnp.float32),
        jnp.asarray(weights_decay, jnp.float32),
        jnp.asarray(l1_vs_l2, jnp.float32),
        jnp.asarray(gradient_moment, jnp.float32),
        jnp.asarray(batch_size, jnp.float32)])
    rows = w2.shape[0]
    # row-tile so big embeddings stream through VMEM; lane dim stays whole
    tile = rows if rows <= 512 else 256
    grid = (pl.cdiv(rows, tile),) if rows % tile == 0 else None
    if grid is None:      # ragged rows: single block (still one HBM pass)
        tile, grid = rows, (1,)
    spec = pl.BlockSpec((tile, w2.shape[1]), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    # under shard_map, outputs must declare their varying-axes type; the
    # update preserves the weights' vma (replicated params stay replicated)
    vma = getattr(jax.typeof(w2), "vma", None)
    out = jax.ShapeDtypeStruct(w2.shape, w2.dtype, vma=vma)
    w_new, v_new = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec, spec, spec],
        out_specs=(spec, spec),
        out_shape=(out, out),
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret,
    )(hyper, w2, g2, v2)
    return w_new.reshape(orig_shape), v_new.reshape(orig_shape)
