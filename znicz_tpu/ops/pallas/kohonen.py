"""Kohonen SOM batch step as one Pallas kernel — distance compute, argmin
reduction and neighborhood-weighted update fused in a single VMEM pass
(SURVEY.md §3.2 names the kohonen.{cl,cu} triple a Pallas deliverable).

Everything stays in VMEM for the whole step: squared distances ride one
MXU GEMM (|x|^2 - 2 x·Wᵀ + |w|^2), the winner one-hot is built by
comparing against the row minimum (no gather), winner grid-coordinates
come from ``onehot @ coords`` (MXU again), and the update's two matmuls
(Hᵀ·X and Hᵀ·1) produce the same batch-stable rule as ops.kohonen.update.
The reference needs three kernel launches with HBM round-trips between
them; here weights are read once and written once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(s_ref, x_ref, w_ref, c_ref, wout_ref, idx_ref):
    alpha, sigma, bs = s_ref[0], s_ref[1], s_ref[2]
    x = x_ref[:]                                     # (B, D)
    w = w_ref[:]                                     # (N, D)
    coords = c_ref[:]                                # (N, 2)
    B, N = x.shape[0], w.shape[0]
    # every dot runs at HIGHEST precision: the winner one-hot compares
    # d2 against its row min EXACTLY, and default-precision MXU bf16
    # passes flip winners vs the f32 oracle (measured on chip: 40% of
    # weight elements diverged). The SOM step is dispatch-latency-bound
    # (docs/BENCH_LOG.md roofline), so the extra passes are free.
    hi = jax.lax.Precision.HIGHEST
    x2 = (x * x).sum(axis=1, keepdims=True)          # (B, 1)
    w2 = (w * w).sum(axis=1)                         # (N,)
    d2 = x2 - 2.0 * jnp.dot(x, w.T, precision=hi,
                            preferred_element_type=jnp.float32) + w2
    dmin = d2.min(axis=1, keepdims=True)
    # winner one-hot WITHOUT gather: smallest column index attaining the
    # row min — argmin's first-tie semantics
    col = jax.lax.broadcasted_iota(jnp.int32, (B, N), 1)
    idx = jnp.where(d2 == dmin, col, N).min(axis=1, keepdims=True)
    onehot = (col == idx).astype(jnp.float32)        # (B, N)
    idx_ref[:] = idx
    # neighborhood of each sample's winner over the grid
    wc = jnp.dot(onehot, coords, precision=hi,
                 preferred_element_type=jnp.float32)  # (B, 2)
    wc2 = (wc * wc).sum(axis=1, keepdims=True)
    c2 = (coords * coords).sum(axis=1)
    g2 = wc2 - 2.0 * jnp.dot(wc, coords.T, precision=hi,
                             preferred_element_type=jnp.float32) + c2
    h = jnp.exp(-g2 / (2.0 * sigma * sigma))         # (B, N)
    row = jax.lax.broadcasted_iota(jnp.int32, (B, N), 0).astype(jnp.float32)
    h = jnp.where(row < bs, h, 0.0)                  # mask padded samples
    num = jnp.dot(h.T, x, precision=hi,
                  preferred_element_type=jnp.float32)  # (N, D)
    den = h.sum(axis=0)[:, None]                     # (N, 1)
    wout_ref[:] = w + alpha * (num - den * w) / (den + 1.0)


def som_step(x, weights, coords, alpha, sigma, batch_size, *,
             interpret: bool = False):
    """-> (new_weights, winner_idx): one fused SOM batch step with
    ops.kohonen.update semantics; ``batch_size`` masks padded rows
    (rows >= batch_size contribute nothing)."""
    B = x.shape[0]
    scal = jnp.stack([jnp.asarray(alpha, jnp.float32),
                      jnp.asarray(sigma, jnp.float32),
                      jnp.asarray(batch_size, jnp.float32)])
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    new_w, idx = pl.pallas_call(
        _kernel,
        in_specs=[smem, vmem, vmem, vmem],
        out_specs=(vmem, vmem),
        out_shape=(jax.ShapeDtypeStruct(weights.shape, weights.dtype),
                   jax.ShapeDtypeStruct((B, 1), jnp.int32)),
        interpret=interpret,
    )(scal, x, weights, coords)
    return new_w, idx[:, 0]
