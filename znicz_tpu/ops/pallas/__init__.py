"""Pallas TPU kernels — the rebuild of the reference's hand-written
.cl/.cu kernel layer (SURVEY.md §3.2 "TPU-native mapping").

Policy: XLA-native lowerings are the default everywhere (XLA already fuses
elementwise chains into matmuls); Pallas versions exist where the
reference's fusion/PRNG semantics are the point — the fused SGD update
(one HBM pass over weights+velocity), dropout with in-kernel counter PRNG,
LRN's sliding-window pair, the implicit-im2col GEMM conv, stochastic
pooling with in-kernel PRNG, and the fused Kohonen
distance+argmin+update step.  Each kernel has an ``interpret=`` switch
so the CPU test mesh can pin it against the jnp oracle
(tests/test_pallas_kernels.py); unit code selects via
``root.common.engine.pallas``.
"""

from znicz_tpu.ops.pallas.sgd import fused_sgd_update  # noqa: F401
from znicz_tpu.ops.pallas.dropout import dropout_forward  # noqa: F401
from znicz_tpu.ops.pallas.lrn import lrn_backward, lrn_forward  # noqa: F401
from znicz_tpu.ops.pallas.conv import conv2d_im2col  # noqa: F401
from znicz_tpu.ops.pallas.conv_bwd import (  # noqa: F401
    conv2d_backward, deconv2d, deconv2d_backward)
from znicz_tpu.ops.pallas.pooling import stochastic_pool  # noqa: F401
from znicz_tpu.ops.pallas.kohonen import som_step  # noqa: F401
from znicz_tpu.ops.pallas.attention import flash_attention  # noqa: F401
from znicz_tpu.ops.pallas.adam import fused_adam_update  # noqa: F401
from znicz_tpu.ops.pallas.gemm import (  # noqa: F401
    fc_backward, fc_forward, matmul)
