"""Fused AdamW update as one Pallas kernel — the single-HBM-pass version
of znicz_tpu.ops.adam.update, completing the optimizer kernel family next
to the fused SGD kernel (ops/pallas/sgd.py; SURVEY.md §3.2 "fused
SGD-update" parity deliverable, extended to the AdamW path).

Weights/grad/moments stream HBM -> VMEM tile by tile; hyperparameters
(including the post-increment step count ``t``) ride SMEM as scalars;
outputs alias the weight/moment inputs (true in-place update).  Shapes
whose rows cannot tile into VMEM fall back to the jnp implementation."""

from __future__ import annotations

import jax.numpy as jnp

from znicz_tpu.ops import adam as adam_ops
from znicz_tpu.ops.pallas._elementwise import tiled_update


def _kernel(h_ref, w_ref, g_ref, m_ref, v_ref, w_out, m_out, v_out):
    lr, wd, b1, b2, eps, t, bs = (h_ref[0], h_ref[1], h_ref[2], h_ref[3],
                                  h_ref[4], h_ref[5], h_ref[6])
    w = w_ref[:]
    g = g_ref[:] / bs
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    mhat = m / (1.0 - b1 ** t)
    vhat = v / (1.0 - b2 ** t)
    w_out[:] = w - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * w)
    m_out[:] = m
    v_out[:] = v


def fused_adam_update(w, grad, m, v, t, learning_rate, weight_decay,
                     beta1, beta2, eps, batch_size, *,
                     interpret: bool = False):
    """(w, m, v) -> (w', m', v') with ops.adam.update semantics, one
    pass.  ``t`` is the POST-increment step count (caller advances it).
    Arrays of any rank; scalars may be traced."""
    result = tiled_update(
        _kernel,
        [learning_rate, weight_decay, beta1, beta2, eps, t, batch_size],
        (w, grad, m, v), aliases={1: 0, 3: 1, 4: 2}, n_out=3,
        interpret=interpret)
    if result is None:
        return adam_ops.update(jnp, w, grad, m, v, t, learning_rate,
                               weight_decay, beta1, beta2, eps,
                               batch_size)
    return result
