"""Fused AdamW update as one Pallas kernel — the single-HBM-pass version
of znicz_tpu.ops.adam.update, completing the optimizer kernel family next
to the fused SGD kernel (ops/pallas/sgd.py; SURVEY.md §3.2 "fused
SGD-update" parity deliverable, extended to the AdamW path).

Weights/grad/moments stream HBM -> VMEM tile by tile; hyperparameters
(including the post-increment step count ``t``) ride SMEM as scalars;
outputs alias the weight/moment inputs (true in-place update).  Shapes
whose rows cannot tile into VMEM fall back to the jnp implementation."""

from __future__ import annotations

import jax.numpy as jnp

from znicz_tpu.ops import adam as adam_ops
from znicz_tpu.ops.pallas._elementwise import tiled_update


def _kernel(h_ref, w_ref, g_ref, m_ref, v_ref, w_out, m_out, v_out):
    # bias corrections c1 = 1-b1^t, c2 = 1-b2^t are computed OUTSIDE the
    # kernel: a scalar pow on SMEM operands crashes the Mosaic scalar
    # core's compiler (observed on-chip as a remote_compile HTTP 500)
    lr, wd, b1, b2, eps, c1, c2, bs = (
        h_ref[0], h_ref[1], h_ref[2], h_ref[3], h_ref[4], h_ref[5],
        h_ref[6], h_ref[7])
    w = w_ref[:]
    g = g_ref[:] / bs
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    mhat = m / c1
    vhat = v / c2
    w_out[:] = w - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * w)
    m_out[:] = m
    v_out[:] = v


def fused_adam_update(w, grad, m, v, t, learning_rate, weight_decay,
                     beta1, beta2, eps, batch_size, *,
                     interpret: bool = False):
    """(w, m, v) -> (w', m', v') with ops.adam.update semantics, one
    pass.  ``t`` is the POST-increment step count (caller advances it).
    Arrays of any rank; scalars may be traced."""
    tf = jnp.asarray(t, jnp.float32)
    c1 = 1.0 - jnp.asarray(beta1, jnp.float32) ** tf
    c2 = 1.0 - jnp.asarray(beta2, jnp.float32) ** tf
    result = tiled_update(
        _kernel,
        [learning_rate, weight_decay, beta1, beta2, eps, c1, c2,
         batch_size],
        (w, grad, m, v), aliases={1: 0, 3: 1, 4: 2}, n_out=3,
        interpret=interpret)
    if result is None:
        return adam_ops.update(jnp, w, grad, m, v, t, learning_rate,
                               weight_decay, beta1, beta2, eps,
                               batch_size)
    return result
