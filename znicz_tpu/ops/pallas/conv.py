"""Implicit-im2col convolution as a Pallas kernel — the hand-written GEMM
conv of SURVEY.md §8 step 3 ("hand-written kernel parity"), rebuilding the
reference's conv/forward.{cl,cu} shared-memory im2col GEMM.

One grid step per image: the padded input tile sits in VMEM and the
kernel-window loop issues one MXU GEMM per (ky, kx) tap —
``y[p, :] += x[p*s + tap, :] @ w[tap]`` — accumulating in f32.  The patch
tensor the reference materializes in shared memory never exists: the
window taps are stride-1 VMEM slices (implicit im2col).

Strides are handled by PHASE DECOMPOSITION outside the kernel: Mosaic
cannot lower strided vector extracts (`vector.extract_strided_slice`
verification error on hardware), so the padded input is split into
``sy*sx`` stride-1 phase planes (one XLA reshape+transpose,
space-to-depth style) and the tap for window offset ``(iy, ix)`` reads
phase ``(iy%sy, ix%sx)`` at stride-1 offset ``(iy//sy, ix//sx)`` — same
bytes, same FLOPs, and the kernel only ever slices with unit stride.
At stride 1 the decomposition is the identity.

Policy note (ops/pallas/__init__.py): XLA's native conv is the default
everywhere; this kernel is the selectable parity path
(``root.common.engine.pallas``) and the tier-1 cross-check target.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from znicz_tpu.ops.conv import normalize_geometry, out_size


def phase_split(xpad, sy: int, sx: int):
    """``(n, hp, wp, c) -> (n, sy, sx, hq, wq, c)`` stride-1 phase planes
    (``hq = ceil(hp/sy)``, zero-padded): plane ``(py, px)`` holds rows
    ``py::sy`` and cols ``px::sx``.  Conv geometry guarantees in-kernel
    taps never reach the ceil padding."""
    n, hp, wp, c = xpad.shape
    hq, wq = -(-hp // sy), -(-wp // sx)
    xpad = jnp.pad(xpad, ((0, 0), (0, hq * sy - hp),
                          (0, wq * sx - wp), (0, 0)))
    return (xpad.reshape(n, hq, sy, wq, sx, c)
            .transpose(0, 2, 4, 1, 3, 5))


def load_planes(xph_ref, sy: int, sx: int):
    """Load each ``(hq, wq, cin)`` phase plane from the block ref ONCE
    (the tap loop would otherwise re-issue a whole-plane load per tap)."""
    return [[xph_ref[0, py, px] for px in range(sx)] for py in range(sy)]


def tap_slice(planes, iy: int, ix: int, sy: int, sx: int,
              oh: int, ow: int):
    """Stride-1 tap for window offset ``(iy, ix)`` from loaded phase
    planes -> ``(oh, ow, cin)``."""
    plane = planes[iy % sy][ix % sx]                # (hq, wq, cin)
    cin = plane.shape[-1]
    return jax.lax.slice(plane, (iy // sy, ix // sx, 0),
                         (iy // sy + oh, ix // sx + ow, cin))


def _kernel(xph_ref, w_ref, b_ref, y_ref, *, ky, kx, sy, sx, oh, ow):
    cin = xph_ref.shape[-1]
    cout = w_ref.shape[-1]
    planes = load_planes(xph_ref, sy, sx)
    acc = jnp.zeros((oh * ow, cout), jnp.float32)
    for iy in range(ky):
        for ix in range(kx):
            tap = tap_slice(planes, iy, ix, sy, sx, oh, ow)
            acc += jnp.dot(tap.reshape(oh * ow, cin), w_ref[iy, ix],
                           preferred_element_type=jnp.float32)
    acc += b_ref[:]
    y_ref[0] = acc.reshape(oh, ow, cout).astype(y_ref.dtype)


def conv2d_im2col(x, weights, bias, sliding=(1, 1), padding=(0, 0, 0, 0),
                  *, interpret: bool = False):
    """NHWC x * HWIO weights (+ bias) — pre-activation conv, identical
    geometry semantics to ops.conv.forward_linear."""
    ky, kx = weights.shape[0], weights.shape[1]
    ky, kx, sy, sx, pt, pb, pl_, pr = normalize_geometry(
        kx, ky, sliding, padding)
    n, h, w, cin = x.shape
    oh = out_size(h, ky, sy, pt, pb)
    ow = out_size(w, kx, sx, pl_, pr)
    cout = weights.shape[3]
    xpad = jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    xph = phase_split(xpad, sy, sx)
    _, _, _, hq, wq, _ = xph.shape
    if bias is None:
        bias = jnp.zeros((cout,), x.dtype)
    kern = partial(_kernel, ky=ky, kx=kx, sy=sy, sx=sx, oh=oh, ow=ow)
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, sy, sx, hq, wq, cin),
                         lambda i: (i, 0, 0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, oh, ow, cout), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, cout), x.dtype),
        interpret=interpret,
    )(xph, weights, bias)
