"""Implicit-im2col convolution as a Pallas kernel — the hand-written GEMM
conv of SURVEY.md §8 step 3 ("hand-written kernel parity"), rebuilding the
reference's conv/forward.{cl,cu} shared-memory im2col GEMM.

One grid step per image: the padded input tile sits in VMEM and the
kernel-window loop issues one MXU GEMM per (ky, kx) tap —
``y[p, :] += x[p*s + tap, :] @ w[tap]`` — accumulating in f32.  The patch
tensor the reference materializes in shared memory never exists: the
window taps are strided VMEM slices (implicit im2col).

Policy note (ops/pallas/__init__.py): XLA's native conv is the default
everywhere; this kernel is the selectable parity path
(``root.common.engine.pallas``) and the tier-1 cross-check target.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from znicz_tpu.ops.conv import normalize_geometry, out_size


def _kernel(x_ref, w_ref, b_ref, y_ref, *, ky, kx, sy, sx, oh, ow):
    x = x_ref[0]                                   # (hp, wp, cin)
    cin = x.shape[-1]
    cout = w_ref.shape[-1]
    acc = jnp.zeros((oh * ow, cout), jnp.float32)
    for iy in range(ky):
        for ix in range(kx):
            tap = jax.lax.slice(
                x, (iy, ix, 0),
                (iy + (oh - 1) * sy + 1, ix + (ow - 1) * sx + 1, cin),
                (sy, sx, 1))                       # (oh, ow, cin)
            acc += jnp.dot(tap.reshape(oh * ow, cin), w_ref[iy, ix],
                           preferred_element_type=jnp.float32)
    acc += b_ref[:]
    y_ref[0] = acc.reshape(oh, ow, cout).astype(y_ref.dtype)


def conv2d_im2col(x, weights, bias, sliding=(1, 1), padding=(0, 0, 0, 0),
                  *, interpret: bool = False):
    """NHWC x * HWIO weights (+ bias) — pre-activation conv, identical
    geometry semantics to ops.conv.forward_linear."""
    ky, kx = weights.shape[0], weights.shape[1]
    ky, kx, sy, sx, pt, pb, pl_, pr = normalize_geometry(
        kx, ky, sliding, padding)
    n, h, w, cin = x.shape
    oh = out_size(h, ky, sy, pt, pb)
    ow = out_size(w, kx, sx, pl_, pr)
    cout = weights.shape[3]
    xpad = jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    hp, wp = xpad.shape[1], xpad.shape[2]
    if bias is None:
        bias = jnp.zeros((cout,), x.dtype)
    kern = partial(_kernel, ky=ky, kx=kx, sy=sy, sx=sx, oh=oh, ow=ow)
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hp, wp, cin), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, oh, ow, cout), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, cout), x.dtype),
        interpret=interpret,
    )(xpad, weights, bias)
