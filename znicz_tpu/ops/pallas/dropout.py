"""Dropout with in-kernel PRNG — Pallas rebuild of the reference's
dropout.{cl,cu} xorshift mask kernel (SURVEY.md §3.2).

The mask is generated from the TPU core PRNG (``pltpu.prng_random_bits``)
and applied in the same VMEM pass — no mask round-trip through HBM on the
generate side (the mask is still emitted for the backward, reference
semantics: backward multiplies by the same mask).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _mask_apply(bits, thresh, scale, x):
    keep = bits > thresh                  # P(keep) = 1 - ratio
    mask = jnp.where(keep, scale, 0.0).astype(x.dtype)
    return x * mask, mask


def _kernel_prng(seed_ref, thresh_ref, scale_ref, x_ref, y_ref, mask_ref):
    pltpu.prng_seed(seed_ref[0])
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    y_ref[:], mask_ref[:] = _mask_apply(bits, thresh_ref[0], scale_ref[0],
                                        x_ref[:])


def _kernel_bits(thresh_ref, scale_ref, bits_ref, x_ref, y_ref, mask_ref):
    y_ref[:], mask_ref[:] = _mask_apply(bits_ref[:], thresh_ref[0],
                                        scale_ref[0], x_ref[:])


def dropout_forward(x, seed, ratio: float, *, bits=None,
                    interpret: bool = False):
    """-> (y, mask): inverted-dropout (kept entries scaled by 1/(1-ratio)),
    mask reusable by the backward.  ``seed`` is an int32 scalar; the same
    (seed, shape) pair reproduces the same mask (counter-PRNG semantics,
    matching znicz_tpu.core.prng's determinism contract).

    ``bits``: optional precomputed uint32 randoms of x.shape — the CPU
    test path (the interpreter's emulated TPU PRNG yields zeros); on TPU
    leave None for in-kernel generation."""
    orig_shape = x.shape
    x2 = x.reshape(-1, orig_shape[-1]) if x.ndim != 2 else x
    thresh = jnp.asarray(
        [jnp.uint32(min(max(ratio, 0.0), 1.0 - 1e-9) * (2 ** 32 - 1))])
    scale = jnp.asarray([1.0 / (1.0 - ratio)], jnp.float32)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    out_shape = (jax.ShapeDtypeStruct(x2.shape, x2.dtype),
                 jax.ShapeDtypeStruct(x2.shape, x2.dtype))
    if bits is None:
        y, mask = pl.pallas_call(
            _kernel_prng, in_specs=[smem, smem, smem, vmem],
            out_specs=(vmem, vmem), out_shape=out_shape,
            interpret=interpret,
        )(jnp.asarray([seed], jnp.int32), thresh, scale, x2)
    else:
        y, mask = pl.pallas_call(
            _kernel_bits, in_specs=[smem, smem, vmem, vmem],
            out_specs=(vmem, vmem), out_shape=out_shape,
            interpret=interpret,
        )(thresh, scale, bits.reshape(x2.shape), x2)
    return y.reshape(orig_shape), mask.reshape(orig_shape)
