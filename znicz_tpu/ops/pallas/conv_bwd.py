"""Hand-written conv backward + deconv Pallas kernels — the col2im
overlap-scatter family SURVEY.md §3.2 calls "the trickiest kernels in the
repo" (reference: gradient_descent_conv/*.{cl,cu}, deconv.{cl,cu},
gradient_descent_deconv/*.{cl,cu}).

TPU-first design: the reference's atomic scatter col2im does not map to
the MXU, so the adjoint is re-expressed as a *gather* — the cotangent is
interior-dilated by the stride and framed by ``k-1`` zeros (one
``lax.pad`` outside the kernel, exactly like the forward kernel's
``jnp.pad``), after which every input-gradient pixel is a stride-1 tap
correlation: ``ei[p, :] += dp[p + tap, :] @ w_flip[tap]`` — one MXU GEMM
per kernel-window tap, f32 accumulation, no atomics, no scatter.  The
weight gradient reuses the forward's strided-tap trick with the GEMM
transposed (``gw[tap] += x[tap-slice]ᵀ @ e``), accumulated across the
batch grid via the revisited-output pattern.

The same two kernels serve the deconv pair: deconv *forward* is the conv
input-gradient with data in place of the cotangent; deconv err_input is
the plain forward conv (ops.pallas.conv); deconv grad_w is the grad
kernel with input/error roles swapped (reference: gd_deconv.py).

Policy note (ops/pallas/__init__.py): XLA's fused vjp conv pair is the
default everywhere; these are the selectable parity path
(``root.common.engine.pallas``) and the tier-1 cross-check target.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from znicz_tpu.ops.conv import normalize_geometry, out_size
from znicz_tpu.ops.pallas.conv import (load_planes, phase_split,
                                       tap_slice)


def _adjoint_kernel(dp_ref, wf_ref, out_ref, *, ky, kx, hp, wp):
    """Stride-1 tap correlation over the dilated+framed cotangent:
    ``out[p, :] = sum_tap dp[p + tap, :] @ wf[tap]``."""
    dp = dp_ref[0]                                 # (hp+ky-1, wp+kx-1, B)
    nb = dp.shape[-1]
    na = wf_ref.shape[-1]
    acc = jnp.zeros((hp * wp, na), jnp.float32)
    for jy in range(ky):
        for jx in range(kx):
            tap = lax.slice(dp, (jy, jx, 0), (jy + hp, jx + wp, nb))
            acc += jnp.dot(tap.reshape(hp * wp, nb), wf_ref[jy, jx],
                           preferred_element_type=jnp.float32)
    out_ref[0] = acc.reshape(hp, wp, na).astype(out_ref.dtype)


def _grad_kernel(xph_ref, e_ref, gw_ref, gb_ref, *,
                 ky, kx, sy, sx, oh, ow):
    """Per-tap transposed GEMM ``gw[tap] += xtapᵀ @ e``, f32-accumulated
    across the batch grid (outputs are revisited every step).  Taps come
    from the phase-split input (see ops.pallas.conv) — Mosaic cannot
    lower strided in-kernel slices."""
    i = pl.program_id(0)
    cin = xph_ref.shape[-1]
    cout = e_ref.shape[-1]
    e = e_ref[0].reshape(oh * ow, cout)

    @pl.when(i == 0)
    def _init():
        gw_ref[...] = jnp.zeros_like(gw_ref)
        gb_ref[...] = jnp.zeros_like(gb_ref)

    planes = load_planes(xph_ref, sy, sx)
    for iy in range(ky):
        for ix in range(kx):
            tap = tap_slice(planes, iy, ix, sy, sx, oh, ow)
            gw_ref[iy, ix] += lax.dot_general(
                tap.reshape(oh * ow, cin), e, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    gb_ref[0, :] += e.astype(jnp.float32).sum(axis=0)


def _dilate_and_frame(e, ky, kx, sy, sx, hp, wp):
    """lax.pad with interior = stride-1 dilation + ``k-1`` frame (+ slack
    rows the window never covered; negative when out_shape crops)."""
    n, oh, ow, c = e.shape
    ry = hp - ((oh - 1) * sy + ky)
    rx = wp - ((ow - 1) * sx + kx)
    return lax.pad(e, jnp.zeros((), e.dtype),
                   ((0, 0, 0), (ky - 1, ky - 1 + ry, sy - 1),
                    (kx - 1, kx - 1 + rx, sx - 1), (0, 0, 0)))


def _adjoint_call(dp, wf, hp, wp, ky, kx, out_dtype, interpret):
    n = dp.shape[0]
    na = wf.shape[-1]
    kern = partial(_adjoint_kernel, ky=ky, kx=kx, hp=hp, wp=wp)
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1,) + dp.shape[1:], lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, hp, wp, na), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, hp, wp, na), out_dtype),
        interpret=interpret,
    )(dp, wf)


def _grad_call(xpad, e, ky, kx, sy, sx, oh, ow, interpret):
    xph = phase_split(xpad, sy, sx)
    n, _, _, hq, wq, cin = xph.shape
    cout = e.shape[-1]
    kern = partial(_grad_kernel, ky=ky, kx=kx, sy=sy, sx=sx, oh=oh, ow=ow)
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, sy, sx, hq, wq, cin),
                         lambda i: (i, 0, 0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, oh, ow, cout), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((ky, kx, cin, cout), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((ky, kx, cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(xph, e)


def conv2d_backward(x, weights, err_v, sliding=(1, 1),
                    padding=(0, 0, 0, 0), *, interpret: bool = False):
    """Linear-conv backward: ``(err_input, grad_w, grad_b)`` for NHWC x,
    HWIO weights and the activation-corrected cotangent ``err_v`` —
    identical semantics to the linear part of ops.conv.backward."""
    ky, kx = weights.shape[0], weights.shape[1]
    ky, kx, sy, sx, pt, pb, pl_, pr = normalize_geometry(
        kx, ky, sliding, padding)
    n, h, w, cin = x.shape
    oh = out_size(h, ky, sy, pt, pb)
    ow = out_size(w, kx, sx, pl_, pr)
    hp, wp = h + pt + pb, w + pl_ + pr
    dp = _dilate_and_frame(err_v, ky, kx, sy, sx, hp, wp)
    wf = weights[::-1, ::-1].transpose(0, 1, 3, 2)  # (ky, kx, cout, cin)
    ei_pad = _adjoint_call(dp, wf, hp, wp, ky, kx, x.dtype, interpret)
    err_input = ei_pad[:, pt:pt + h, pl_:pl_ + w, :]
    xpad = jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    gw, gb = _grad_call(xpad, err_v, ky, kx, sy, sx, oh, ow, interpret)
    return (err_input, gw.astype(weights.dtype),
            gb.reshape(-1).astype(err_v.dtype))


def deconv2d(x, weights, sliding, padding, out_shape, *,
             interpret: bool = False):
    """Transposed conv: ``(n, oh, ow, nk)`` x, HWIO ``(ky, kx, c, nk)``
    weights -> ``out_shape`` ``(n, h, w, c)`` — semantics of
    ops.deconv.forward (the conv input-gradient with data as cotangent)."""
    ky, kx, c, nk = weights.shape
    ky, kx, sy, sx, pt, pb, pl_, pr = normalize_geometry(
        kx, ky, sliding, padding)
    h, w_out = out_shape[1], out_shape[2]
    hp, wp = h + pt + pb, w_out + pl_ + pr
    dp = _dilate_and_frame(x, ky, kx, sy, sx, hp, wp)
    wf = weights[::-1, ::-1].transpose(0, 1, 3, 2)  # (ky, kx, nk, c)
    out_pad = _adjoint_call(dp, wf, hp, wp, ky, kx, x.dtype, interpret)
    return out_pad[:, pt:pt + h, pl_:pl_ + w_out, :]


def deconv2d_backward(x, weights, err_output, sliding=(1, 1),
                      padding=(0, 0, 0, 0), *, interpret: bool = False):
    """``(err_input, grad_w)`` for the deconv pair: err_input is the
    plain forward conv of err_output (adjoint of the adjoint — reuses the
    forward im2col kernel); grad_w is the grad kernel with input/error
    roles swapped (ops.deconv.backward semantics)."""
    from znicz_tpu.ops.pallas.conv import conv2d_im2col

    ky, kx, c, nk = weights.shape
    ky, kx, sy, sx, pt, pb, pl_, pr = normalize_geometry(
        kx, ky, sliding, padding)
    err_input = conv2d_im2col(err_output, weights, None, (sy, sx),
                              (pt, pb, pl_, pr), interpret=interpret)
    n, oh, ow, _ = x.shape
    epad = jnp.pad(err_output, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    gw, _ = _grad_call(epad, x, ky, kx, sy, sx, oh, ow, interpret)
    return err_input, gw.astype(weights.dtype)
