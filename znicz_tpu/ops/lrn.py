"""Local response normalization (cross-map, AlexNet-style) — rebuild of the
reference's normalization.{cl,cu} kernels (SURVEY.md §3.2: "cross-map
sliding sums fwd; exact-derivative bwd").

    d_i = k + alpha * sum_{j in window(i)} x_j^2
    y_i = x_i * d_i^(-beta)

The channel window is ``n`` channels centred on i (clipped at the ends).
Backward is the exact derivative, not an approximation:

    dL/dx_j = e_j d_j^(-beta)
              - 2 alpha beta x_j * sum_{i: j in window(i)} e_i x_i d_i^(-beta-1)

The inverse-neighbourhood sum ``sum_{i: j in window(i)}`` is the adjoint of
the forward window: for odd ``n`` (symmetric window) it equals the forward
sliding sum applied to ``t = e * x * d^(-beta-1)``; for even ``n`` the
centring is asymmetric (window of output i covers [i-n//2, i+n-1-n//2]),
so the adjoint uses the mirrored padding.
"""

from __future__ import annotations


def window_sum(xp, x, n: int, adjoint: bool = False):
    """Sliding sum over the channel (last) axis, window ``n`` centred,
    zero-padded — static python loop, fuses under XLA.  ``adjoint=True``
    mirrors the padding, giving the transpose of the forward operator
    (identical for odd n)."""
    half = n // 2
    lo, hi = (n - 1 - half, half) if adjoint else (half, n - 1 - half)
    pad = [(0, 0)] * (x.ndim - 1) + [(lo, hi)]
    xpad = xp.pad(x, pad)
    c = x.shape[-1]
    acc = xpad[..., 0:c]
    for i in range(1, n):
        acc = acc + xpad[..., i:i + c]
    return acc


def _pow_neg_beta(xp, d, beta: float):
    """``d ** -beta`` with a cheap exact path for the AlexNet exponent:
    generic pow lowers to exp/log per element; for beta = 3/4,
    ``d^-3/4 = sqrt(sqrt(d)) / d`` is two sqrts and a divide."""
    if beta == 0.75:
        return xp.sqrt(xp.sqrt(d)) / d
    return d ** (-beta)


def forward(xp, x, alpha: float, beta: float, k: float, n: int):
    d = k + alpha * window_sum(xp, x * x, n)
    return x * _pow_neg_beta(xp, d, beta)


def backward(xp, x, err_output, alpha: float, beta: float, k: float, n: int):
    d = k + alpha * window_sum(xp, x * x, n)
    dnb = _pow_neg_beta(xp, d, beta)
    t = err_output * x * (dnb / d)           # d^(-beta-1)
    return err_output * dnb - 2.0 * alpha * beta * x * window_sum(
        xp, t, n, adjoint=True)
