"""Transposed convolution (deconv) forward/backward — rebuild of the
reference's deconv.{cl,cu} / gradient_descent_deconv kernels (SURVEY.md
§3.2 "transposed-conv gather/scatter pair").

A Deconv is the exact adjoint of a Conv with the same geometry: its input
has the conv's *output* shape ``(n, oh, ow, n_kernels)``, its output the
conv's *input* shape ``(n, h, w, c)``, sharing the HWIO weights.

- numpy path: the patch-GEMM + overlap-add ``col2im`` oracle (what the
  reference's scatter kernel does with atomics);
- jnp path: one ``lax.conv_general_dilated`` with ``lhs_dilation`` = the
  conv's stride and the spatially-flipped, io-swapped kernel — the exact
  adjoint, expressed as a native XLA conv (MXU path) that traces cleanly
  under jit/shard_map/autograd (a ``jax.vjp``-based formulation would not:
  the cotangent's varying-axis type must match the primal's under
  shard_map).

``min_output_size`` gives the canonical inverse spatial size
``(o-1)*stride + k - pad0 - pad1`` (the conv input size that produces ``o``
outputs with nothing left over).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from znicz_tpu.ops.conv import (_DIMNUMS, col2im, forward_linear, im2col,
                                normalize_geometry)


def min_output_size(o: int, k: int, stride: int, pad0: int, pad1: int) -> int:
    return (o - 1) * stride + k - pad0 - pad1


def output_shape_for(in_shape, weights_shape, sliding, padding):
    """Deconv output shape (the paired conv's input shape)."""
    n, oh, ow, nk = in_shape
    ky, kx, c, nk_w = weights_shape
    if nk != nk_w:
        raise ValueError(f"input channels {nk} != weight kernels {nk_w}")
    ky, kx, sy, sx, pt, pb, pl, pr = normalize_geometry(
        kx, ky, sliding, padding)
    return (n, min_output_size(oh, ky, sy, pt, pb),
            min_output_size(ow, kx, sx, pl, pr), c)


def forward(xp, x, weights, sliding, padding, out_shape):
    """x ``(n, oh, ow, nk)``, HWIO weights -> ``out_shape`` (n, h, w, c)."""
    ky, kx, c, nk = weights.shape
    ky, kx, sy, sx, pt, pb, pl, pr = normalize_geometry(
        kx, ky, sliding, padding)
    if xp is np:
        n, oh, ow, _ = x.shape
        e = x.reshape(n * oh * ow, nk)
        cols = (e @ weights.reshape(-1, nk).T).reshape(
            n, oh, ow, ky, kx, c)
        return col2im(np, cols, out_shape, ky, kx, sy, sx, pt, pb, pl, pr)
    n, oh, ow, _ = x.shape
    h, w_out = out_shape[1], out_shape[2]
    # padding that makes the dilated conv produce exactly (h, w_out):
    # b may go negative (XLA negative padding) when out_shape crops rows
    a_h, b_h = ky - 1 - pt, h + pt - (oh - 1) * sy - 1
    a_w, b_w = kx - 1 - pl, w_out + pl - (ow - 1) * sx - 1
    w_t = weights[::-1, ::-1].transpose(0, 1, 3, 2)  # flip + io-swap: HWOI'
    return lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1), padding=((a_h, b_h), (a_w, b_w)),
        lhs_dilation=(sy, sx), dimension_numbers=_DIMNUMS)


def backward(xp, x, weights, err_output, sliding, padding):
    """Returns ``(err_input, grad_weights)``: err_input is the forward conv
    of err_output (adjoint of the adjoint); grad_weights the patch GEMM
    with input/error roles swapped relative to conv backward."""
    ky, kx, c, nk = weights.shape
    ky, kx, sy, sx, pt, pb, pl, pr = normalize_geometry(
        kx, ky, sliding, padding)
    if xp is np:
        err_input = forward_linear(np, err_output, weights, None,
                                   (sy, sx), (pt, pb, pl, pr))
        cols, oh, ow = im2col(np, err_output, ky, kx, sy, sx, pt, pb, pl, pr)
        n = x.shape[0]
        grad_w = (cols.reshape(n * oh * ow, -1).T @
                  x.reshape(n * oh * ow, nk)).reshape(weights.shape)
        return err_input, grad_w
    fwd = lambda xx, ww: forward(jnp, xx, ww, (sy, sx),   # noqa: E731
                                 (pt, pb, pl, pr), err_output.shape)
    _, vjp = jax.vjp(fwd, x, weights)
    return vjp(err_output)
