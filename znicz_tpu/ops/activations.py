"""Activation function library — rebuild of the reference's activation macro
set in defines.{cl,cu} + activation.{cl,cu} (SURVEY.md §3.2).

The reference's activation set, kept verbatim:

- ``linear``:      y = x
- ``tanh``:        y = 1.7159 * tanh(2/3 x)        (LeCun-scaled tanh)
- ``relu``:        y = log(1 + e^x)                ("soft" ReLU / softplus —
                    this IS the reference's RELU; see ocl defines)
- ``strict_relu``: y = max(0, x)
- ``sigmoid``:     y = 1 / (1 + e^-x)

Backward derivatives are expressed **in terms of the forward output y**
(not x) — the reference kernels do the same because only the output buffer
is resident when the gradient unit runs.
"""

from __future__ import annotations

#: activation names (reference: activation macro library)
LINEAR = "linear"
TANH = "tanh"
RELU = "relu"
STRICT_RELU = "strict_relu"
SIGMOID = "sigmoid"
#: standalone-unit extras (reference: activation.{cl,cu} — SURVEY.md §3.1;
#: exact formulas reconstructed, marked [MED] there)
LOG = "log"            # y = log(x + sqrt(x^2+1))  (asinh — defined everywhere)
SINCOS = "sincos"      # even flat indices cos(x), odd sin(x)
TANHLOG = "tanhlog"    # LeCun tanh below |x|<=d, log-growth tail above

#: LeCun tanh constants (reference: defines.cl :: 1.7159 * tanh(2/3 x))
TANH_A = 1.7159
TANH_B = 2.0 / 3.0
#: tanh->log switchover point for TANHLOG
TANHLOG_D = 1.0


def forward(xp, name: str, v):
    """Apply activation ``name`` elementwise to pre-activation ``v``."""
    if name == LINEAR:
        return v
    if name == TANH:
        return TANH_A * xp.tanh(TANH_B * v)
    if name == RELU:
        # log1p(exp(v)) overflows for large v; use the stable max + log1p form
        return xp.maximum(v, 0) + xp.log1p(xp.exp(-xp.abs(v)))
    if name == STRICT_RELU:
        return xp.maximum(v, 0)
    if name == SIGMOID:
        return 1.0 / (1.0 + xp.exp(-v))
    if name == LOG:
        return xp.log(v + xp.sqrt(v * v + 1.0))
    if name == SINCOS:
        flat = v.reshape(v.shape[0], -1)
        idx = xp.arange(flat.shape[1]) % 2
        out = xp.where(idx[None, :] == 0, xp.cos(flat), xp.sin(flat))
        return out.reshape(v.shape)
    if name == TANHLOG:
        d = TANHLOG_D
        knee = TANH_A * xp.tanh(TANH_B * d)
        tail = xp.sign(v) * (knee + xp.log(xp.maximum(xp.abs(v), d) / d))
        return xp.where(xp.abs(v) <= d, TANH_A * xp.tanh(TANH_B * v), tail)
    raise ValueError(f"unknown activation {name!r}")


def derivative_from_input(xp, name: str, x, y):
    """d(act)/dx for activations whose derivative needs the *input* —
    the standalone activation units link both sides (reference:
    ActivationBackward has input + output attrs)."""
    if name == LOG:
        return 1.0 / xp.sqrt(x * x + 1.0)
    if name == SINCOS:
        flat = x.reshape(x.shape[0], -1)
        idx = xp.arange(flat.shape[1]) % 2
        out = xp.where(idx[None, :] == 0, -xp.sin(flat), xp.cos(flat))
        return out.reshape(x.shape)
    if name == TANHLOG:
        d = TANHLOG_D
        t = TANH_A * xp.tanh(TANH_B * x)
        dtanh = TANH_B * (TANH_A - t * t / TANH_A)
        return xp.where(xp.abs(x) <= d, dtanh,
                        1.0 / xp.maximum(xp.abs(x), d))
    return derivative_from_output(xp, name, y)


def derivative_from_output(xp, name: str, y):
    """d(act)/d(pre-activation) expressed via the forward output ``y``."""
    if name == LINEAR:
        return xp.ones_like(y)
    if name == TANH:
        # y = A tanh(Bv)  =>  dy/dv = B (A - y^2 / A)
        return TANH_B * (TANH_A - y * y / TANH_A)
    if name == RELU:
        # y = log(1+e^v)  =>  dy/dv = sigmoid(v) = 1 - e^-y
        return 1.0 - xp.exp(-y)
    if name == STRICT_RELU:
        return (y > 0).astype(y.dtype)
    if name == SIGMOID:
        return y * (1.0 - y)
    raise ValueError(f"unknown activation {name!r}")


def backward(xp, name: str, y, err_output):
    """Propagate err through the activation: err_v = err_y * act'(y)."""
    if name == LINEAR:
        return err_output
    return err_output * derivative_from_output(xp, name, y)
