"""Window pooling forward/backward — rebuild of the reference's
pooling.{cl,cu} + gradient_descent_pooling kernels (SURVEY.md §3.2).

Semantics kept from the reference:
- geometry ``kx/ky`` window, ``sliding`` stride; **partial border windows
  are included** (output size = ceil((in - k)/stride) + 1, window clipped
  at the edge), so pooling covers the whole input whenever stride <= k.
  A window that would START beyond the input (possible only when stride >
  kernel, where strided pooling skips cells by construction) is dropped —
  torch ceil_mode semantics; see :func:`pool_out_size`;
- max variants record the winner's flat ``(row*W + col)`` offset per
  ``(n, oy, ox, c)`` into ``input_offset`` for the backward scatter;
- avg divides by the *actual* (clipped) window element count;
- stochastic variants sample the winner with probability proportional to
  the (abs) activation — Zeiler&Fergus stochastic pooling, which the
  reference implements with its device xorshift PRNG; in ``forward_mode``
  (inference) they fall back to the probability-weighted expectation.

One implementation serves both backends: the patch tensor is built by a
static python loop over the window (numpy slices / XLA-fused slices).
The recorded offsets are only used by the eager per-unit backward —
exactly the role the reference's ``input_offset`` plays.  The fused
training path's backwards are custom VJPs (first-winner masks +
interior-dilated pads over the strided taps) so no pooling gradient
lowers to select-and-scatter or scatter-add on TPU; each is pinned
against the XLA-native route it replaced (docs/TUNING.md).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def pool_out_size(size: int, k: int, stride: int) -> int:
    """ceil((size - k)/stride) + 1, but never losing the first window and
    never emitting a window that STARTS beyond the input (stride > kernel
    can otherwise produce a fully out-of-bounds window — zero valid
    elements, offsets past the input; torch's ceil_mode drops it the same
    way: "the last pooling must start inside the image")."""
    if size <= k:
        return 1
    out = -(-(size - k) // stride) + 1
    if (out - 1) * stride >= size:
        out -= 1
    return out


def window_counts(h, w, ky, kx, sy, sx):
    """Static window geometry: ``(valid, count)`` where valid (oh, ow, ky*kx)
    masks in-bounds window elements and count (oh, ow, 1) is their number.
    Pure numpy — computed once from shapes, no data touched."""
    oh = pool_out_size(h, ky, sy)
    ow = pool_out_size(w, kx, sx)
    oy = np.arange(oh)[:, None, None] * sy
    ox = np.arange(ow)[None, :, None] * sx
    iy = np.arange(ky * kx)[None, None, :] // kx
    ix = np.arange(ky * kx)[None, None, :] % kx
    valid = ((oy + iy < h) & (ox + ix < w))          # (oh, ow, ky*kx)
    return valid, valid.sum(axis=2, keepdims=True)


def patches(xp, x, ky, kx, sy, sx, pad_value=0.0):
    """``(patch, valid, count)`` where patch is (n, oh, ow, ky*kx, c) with
    out-of-bounds elements set to ``pad_value``."""
    n, h, w, c = x.shape
    oh = pool_out_size(h, ky, sy)
    ow = pool_out_size(w, kx, sx)
    pb, pr = _border_pad(h, w, ky, kx, sy, sx)
    xpad = xp.pad(x, ((0, 0), (0, pb), (0, pr), (0, 0)),
                  constant_values=pad_value)
    parts = []
    for iy in range(ky):
        for ix in range(kx):
            parts.append(xpad[:, iy:iy + oh * sy:sy, ix:ix + ow * sx:sx, :])
    patch = xp.stack(parts, axis=3)
    valid, count = window_counts(h, w, ky, kx, sy, sx)
    return patch, xp.asarray(valid), count


def offsets_of(xp, winner_idx, in_shape, ky, kx, sy, sx):
    """Flat (row*W + col) input offset of window element ``winner_idx``
    (n, oh, ow, c) — the reference's ``input_offset`` payload."""
    _, h, w, _ = in_shape
    oh, ow = winner_idx.shape[1], winner_idx.shape[2]
    oy = xp.asarray(np.arange(oh)[None, :, None, None] * sy)
    ox = xp.asarray(np.arange(ow)[None, None, :, None] * sx)
    row = oy + winner_idx // kx
    col = ox + winner_idx % kx
    return (row * w + col).astype(xp.int32)


def _border_pad(h, w, ky, kx, sy, sx):
    """Bottom/right padding that turns znicz's clipped border windows into
    full windows over a padded input."""
    oh = pool_out_size(h, ky, sy)
    ow = pool_out_size(w, kx, sx)
    return max((oh - 1) * sy + ky - h, 0), max((ow - 1) * sx + kx - w, 0)


def max_forward_fast(x, ky, kx, sy, sx):
    """Fused-path max pooling: one ``lax.reduce_window`` whose VJP is XLA's
    native select-and-scatter — the gradient routes to the in-window
    maximum exactly like the eager offset-scatter backward (first-match
    tie-break in both).  The patch-tensor :func:`max_forward` materializes
    a (n, oh, ow, ky*kx, c) gather whose argmax/take_along_axis pair
    dominated the whole AlexNet step on TPU (~50x this op).

    Both dispatch targets avoid select-and-scatter (TPU-hostile, and
    ``reduce_window`` in the graph skews XLA's layout choices): the
    non-overlapping evenly-dividing case (CIFAR k2s2) takes the
    reshape-max :func:`_maxpool_nonoverlap`; everything else
    (overlapping AlexNet k3s2, partial border windows, stride > kernel)
    takes the strided-taps :func:`_maxpool_taps`.  Values and the
    winner each gradient routes to equal the reduce_window route
    exactly, ties included; where one input wins SEVERAL overlapping
    windows the contributions sum in a different (fixed, deterministic)
    order — 1-ULP-scale differences the parity test bounds."""
    if (sy, sx) == (ky, kx) and x.shape[1] % ky == 0 and \
            x.shape[2] % kx == 0:
        return _maxpool_nonoverlap(x, ky, kx)
    return _maxpool_taps(x, ky, kx, sy, sx)


def _tap_geometry(h, w, ky, kx, sy, sx):
    """Padded extent covering every (possibly partial) window: taps for
    window offset (dy, dx) are the stride-(sy, sx) slices starting
    there; out-of-input positions pad with -inf (never win the max,
    and their gradient contributions are sliced away).  Clamped to at
    least the input extent: stride > kernel can leave the last window
    ending BEFORE the input does, and an unclamped extent would trim
    the input (negative pad) and truncate the cotangent."""
    oh, ow = pool_out_size(h, ky, sy), pool_out_size(w, kx, sx)
    return oh, ow, max(h, (oh - 1) * sy + ky), max(w, (ow - 1) * sx + kx)


def _taps(xp_pad, oh, ow, ky, kx, sy, sx):
    """The k*k strided views, row-major window order."""
    return [xp_pad[:, dy:dy + (oh - 1) * sy + 1:sy,
                   dx:dx + (ow - 1) * sx + 1:sx, :]
            for dy in range(ky) for dx in range(kx)]


def _mpgen_pad(x, ph, pw):
    n, h, w, c = x.shape
    return lax.pad(x, jnp.asarray(-jnp.inf, x.dtype),
                   ((0, 0, 0), (0, ph - h, 0), (0, pw - w, 0),
                    (0, 0, 0)))


def _mpgen_fwd(x, ky, kx, sy, sx):
    n, h, w, c = x.shape
    oh, ow, ph, pw = _tap_geometry(h, w, ky, kx, sy, sx)
    xp_pad = _mpgen_pad(x, ph, pw)
    taps = _taps(xp_pad, oh, ow, ky, kx, sy, sx)
    y = taps[0]
    for t in taps[1:]:
        y = jnp.maximum(y, t)
    return y, (x, y)


def _tap_transpose_pad(contrib, zero, dy, dx, geom):
    """Transpose of the (dy, dx) strided tap slice: interior-dilated pad
    back onto the padded input grid.  THE one copy of the pad config —
    shared by every taps-path backward."""
    oh, ow, ph, pw, sy, sx = geom
    return lax.pad(
        contrib, zero,
        ((0, 0, 0), (dy, ph - dy - ((oh - 1) * sy + 1), sy - 1),
         (dx, pw - dx - ((ow - 1) * sx + 1), sx - 1), (0, 0, 0)))


def _mpgen_bwd(ky, kx, sy, sx, res, g):
    x, y = res
    n, h, w, c = x.shape
    oh, ow, ph, pw = _tap_geometry(h, w, ky, kx, sy, sx)
    xp_pad = _mpgen_pad(x, ph, pw)
    taps = _taps(xp_pad, oh, ow, ky, kx, sy, sx)
    zero = jnp.zeros((), g.dtype)
    seen = jnp.zeros(y.shape, jnp.bool_)
    dx_acc = jnp.zeros((n, ph, pw, c), g.dtype)
    for (dy, dx), tap in zip(((dy, dx) for dy in range(ky)
                             for dx in range(kx)), taps):
        hit = tap == y
        first = hit & ~seen
        seen = seen | hit
        contrib = jnp.where(first, g, zero)
        dx_acc = dx_acc + _tap_transpose_pad(contrib, zero, dy, dx,
                                             (oh, ow, ph, pw, sy, sx))
    return (dx_acc[:, :h, :w, :],)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _maxpool_taps(x, ky, kx, sy, sx):
    """Max pooling as an elementwise max over the k*k strided taps —
    no ``reduce_window``, so the backward is first-winner masks + pads
    instead of TPU-hostile select-and-scatter, for ANY geometry
    (overlapping windows included).  Tie-break matches select-and-
    scatter and the eager offset recorder (row-major window order);
    per-window routing is exact, cross-window sums may differ from the
    reduce_window route at 1-ULP scale (see max_forward_fast)."""
    return _mpgen_fwd(x, ky, kx, sy, sx)[0]


_maxpool_taps.defvjp(_mpgen_fwd, _mpgen_bwd)


def _mpno_fwd(x, ky, kx):
    n, h, w, c = x.shape
    xr = x.reshape(n, h // ky, ky, w // kx, kx, c)
    y = xr.max(axis=(2, 4))
    return y, (x, y)


def _mpno_bwd(ky, kx, res, g):
    x, y = res
    n, h, w, c = x.shape
    xr = x.reshape(n, h // ky, ky, w // kx, kx, c)
    mask = xr == y[:, :, None, :, None, :]
    # first-winner in row-major (dy, dx) window order — the tie-break
    # select-and-scatter and the eager offset recorder share.  rank =
    # lexicographic running count of winners; the first has rank 1.
    s_dx = jnp.cumsum(mask.astype(jnp.int32), axis=4)
    row_tot = s_dx[:, :, :, :, -1:, :]
    rank = jnp.cumsum(row_tot, axis=2) - row_tot + s_dx
    first = mask & (rank == 1)
    gb = jnp.broadcast_to(g[:, :, None, :, None, :], xr.shape)
    dx = jnp.where(first, gb, jnp.zeros((), g.dtype))
    return (dx.reshape(n, h, w, c),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _maxpool_nonoverlap(x, ky, kx):
    return _mpno_fwd(x, ky, kx)[0]


_maxpool_nonoverlap.defvjp(_mpno_fwd, _mpno_bwd)


def _mabs_fwd(x, ky, kx, sy, sx):
    n, h, w, c = x.shape
    oh, ow, ph, pw = _tap_geometry(h, w, ky, kx, sy, sx)
    # pad each fold's operand separately: negating a shared -inf-padded
    # input would turn border padding into +inf winners in the neg fold
    pos = neg = None
    for tp, tn in zip(_taps(_mpgen_pad(x, ph, pw), oh, ow, ky, kx, sy,
                            sx),
                      _taps(_mpgen_pad(-x, ph, pw), oh, ow, ky, kx, sy,
                            sx)):
        pos = tp if pos is None else jnp.maximum(pos, tp)
        neg = tn if neg is None else jnp.maximum(neg, tn)
    y = jnp.where(pos >= neg, pos, -neg)
    return y, (x, y)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def maxabs_forward_fast(x, ky, kx, sy, sx):
    """Signed winner of the max-|x| window via two strided-taps max
    folds (same no-reduce_window rationale as :func:`_maxpool_taps`):
    ``pos = max(x)``, ``neg = max(-x)``, ``y = pos if pos >= neg else
    -neg``.  In BOTH branches ``y`` equals the winning tap's value, so
    the backward is :func:`_mpgen_bwd` unchanged — first row-major tap
    with ``t == y`` gets the gradient, which reproduces the old
    twin-reduce_window route's select-and-scatter winner exactly (a
    custom VJP because ``jnp.maximum`` SPLITS gradient on in-fold ties
    instead of first-match)."""
    return _mabs_fwd(x, ky, kx, sy, sx)[0]


maxabs_forward_fast.defvjp(_mabs_fwd, _mpgen_bwd)


def avg_forward_fast(x, ky, kx, sy, sx):
    """Fused-path avg pooling: windowed sum via ``reduce_window`` divided
    by the static clipped-window element count (border semantics kept)."""
    pb, pr = _border_pad(x.shape[1], x.shape[2], ky, kx, sy, sx)
    # init must be a CONCRETE scalar: a traced jnp.zeros(()) init makes
    # reduce_window's linearization fail under shard_map ("Linearization
    # failed to produce known values for all output primals") — found by
    # the composition fuzzer, tests/test_workflow_fuzz.py
    s = lax.reduce_window(
        x, np.zeros((), x.dtype)[()], lax.add, (1, ky, kx, 1),
        (1, sy, sx, 1), ((0, 0), (0, pb), (0, pr), (0, 0)))
    _, count = window_counts(x.shape[1], x.shape[2], ky, kx, sy, sx)
    return s / jnp.asarray(count[None], x.dtype)


def max_forward(xp, x, ky, kx, sy, sx, use_abs: bool = False):
    """Returns ``(y, offsets)``."""
    patch, valid, _ = patches(xp, x, ky, kx, sy, sx, pad_value=NEG_INF)
    key = xp.abs(patch) if use_abs else patch
    key = xp.where(valid[None, :, :, :, None], key, NEG_INF)
    idx = key.argmax(axis=3)                                  # (n,oh,ow,c)
    y = xp.take_along_axis(patch, idx[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    return y, offsets_of(xp, idx, x.shape, ky, kx, sy, sx)


def avg_forward(xp, x, ky, kx, sy, sx):
    patch, _, count = patches(xp, x, ky, kx, sy, sx, pad_value=0.0)
    return patch.sum(axis=3) / xp.asarray(count[None].astype(np.float32))


def _stochastic_probs(xp, x, ky, kx, sy, sx, use_abs: bool):
    """``(patch, p, total)`` — the (abs-)activation window probabilities
    shared by train sampling and eval expectation."""
    patch, valid, _ = patches(xp, x, ky, kx, sy, sx, pad_value=0.0)
    vmask = valid[None, :, :, :, None]
    p = xp.abs(patch) if use_abs else xp.maximum(patch, 0.0)
    p = xp.where(vmask, p, 0.0)
    return patch, p, p.sum(axis=3, keepdims=True)


def _stochastic_choice(xp, x, ky, kx, sy, sx, uniform, use_abs: bool):
    """Inverse-CDF winner per window -> ``(patch, idx)``.  STRICT
    compare: a zero-total window (all probabilities 0, u = 0) selects
    element 0, which is always in-bounds — the window origin is a real
    input cell."""
    patch, p, total = _stochastic_probs(xp, x, ky, kx, sy, sx, use_abs)
    cdf = xp.cumsum(p, axis=3)
    u = uniform[:, :, :, None, :] * total
    idx = (cdf < u).sum(axis=3)
    return patch, xp.minimum(idx, ky * kx - 1)


def stochastic_forward(xp, x, ky, kx, sy, sx, uniform, use_abs: bool,
                       train: bool):
    """Zeiler&Fergus stochastic pooling.  ``uniform`` is (n, oh, ow, c) in
    [0, 1) from the framework PRNG (host xorshift for numpy, counter-based
    jax PRNG on device).  Returns ``(y, offsets)`` when training, else
    ``(expectation, None)``."""
    if not train:
        patch, p, total = _stochastic_probs(xp, x, ky, kx, sy, sx,
                                            use_abs)
        w = xp.where(total > 0, p / xp.where(total > 0, total, 1.0), 0.0)
        return (patch * w).sum(axis=3), None
    patch, idx = _stochastic_choice(xp, x, ky, kx, sy, sx, uniform,
                                    use_abs)
    y = xp.take_along_axis(patch, idx[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    return y, offsets_of(xp, idx, x.shape, ky, kx, sy, sx)


def _stoch_fwd(x, uniform, ky, kx, sy, sx, use_abs):
    patch, idx = _stochastic_choice(jnp, x, ky, kx, sy, sx, uniform,
                                    use_abs)
    y = jnp.take_along_axis(
        patch, idx[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    return y, (x, idx)


def _stoch_bwd(ky, kx, sy, sx, use_abs, res, g):  # nondiff args lead
    x, idx = res            # x rides for shape/dtype only
    n, h, w, c = x.shape
    oh, ow, ph, pw = _tap_geometry(h, w, ky, kx, sy, sx)
    zero = jnp.zeros((), g.dtype)
    dx_acc = jnp.zeros((n, ph, pw, c), g.dtype)
    for t, (dy, dx) in enumerate((dy, dx) for dy in range(ky)
                                 for dx in range(kx)):
        contrib = jnp.where(idx == t, g, zero)
        dx_acc = dx_acc + _tap_transpose_pad(contrib, zero, dy, dx,
                                             (oh, ow, ph, pw, sy, sx))
    # uniform's cotangent is structurally zero (idx is integer-valued);
    # None is custom_vjp's symbolic zero and stays correct when uniform's
    # dtype (f32) differs from a mixed-precision cotangent g (bf16)
    return (dx_acc[:, :h, :w, :].astype(x.dtype), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def stochastic_forward_fast(x, uniform, ky, kx, sy, sx, use_abs):
    """Train-mode stochastic pooling whose backward routes the gradient
    to the sampled winner with masks + interior-dilated pads instead of
    AD's scatter through ``take_along_axis`` (the same
    no-select-and-scatter rationale as :func:`_maxpool_taps`; the
    sampled index IS the routing, so no value matching is needed).
    Gradient equals the AD route exactly: only the chosen patch position
    receives cotangent (``idx`` is integer — nothing flows through the
    probability computation)."""
    return _stoch_fwd(x, uniform, ky, kx, sy, sx, use_abs)[0]


stochastic_forward_fast.defvjp(_stoch_fwd, _stoch_bwd)


def scatter_backward(xp, err_output, offsets, in_shape):
    """Route err to recorded winner offsets (max/stochastic backward)."""
    n, h, w, c = in_shape
    flat = offsets.reshape(n, -1, c)
    e = err_output.reshape(n, -1, c)
    if xp is np:
        out = np.zeros((n, h * w, c), err_output.dtype)
        ni = np.arange(n)[:, None, None]
        ci = np.arange(c)[None, None, :]
        np.add.at(out, (ni, flat, ci), e)
    else:
        out = jnp.zeros((n, h * w, c), err_output.dtype)
        ni = jnp.arange(n)[:, None, None]
        ci = jnp.arange(c)[None, None, :]
        out = out.at[ni, flat, ci].add(e)
    return out.reshape(in_shape)


def avg_backward(xp, err_output, in_shape, ky, kx, sy, sx):
    """Spread err uniformly over each (clipped) window."""
    n, h, w, c = in_shape
    oh = pool_out_size(h, ky, sy)
    ow = pool_out_size(w, kx, sx)
    _, count = window_counts(h, w, ky, kx, sy, sx)
    e = err_output / xp.asarray(count[None].astype(np.float32))
    pb, pr = _border_pad(h, w, ky, kx, sy, sx)
    if xp is np:
        padded = np.zeros((n, h + pb, w + pr, c), err_output.dtype)
        for iy in range(ky):
            for ix in range(kx):
                padded[:, iy:iy + oh * sy:sy, ix:ix + ow * sx:sx, :] += e
    else:
        padded = jnp.zeros((n, h + pb, w + pr, c), err_output.dtype)
        for iy in range(ky):
            for ix in range(kx):
                padded = padded.at[
                    :, iy:iy + oh * sy:sy, ix:ix + ow * sx:sx, :].add(e)
    return padded[:, :h, :w, :]
