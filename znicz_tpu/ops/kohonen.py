"""Kohonen SOM ops — rebuild of the reference's kohonen.{cl,cu} kernels
(SURVEY.md §3.2: "distance compute + argmin reduction +
neighborhood-weighted update").

TPU-first formulation: the per-sample distance scan becomes one batched
GEMM (``|x-w|^2 = |x|^2 - 2 x·Wᵀ + |w|^2`` — MXU path) + row argmin; the
winner-neighborhood weight update becomes two matmuls
(``ΔW = Hᵀ·X - diag(Hᵀ·1)·W``) instead of the reference's per-neuron
scatter loop.  Works for numpy and traced jnp alike.
"""

from __future__ import annotations

import numpy as np


def grid_coords(xp, sy: int, sx: int):
    """(n_neurons, 2) [row, col] coordinates of the SOM grid."""
    rows = xp.repeat(xp.arange(sy), sx)
    cols = xp.tile(xp.arange(sx), sy)
    return xp.stack([rows, cols], axis=1).astype(xp.float32)


def distances_sq(xp, x, weights):
    """``(batch, n_neurons)`` squared euclidean distances; x ``(b, d)``,
    weights ``(n_neurons, d)``."""
    x2 = (x * x).sum(axis=1, keepdims=True)
    w2 = (weights * weights).sum(axis=1)
    return x2 - 2.0 * (x @ weights.T) + w2


def winners(xp, x, weights):
    """Best-matching-unit index per sample (the argmin reduction)."""
    return distances_sq(xp, x, weights).argmin(axis=1)


def neighborhood(xp, winner_idx, coords, sigma: float):
    """Gaussian grid-distance weighting ``(batch, n_neurons)`` of every
    neuron to each sample's winner."""
    wc = coords[winner_idx]                      # (b, 2)
    d2 = ((wc[:, None, :] - coords[None, :, :]) ** 2).sum(axis=2)
    return xp.exp(-d2 / (2.0 * sigma * sigma))


def update(xp, x, weights, coords, alpha: float, sigma: float,
           mask=None):
    """One batch SOM step: returns ``(new_weights, winner_idx)``.

    Batch-stable form: each neuron is pulled toward its neighborhood-
    weighted batch mean, ``W_j += alpha * (Σ_b H[b,j] x_b - Σ_b H[b,j] W_j)
    / (Σ_b H[b,j] + 1)`` — as the neighborhood mass grows this approaches
    ``alpha * (mean - W_j)`` (bounded for alpha <= 1, unlike the raw
    batch-summed delta), and neurons far from every winner barely move.
    ``mask`` (b,) zeroes padded samples' contribution.
    """
    idx = winners(xp, x, weights)
    h = neighborhood(xp, idx, coords, sigma)
    if mask is not None:
        h = h * mask.astype(h.dtype)[:, None]
    num = h.T @ x                                # (n, d)
    den = h.sum(axis=0)[:, None]                 # (n, 1)
    new_w = weights + alpha * (num - den * weights) / (den + 1.0)
    return new_w, idx


def hits(xp, winner_idx, n_neurons: int):
    """Winner histogram (reference: KohonenHits plotting input)."""
    if xp is np:
        return np.bincount(np.asarray(winner_idx), minlength=n_neurons)
    one_hot = (winner_idx[:, None] ==
               xp.arange(n_neurons)[None, :]).astype(xp.int32)
    return one_hot.sum(axis=0)
